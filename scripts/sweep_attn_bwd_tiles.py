"""Fused-backward tile sweep, round 5 (fwd tiles fixed at the 1024x1024
optimum; the native-dtype-dot change moved the BACKWARD's optimum, so
its tiles are now chosen independently — ops/flash_attention.py
`bwd_tiles`).

Measures fwd+bwd (all three grads live) at the 186M shape and the
16k-long-context shape per bwd-tile combo.

Usage: python scripts/sweep_attn_bwd_tiles.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def chain(fn, x0, n=4, reps=3):
    import jax
    import jax.numpy as jnp
    from jax import lax

    looped = jax.jit(lambda x: lax.scan(
        lambda c, _: (fn(c), None), x, None, length=n)[0])
    out = looped(x0)
    float(jnp.sum(out).astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = looped(out)
    float(jnp.sum(out).astype(jnp.float32))
    return (time.perf_counter() - t0) / (reps * n)


def sweep_shape(tag, bh, s, d, combos):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.flash_attention import (flash_attention,
                                               resolve_bwd_form)

    rng = np.random.RandomState(0)
    q0 = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
    k0 = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
    v0 = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)

    # record what will ACTUALLY run (the profile_bilstm convention):
    # past the fused backward's resident cap, bwd_tiles do not apply —
    # the split backward tiles at the forward blocks, and timing it
    # under a bwd_tiles label would be the ADVICE-r05 wrong-kernel
    # hazard. Skip the combos instead of mislabeling them.
    bwd_form = resolve_bwd_form(s, d, q0.dtype.itemsize, block_q=1024)
    if bwd_form != "fused":
        print(json.dumps({"shape": tag, "SKIPPED":
                          f"resolve_bwd_form -> {bwd_form}: bwd_tiles "
                          f"do not apply (split backward tiles at the "
                          f"fwd blocks); rows would mislabel"}),
              flush=True)
        return

    for bt in combos:
        try:
            g = jax.grad(lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=1024, block_k=1024,
                impl="pallas", bwd_tiles=bt)
                .astype(jnp.float32).sum(), argnums=(0, 1, 2))

            def fwdbwd(q):
                dq, dk, dv = g(q, k0, v0)
                return (dq + 1e-30 * (dk.astype(jnp.float32).sum()
                                      + dv.astype(jnp.float32).sum())
                        .astype(dq.dtype))

            t_b = chain(fwdbwd, q0, n=4)
            row = {"shape": tag, "bwd_tiles": list(bt) if bt else None,
                   "bwd_form": bwd_form,
                   "fwdbwd_ms": round(t_b * 1e3, 3)}
        except Exception as e:
            row = {"shape": tag, "bwd_tiles": list(bt) if bt else None,
                   "FAILED": str(e)[:140]}
        print(json.dumps(row), flush=True)


def main():
    combos = [(512, 512), (512, 1024), (1024, 512), (256, 512),
              (512, 256), (256, 1024)]
    sweep_shape("186m_B8H16_S2048_D64", 128, 2048, 64, combos)
    sweep_shape("longctx_B1H8_S16384_D64", 8, 16384, 64,
                [(512, 512), (512, 1024), (256, 1024), (256, 512)])


if __name__ == "__main__":
    main()
