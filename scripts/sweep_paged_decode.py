"""Paged-decode kernel tile/residency sweep (ISSUE 17).

Sweeps the one-launch paged-attention decode kernel
(ops/paged_decode.py) over its (block_tile, head_tile) grid at the 43M
serving shape. Off-TPU this is an INTERPRET-MODE SMOKE: every tile
combo must route the block table correctly and stay BITWISE equal to
the `ops/kv_cache.paged_attention` oracle (fp32) — timing there is the
Pallas interpreter's, i.e. meaningless, and is printed only on a real
TPU. On-chip the sweep times each combo with a fenced device→host
fetch (block_until_ready LIES through the axon tunnel — CLAUDE.md) and
rotates input batches to defeat server-side memoization; the winning
tile pair is what `BIGDL_PAGED_DECODE_TILES` should pin. On-chip
numbers are standing MEASUREMENT DEBT from the ISSUE 17 session
(PROFILE_r06/ANALYSIS.md protocol — no chip was attached).

The env-knob leg exercises the import-snapshot contract end to end:
mutate `BIGDL_PAGED_DECODE_TILES`, call `envknobs.refresh()`, build a
FRESH jit root (utils/envknobs discipline — never read env at trace
time), and check the launch resolved the env tiles.

Usage: [JAX_PLATFORMS=cpu] python scripts/sweep_paged_decode.py
       [--heads 8] [--head-dim 64] [--blocks 16] [--block-size 16]
       [--batch 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _setup(args):
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    b, h, d = args.batch, args.heads, args.head_dim
    nb, bs = args.blocks, args.block_size
    pool_n = b * nb + 1                       # block 0 = reserved scratch
    k_pool = jnp.asarray(rng.randn(pool_n, h, bs, d), jnp.float32)
    v_pool = jnp.asarray(rng.randn(pool_n, h, bs, d), jnp.float32)
    # each row owns a disjoint, shuffled block chain (never block 0):
    # the routing the index maps must reproduce
    ids = rng.permutation(np.arange(1, pool_n))[:b * nb]
    table = jnp.asarray(ids.reshape(b, nb), jnp.int32)
    # ragged clocks, incl. one row mid-block
    pos = jnp.asarray(
        rng.randint(bs, nb * bs, size=b), jnp.int32)
    qs = [jnp.asarray(rng.randn(b, h, 1, d), jnp.float32)
          for _ in range(4)]                  # rotated inputs (memoization)
    return qs, k_pool, v_pool, table, pos


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--blocks", type=int, default=16,
                    help="table width (logical cache blocks per row)")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args(argv)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from bigdl_tpu.utils.engine import ensure_cpu_platform

        ensure_cpu_platform()

    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.kv_cache import paged_attention
    from bigdl_tpu.ops.paged_decode import paged_decode_attention
    from bigdl_tpu.utils import envknobs

    on_tpu = jax.devices()[0].platform == "tpu"
    impl = "pallas" if on_tpu else "interpret"
    qs, k_pool, v_pool, table, pos = _setup(args)
    ref = paged_attention(qs[0], k_pool, v_pool, table, pos)

    tiles = [(bt, ht)
             for bt in (1, 2, 4, 8, 16) if args.blocks % bt == 0
             for ht in (1, 2, 4, 8) if args.heads % ht == 0]
    for bt, ht in tiles:
        try:
            fn = jax.jit(lambda q, _bt=bt, _ht=ht: paged_decode_attention(
                q, k_pool, v_pool, table, pos, impl=impl,
                block_tile=_bt, head_tile=_ht))
            out = fn(qs[0])
            err = float(jnp.max(jnp.abs(out - ref)))
            bitwise = bool(jnp.array_equal(out, ref))
            row = {"tiles": f"{bt}x{ht}", "max_err_vs_oracle": err,
                   "bitwise": bitwise}
            if on_tpu:
                float(fn(qs[1]).sum())        # compile + warm outside timing
                t0 = time.perf_counter()
                acc = 0.0
                for i in range(20):
                    acc += float(fn(qs[i % len(qs)]).sum())  # fenced fetch
                row["ms"] = round((time.perf_counter() - t0) / 20 * 1e3, 3)
                # VMEM residency the scratch pair charges this combo
                row["scratch_kb"] = round(
                    2 * ht * args.blocks * args.block_size
                    * args.head_dim * 4 / 1024, 1)
            else:
                assert bitwise, f"interpret tiles {bt}x{ht} not bitwise"
        except Exception as e:                # pragma: no cover - report
            row = {"tiles": f"{bt}x{ht}", "FAILED": str(e)[:140]}
        print(json.dumps(row), flush=True)

    # env-knob leg: snapshot discipline round-trip (fresh jit root)
    old = os.environ.get("BIGDL_PAGED_DECODE_TILES")
    os.environ["BIGDL_PAGED_DECODE_TILES"] = "2x2"
    try:
        envknobs.refresh()
        fn = jax.jit(lambda q: paged_decode_attention(
            q, k_pool, v_pool, table, pos, impl=impl))
        env_ok = bool(jnp.array_equal(fn(qs[0]), ref)) \
            and envknobs.PAGED_DECODE_TILES == (2, 2)
    finally:
        if old is None:
            os.environ.pop("BIGDL_PAGED_DECODE_TILES", None)
        else:
            os.environ["BIGDL_PAGED_DECODE_TILES"] = old
        envknobs.refresh()
    print(json.dumps({"env_knob_roundtrip": env_ok}), flush=True)
    if not on_tpu:
        print(json.dumps({
            "note": "interpret-mode smoke only — on-chip ms/tile is "
                    "ISSUE 17 measurement debt (PROFILE_r06 protocol)"},
        ), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
