"""Fleet ops console (ISSUE 14) — a terminal dashboard over the live
SLO plane.

Renders pool health, per-engine / per-layout SLO compliance, firing
alerts, and recent incidents as one text frame. Two sources:

* **JSONL event log** (the `BIGDL_OBS_EVENTS` sink / an explicit
  `EventLog(path=...)`): the frame is a PURE function of the parsed
  events — replaying the same file twice prints byte-identical
  frames (the deterministic mode the tests pin). `--follow` tails the
  file of a LIVE run (e.g. a loadgen or serve_lm process writing the
  sink) and redraws every `--interval` seconds.
* **Scrape endpoint** (`--url http://host:port`, obs/exposition.py):
  polls `/health` (+ `/metrics` for the pool gauges) and renders the
  JSON ops view — the live-fleet mode when only the HTTP surface is
  reachable.

Usage:
    # deterministic replay (one frame, byte-identical run to run):
    python scripts/ops_console.py /tmp/run.jsonl

    # watch a live loadgen run through its JSONL sink:
    BIGDL_OBS_EVENTS=/tmp/run.jsonl JAX_PLATFORMS=cpu \
        python scripts/loadgen.py --requests 64 --engines 2 ... &
    python scripts/ops_console.py /tmp/run.jsonl --follow

    # watch through a scrape endpoint (obs.ScrapeServer):
    python scripts/ops_console.py --url http://127.0.0.1:8080 --follow
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

WIDTH = 78


def _report_mod():
    """scripts/obs_report.py as a module — the console reuses its
    summarize() digests (SLO, alerts, incidents) so the two surfaces
    can never disagree about a run."""
    mod = sys.modules.get("bigdl_obs_report")
    if mod is None:
        path = os.path.join(os.path.dirname(__file__), "obs_report.py")
        spec = importlib.util.spec_from_file_location(
            "bigdl_obs_report", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["bigdl_obs_report"] = mod
        spec.loader.exec_module(mod)
    return mod


def _rule(title: str) -> str:
    pad = WIDTH - len(title) - 4
    return f"── {title} " + "─" * max(pad, 0)


def _kv_rows(rows: List[tuple], indent: str = "  ") -> List[str]:
    if not rows:
        return [indent + "(none)"]
    w = max(len(str(k)) for k, _ in rows)
    return [f"{indent}{str(k):<{w}}  {v}" for k, v in rows]


def _fmt_s(v) -> str:
    return "-" if v is None else f"{v:.4g}s"


# --------------------------------------------------------- event frames

def render_frame(events: List[dict]) -> str:
    """One dashboard frame from an event list — deterministic: no
    wall-clock reads, no environment, output a pure function of the
    events (the byte-identity surface)."""
    rep = _report_mod()
    s = rep.summarize(events)
    lines: List[str] = []
    ts = [e["ts"] for e in events
          if isinstance(e.get("ts"), (int, float))]
    span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    lines.append("═" * WIDTH)
    lines.append(f" fleet ops console — {len(events)} events over "
                 f"{round(span, 3)}s")
    lines.append("═" * WIDTH)

    # ---- pool health -----------------------------------------------
    lines.append(_rule("pool"))
    term = [e for e in events if e.get("kind") == "request_terminal"]
    engines = sorted({e.get("engine", "?") for e in term}
                     | {e.get("engine") for e in events
                        if e.get("kind") in ("engine_added",
                                             "engine_degraded",
                                             "engine_drain")
                        and e.get("engine")})
    degraded = {e.get("engine") for e in events
                if e.get("kind") == "engine_degraded"}
    drained = {e.get("engine") for e in events
               if e.get("kind") == "engine_removed"}
    rows = []
    for eng in engines:
        evs = [e for e in term if e.get("engine", "?") == eng]
        state = ("DEGRADED" if eng in degraded
                 else "removed" if eng in drained else "serving")
        tp = evs[-1].get("tp") if evs else None
        role = evs[-1].get("role") if evs else None
        tag = "" if tp in (None, 1) else f" tp={tp}"
        tag += f" role={role}" if role and role != "both" else ""
        done = sum(1 for e in evs if e.get("status") == "done")
        toks = sum(e.get("tokens", 0) for e in evs
                   if e.get("status") == "done")
        rows.append((eng, f"{state}{tag}  {done}/{len(evs)} done, "
                          f"{toks} tok"))
    added = sum(1 for e in events if e.get("kind") == "engine_added")
    removed = sum(1 for e in events
                  if e.get("kind") == "engine_removed")
    if added or removed:
        rows.append(("pool churn", f"+{added} engines, -{removed}"))
    lines.extend(_kv_rows(rows))

    # ---- SLO compliance --------------------------------------------
    lines.append(_rule("SLO"))
    slo = s.get("slo")
    if slo:
        def fmt(d):
            return (f"done {d['done']}/{d['requests']}  ttft p50/p99 "
                    f"{_fmt_s(d['ttft_p50_s'])}/{_fmt_s(d['ttft_p99_s'])}"
                    f"  latency p99 {_fmt_s(d['latency_p99_s'])}  "
                    f"shed/exp/poison {d['shed_rate']}"
                    f"/{d['expired_rate']}/{d['poisoned_rate']}")
        rows = [("fleet", fmt(slo["fleet"]))]
        rows += [(eng, fmt(d))
                 for eng, d in slo["per_engine"].items()]
        rows += [(layout, fmt(d))
                 for layout, d in slo.get("per_layout", {}).items()]
        lines.extend(_kv_rows(rows))
    else:
        lines.extend(_kv_rows([]))

    # ---- tenants (ISSUE 19) ----------------------------------------
    tn = s.get("tenants")
    if tn:
        lines.append(_rule("tenants"))
        rows = []
        for t, d in tn.items():
            thr = d.get("throttled", {})
            thr_txt = ("none" if not thr else
                       " ".join(f"{k}={n}" for k, n in thr.items()))
            if d["requests"]:
                rows.append((t, f"done {d['done']}/{d['requests']}  "
                                f"p99 {_fmt_s(d.get('latency_p99_s'))}"
                                f"  throttled {thr_txt}"))
            else:
                rows.append((t, f"no terminals  throttled {thr_txt}"))
        lines.extend(_kv_rows(rows))

    # ---- alerts -----------------------------------------------------
    lines.append(_rule("alerts"))
    al = s.get("alerts")
    if al:
        rows = []
        for obj, o in al["objectives"].items():
            comp = ("-" if o["compliant_frac"] is None
                    else f"{o['compliant_frac']:.2%}")
            rows.append((obj, f"{o['alerts']} alert(s), "
                              f"{o['time_firing_s']}s firing, "
                              f"compliant {comp}"))
        for rec in al["timeline"]:
            state = (f"resolved after {rec['firing_s']}s"
                     if rec["firing_s"] is not None
                     else "** STILL FIRING **")
            rows.append((f"{rec['alert']}",
                         f"fired t={rec['fired_ts']} value "
                         f"{rec['value']} > {rec['target']} "
                         f"({rec['rule_kind']}) — {state}"))
        lines.extend(_kv_rows(rows))
    else:
        lines.extend(_kv_rows([]))

    # ---- kv tier (ISSUE 16) ----------------------------------------
    kt = s.get("kv_tier")
    if kt:
        lines.append(_rule("kv tier"))
        rows = [("spill / re-admit",
                 f"{kt['spilled_blocks']} blocks out, "
                 f"{kt['readmitted_blocks']} back")]
        if "hit_source" in kt:
            hs = kt["hit_source"]
            rows.append(("hit source",
                         f"device {hs['device']} / host {hs['host']}"
                         f" / miss {hs['miss']}"))
        for path in kt.get("migration_paths", []):
            rows.append((f"{path['source']} -> {path['target']}",
                         f"migrated {path['blocks']} blocks "
                         f"({path['chains']} chains)"))
        for key, v in sorted(kt.get("tier_blocks_in_use",
                                    {}).items()):
            rows.append((f"in use [{key}]", v))
        lines.extend(_kv_rows(rows))

    # ---- incidents --------------------------------------------------
    lines.append(_rule("incidents"))
    inc = s.get("incidents")
    if inc:
        rows = [(b["bundle"], f"{b['incident']} @ {b['component']} "
                              f"(trigger {b['trigger_kind']})")
                for b in inc["bundles"]]
        lines.extend(_kv_rows(rows))
    else:
        lines.extend(_kv_rows([]))
    lines.append("═" * WIDTH)
    return "\n".join(lines)


# ------------------------------------------------------- scrape frames

def render_scrape_frame(health: dict, metrics_text: str) -> str:
    """One frame from a scrape endpoint's /health JSON + /metrics
    text (obs/exposition.py)."""
    lines = ["═" * WIDTH, " fleet ops console — scrape endpoint",
             "═" * WIDTH, _rule("endpoint")]
    samp = health.get("sampler") or {}
    lines.extend(_kv_rows([
        ("scrapes", health.get("scrapes")),
        ("samples", samp.get("samples")),
        ("last sample t", samp.get("last_sample_t")),
    ]))
    lines.append(_rule("objectives"))
    rows = [(o["objective"],
             f"value {o['value']} vs target {o['target']} — "
             + ("OK" if o["ok"] else "VIOLATED"))
            for o in health.get("objectives", [])]
    lines.extend(_kv_rows(rows))
    lines.append(_rule("alerts"))
    rows = [(a["alert"], f"{a['state']}  value {a['value']} target "
                         f"{a['target']} ({a['kind']})")
            for a in health.get("alerts", [])]
    lines.extend(_kv_rows(rows))
    lines.append(_rule("pool gauges"))
    rows = []
    for ln in metrics_text.splitlines():
        if ln.startswith(("router_pool_size",
                          "serving_kv_pool_blocks_in_use",
                          "serving_kv_tier_blocks_in_use",
                          "serving_tp_shards")):
            name, _, val = ln.rpartition(" ")
            rows.append((name, val))
    lines.extend(_kv_rows(rows))
    lines.append("═" * WIDTH)
    return "\n".join(lines)


def _fetch(url: str) -> bytes:
    import urllib.request

    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.read()


def _one_frame(args) -> Optional[str]:
    if args.url:
        health = json.loads(_fetch(args.url.rstrip("/") + "/health"))
        metrics = _fetch(args.url.rstrip("/") + "/metrics").decode()
        return render_scrape_frame(health, metrics)
    from bigdl_tpu.obs.events import read_jsonl

    events = read_jsonl(args.path)
    if not events:
        return None
    return render_frame(events)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", nargs="?", default=None,
                    help="JSONL event file (BIGDL_OBS_EVENTS sink)")
    ap.add_argument("--url", default=None,
                    help="scrape endpoint base URL instead of a file "
                         "(obs.ScrapeServer: /health + /metrics)")
    ap.add_argument("--follow", action="store_true",
                    help="redraw every --interval seconds (live run)")
    ap.add_argument("--interval", type=float, default=2.0)
    args = ap.parse_args(argv)
    if (args.path is None) == (args.url is None):
        print("ops-console: pass a JSONL path OR --url", file=sys.stderr)
        return 2
    if not args.follow:
        try:
            frame = _one_frame(args)
        except OSError as e:
            print(f"ops-console: cannot read source: {e}",
                  file=sys.stderr)
            return 2
        if frame is None:
            print(f"ops-console: no events in {args.path}",
                  file=sys.stderr)
            return 2
        print(frame)
        return 0
    try:
        while True:
            try:
                frame = _one_frame(args)
            except OSError as e:
                frame = f"(source unavailable: {e})"
            # clear + home, then the frame — a cheap live dashboard
            sys.stdout.write("\x1b[2J\x1b[H"
                             + (frame or "(no events yet)") + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
