"""ResNet-50 / Inception-v1 train-step profile — decomposed fenced
timings + ablations (round-4 attribution, VERDICT r3 item 1).

Methodology identical to scripts/profile_lm.py: jax.profiler traces are
unreliable through the remote-TPU tunnel, so the primary instrument is
component decomposition — each stage of the network (stem, stage1..4,
head) and each ablated full step (frozen-BN, no-BN, one-pass-var BN) is
jitted separately and timed with the fenced-fetch methodology (bench.py
"Measurement notes": serial chaining inside one jit, final host fetch,
rotating inputs are unnecessary here because the chain perturbs its own
input each iteration).

Reference parity: models/utils/LocalOptimizerPerf.scala-style synthetic
harness (SURVEY.md §5.1) specialized to the vision flagship.

Usage:
    python scripts/profile_resnet.py                    # resnet50, B=256
    python scripts/profile_resnet.py --model inception_v1
    python scripts/profile_resnet.py --skip-components  # full steps only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    # CPU-only runs must also drop the axon remote-TPU factory before
    # first backend use (tests/conftest.py documents why)
    from bigdl_tpu.utils.engine import ensure_cpu_platform

    ensure_cpu_platform()

PEAK_BF16 = 197e12  # TPU v5e peak bf16 FLOP/s


def fenced(fn, args, iters, fetch):
    out = fn(*args)
    float(fetch(out))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(fetch(out))
    return (time.perf_counter() - t0) / iters


def measure(report, key, fn, args, iters, fetch):
    try:
        t = fenced(fn, args, iters, fetch)
        report[key] = round(t * 1e3, 3)
    except Exception as e:
        report[key] = f"FAILED: {str(e)[:160]}"
    print(json.dumps({key: report[key]}), flush=True)


CHAIN_N, CHAIN_REPS = 6, 3  # overridden by --chain-n/--chain-reps


def chain_stage(report, key, apply_fn, x0, n=None, reps=None):
    """Per-call time of `apply_fn(x)` (arbitrary out-shape) with the
    dispatch floor amortized: serialize n calls inside one jit by
    coupling each call's input to the previous call's output through a
    scalar (+ c*eps forces the data dependence; compiler cannot hoist)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = n or CHAIN_N
    reps = reps or CHAIN_REPS

    def body(c, _):
        y = apply_fn(x0 + c.astype(x0.dtype))
        return jnp.sum(y).astype(jnp.float32) * 1e-30, None

    looped = jax.jit(lambda c: lax.scan(body, c, None, length=n)[0])
    try:
        c = looped(jnp.zeros((), jnp.float32))
        float(c)
        t0 = time.perf_counter()
        for _ in range(reps):
            c = looped(c)
        float(c)
        report[key] = round((time.perf_counter() - t0) / (reps * n) * 1e3, 3)
    except Exception as e:
        report[key] = f"FAILED: {str(e)[:160]}"
    print(json.dumps({key: report[key]}), flush=True)


def _xla_fwd_flops(fn, *args):
    try:
        ca = fn.lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:
        return None


def build_model(name, bn_mode="train"):
    """bn_mode: train = normal; none = BN layers replaced by Identity."""
    from bigdl_tpu import nn
    from bigdl_tpu.models import inception, resnet

    model = (resnet.build_imagenet(50, 1000) if name == "resnet50"
             else inception.build(1000))
    if bn_mode == "none":
        def strip(container):
            for i, m in enumerate(container.modules):
                if isinstance(m, nn.SpatialBatchNormalization):
                    container.modules[i] = nn.Identity()
                elif hasattr(m, "modules"):
                    strip(m)
        strip(model)
    return model


def make_step(model, method, policy, frozen_bn=False):
    """Full train step exactly as bench.py's bench_vision builds it.
    frozen_bn: run the model with training=False inside the loss (BN
    normalizes with running stats — no batch reductions) while still
    taking grads; isolates the cost of BN's train-mode statistics."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.ops.losses import build_train_loss

    if not frozen_bn:
        loss_call = build_train_loss(model, nn.ClassNLLCriterion(), policy)
    else:
        crit = nn.ClassNLLCriterion()

        def loss_call(p, mod_state, x, y, rng):
            p = policy.cast_to_compute(p)
            x = policy.cast_to_compute(x)
            out, _ = model.apply(
                {"params": p, "state": policy.cast_to_compute(mod_state)},
                x, training=False, rng=rng)
            # return the ORIGINAL f32 state: returning the cast copy
            # changes the carry dtype between warmup and the timed
            # loop, landing a recompile inside the timed region
            # (memory: tpu-measurement-gotchas)
            return crit(policy.cast_to_output(out), y), mod_state

    @jax.jit
    def step(bx, by, carry):
        params, state, slots = carry
        (loss, new_state), grads = jax.value_and_grad(
            lambda p: loss_call(p, state, bx, by, jax.random.PRNGKey(1)),
            has_aux=True)(params)
        new_params, new_slots = method.update(
            grads, params, slots, jnp.asarray(0.1), jnp.asarray(0))
        return (new_params, new_state, new_slots), loss

    return step


def run_full(report, key, model, batch, iters, policy):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.optim import SGD

    method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0)
    variables = model.init(jax.random.PRNGKey(0))
    step = make_step(model, method, policy,
                     frozen_bn=key.endswith("frozen_bn"))
    carry = ((variables["params"], variables["state"],
              method.init_slots(variables["params"])))
    rng = np.random.RandomState(0)
    pool = [(jnp.asarray(rng.rand(batch, 224, 224, 3).astype(np.float32)),
             jnp.asarray(rng.randint(0, 1000, batch).astype(np.int32)))
            for _ in range(4)]
    try:
        carry, loss = step(*pool[0], carry)
        float(loss)
        t0 = time.perf_counter()
        for i in range(iters):
            carry, loss = step(*pool[(i + 1) % 4], carry)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        report[key] = {"step_ms": round(dt * 1e3, 2),
                       "images_per_sec": round(batch / dt, 1)}
    except Exception as e:
        report[key] = f"FAILED: {str(e)[:160]}"
    print(json.dumps({key: report[key]}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "inception_v1"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--skip-components", action="store_true")
    ap.add_argument("--skip-ablations", action="store_true")
    ap.add_argument("--skip-full", action="store_true")
    ap.add_argument("--only-stage", default=None,
                    help="comma list: stem,stage1..stage4,head,micro")
    ap.add_argument("--chain-n", type=int, default=6)
    ap.add_argument("--chain-reps", type=int, default=3)
    args = ap.parse_args()

    global CHAIN_N, CHAIN_REPS
    CHAIN_N, CHAIN_REPS = args.chain_n, args.chain_reps

    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.models import resnet as R
    from bigdl_tpu.utils.precision import DEFAULT_MIXED as policy

    B = args.batch
    report = {"config": {"model": args.model, "batch": B}}
    rng = np.random.RandomState(0)

    # ---- full-step baselines + ablations ----------------------------
    if not args.skip_full:
        run_full(report, "full_step", build_model(args.model), B,
                 args.iters, policy)
    if not (args.skip_ablations or args.skip_full):
        run_full(report, "full_step_frozen_bn", build_model(args.model),
                 B, args.iters, policy)
        run_full(report, "full_step_no_bn",
                 build_model(args.model, bn_mode="none"), B, args.iters,
                 policy)

    if args.skip_components or args.model != "resnet50":
        print(json.dumps(report, indent=1))
        return

    # ---- per-stage decomposition (resnet50) -------------------------
    # Shapes at B: stem (B,224,224,3)->(B,56,56,64); s1 ->(B,56,56,256);
    # s2 ->(B,28,28,512); s3 ->(B,14,14,1024); s4 ->(B,7,7,2048).
    def seq(*mods):
        return nn.Sequential(*mods)

    stages = {
        "stem": (seq(R._conv(3, 64, 7, 2, 3), R._bn(64), nn.ReLU(),
                     nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)),
                 (B, 224, 224, 3)),
        "stage1": (seq(R.bottleneck(64, 64, 1),
                       R.bottleneck(256, 64), R.bottleneck(256, 64)),
                   (B, 56, 56, 64)),
        "stage2": (seq(R.bottleneck(256, 128, 2),
                       *[R.bottleneck(512, 128) for _ in range(3)]),
                   (B, 56, 56, 256)),
        "stage3": (seq(R.bottleneck(512, 256, 2),
                       *[R.bottleneck(1024, 256) for _ in range(5)]),
                   (B, 28, 28, 512)),
        "stage4": (seq(R.bottleneck(1024, 512, 2),
                       *[R.bottleneck(2048, 512) for _ in range(2)]),
                   (B, 14, 14, 1024)),
        "head": (seq(nn.SpatialAveragePooling(7, 7, 1, 1),
                     nn.Reshape([2048]), nn.Linear(2048, 1000),
                     nn.LogSoftMax()),
                 (B, 7, 7, 2048)),
        # single interior bottlenecks (stage × block-count estimates the
        # stage; whole-stage graphs reproducibly hang the remote compile
        # service — see tpu-measurement-gotchas)
        "block1": (seq(R.bottleneck(256, 64)), (B, 56, 56, 256)),
        "block2": (seq(R.bottleneck(512, 128)), (B, 28, 28, 512)),
        "block3": (seq(R.bottleneck(1024, 256)), (B, 14, 14, 1024)),
        "block4": (seq(R.bottleneck(2048, 512)), (B, 7, 7, 2048)),
    }

    only = (set(args.only_stage.split(",")) if args.only_stage else None)
    for name, (stage, shape) in stages.items():
        if only is not None and name not in only:
            continue
        variables = stage.init(jax.random.PRNGKey(0))
        pc = policy.cast_to_compute(variables["params"])
        st = variables["state"]
        x0 = jnp.asarray(rng.rand(*shape), jnp.bfloat16)

        def fwd(x, _pc=pc, _st=st, _stage=stage):
            return _stage.apply({"params": _pc, "state": _st}, x,
                                training=True)[0]

        chain_stage(report, f"{name}_fwd_ms", fwd, x0)

        # fwd+bwd: grads wrt params AND input (params-only would DCE
        # nothing but input-only would DCE all the dW work — see
        # memory: attention-kernel-tuning "misleading micro-benchmarks")
        def loss(p, x, _st=st, _stage=stage):
            y = _stage.apply({"params": p, "state": _st}, x,
                             training=True)[0]
            return jnp.sum(y.astype(jnp.float32))

        g = jax.grad(loss, argnums=(0, 1))

        def fwdbwd(x, _g=g, _pc=pc):
            gp, gx = _g(_pc, x)
            extra = sum(jnp.sum(l).astype(jnp.float32)
                        for l in jax.tree_util.tree_leaves(gp))
            return gx + (extra * 1e-30).astype(gx.dtype)

        chain_stage(report, f"{name}_fwdbwd_ms", fwdbwd, x0,
                    n=max(1, CHAIN_N - 2))

        # XLA fwd flops per stage (conv nets: no scan, count is usable)
        jf = jax.jit(fwd)
        fl = _xla_fwd_flops(jf, x0)
        if fl:
            report[f"{name}_fwd_gflops"] = round(fl / 1e9, 1)
            if isinstance(report.get(f"{name}_fwd_ms"), float):
                report[f"{name}_fwd_tflops"] = round(
                    fl / (report[f"{name}_fwd_ms"] / 1e3) / 1e12, 1)
            print(json.dumps({f"{name}_fwd_gflops":
                              report[f"{name}_fwd_gflops"],
                              f"{name}_fwd_tflops":
                              report.get(f"{name}_fwd_tflops")}),
                  flush=True)

    # ---- BN microcosts at a representative shape --------------------
    # conv3x3 alone vs conv+bn+relu at stage-2 interior shape
    if only is not None and "micro" not in only:
        print(json.dumps(report, indent=1))
        return
    shape = (B, 28, 28, 128)
    x0 = jnp.asarray(rng.rand(*shape), jnp.bfloat16)
    convm = seq(R._conv(128, 128, 3, 1, 1))
    cbr = seq(R._conv(128, 128, 3, 1, 1), R._bn(128), nn.ReLU())
    for nm, m in [("conv3x3_alone", convm), ("conv3x3_bn_relu", cbr)]:
        v = m.init(jax.random.PRNGKey(0))
        pc = policy.cast_to_compute(v["params"])

        def f(x, _pc=pc, _st=v["state"], _m=m):
            return _m.apply({"params": _pc, "state": _st}, x,
                            training=True)[0]

        chain_stage(report, f"{nm}_fwd_ms", f, x0, n=CHAIN_N + 2)

    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
