"""Transformer-LM step profile — decomposed fenced timings + MFU.

Reference parity: models/utils/DistriOptimizerPerf.scala-style synthetic
harness (SURVEY.md §5.1), specialized to the LM flagship so the time
sinks in the 186M/S=2048 training step can be attributed (VERDICT r1
next-round item 1).

Because `jax.profiler` traces may not capture device-side activity
through the remote-TPU tunnel, the primary instrument is component
decomposition: each piece of the step (attention fwd, attention
fwd+bwd, loss head, full fwd, full step, optimizer update) is jitted
separately and timed with the fenced-fetch methodology (see bench.py
"Measurement notes"). Component times don't add exactly to the full
step (fusion boundaries differ) but rank the sinks reliably.

Usage:
    python scripts/profile_lm.py                 # 186M config
    python scripts/profile_lm.py --dim 512 --layers 8   # 43M config
    python scripts/profile_lm.py --trace /tmp/lm_trace  # + profiler trace
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PEAK_BF16 = 197e12  # TPU v5e (v5 lite) peak bf16 FLOP/s


def lm_matmul_flops_per_token(cfg, vocab_tied=True):
    """See models/transformer.lm_train_matmul_flops_per_token — the
    canonical analytic count (kept here as an alias for older tooling)."""
    from bigdl_tpu.models.transformer import lm_train_matmul_flops_per_token

    return lm_train_matmul_flops_per_token(cfg)


def param_count(params):
    import jax

    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def fenced(fn, args, iters, fetch):
    """Time `iters` chained calls of fn; fence with a host fetch."""
    out = fn(*args)
    float(fetch(out))  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(fetch(out))
    return (time.perf_counter() - t0) / iters


def measure(report, key, fn, args, iters, fetch):
    """fenced() with OOM/compile-failure tolerance + incremental print."""
    try:
        t = fenced(fn, args, iters, fetch)
        report[key] = round(t * 1e3, 3)
    except Exception as e:  # RESOURCE_EXHAUSTED etc: record, keep going
        report[key] = f"FAILED: {str(e)[:160]}"
    print(json.dumps({key: report[key]}), flush=True)


def chain_time(fn, x0, n=8, reps=3):
    """Per-call time of `fn` with the dispatch floor amortized away:
    scan n dependent applications inside ONE jit (each call feeds the
    next), so the tunnel's per-dispatch latency (~17ms observed) is paid
    once per n calls, not once per call."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    looped = jax.jit(lambda x: lax.scan(
        lambda c, _: (fn(c), None), x, None, length=n)[0])
    out = looped(x0)
    float(jnp.sum(out).astype(jnp.float32))  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = looped(out)
    float(jnp.sum(out).astype(jnp.float32))
    return (time.perf_counter() - t0) / (reps * n)


def measure_chain(report, key, fn, x0, n=8):
    try:
        t = chain_time(fn, x0, n=n)
        report[key] = round(t * 1e3, 3)
    except Exception as e:
        report[key] = f"FAILED: {str(e)[:160]}"
    print(json.dumps({key: report[key]}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--trace", default=None, help="jax.profiler trace dir")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--remat-policy", default="full",
                    choices=["full", "dots", "attn_saved"])
    ap.add_argument("--attn-impl", default=None,
                    choices=[None, "pallas", "reference", "xla"],
                    help="attention implementation for the in-model runs")
    ap.add_argument("--skip-components", action="store_true")
    ap.add_argument("--loss", default="fused",
                    choices=["fused", "logsoftmax"],
                    help="fused = logits+LSE chunked loss; logsoftmax = "
                    "materialize full log-probs then NLL (round-1 path)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import TransformerConfig, TransformerLM
    from bigdl_tpu.optim import Adam
    from bigdl_tpu.utils.precision import DEFAULT_MIXED as policy

    cfg = TransformerConfig(
        vocab_size=args.vocab, max_len=args.seq, dim=args.dim,
        num_heads=args.heads, num_layers=args.layers, remat=args.remat,
        remat_policy=args.remat_policy)
    model = TransformerLM(cfg, attn_impl=args.attn_impl)
    variables = model.init(jax.random.PRNGKey(0))
    params = variables["params"]
    n_params = param_count(params)
    method = Adam(3e-4)
    slots = method.init_slots(params)

    B, S, e, H = args.batch, args.seq, args.dim, args.heads
    D = e // H
    rng = np.random.RandomState(0)
    # rotate a batch pool: identical executions may be memoized server-side
    POOL = 4
    toks = [jnp.asarray(rng.randint(0, args.vocab, (B, S)), jnp.int32)
            for _ in range(POOL)]
    tgts = [jnp.asarray(rng.randint(0, args.vocab, (B, S)), jnp.int32)
            for _ in range(POOL)]

    report = {
        "config": {"dim": e, "layers": args.layers, "heads": H,
                   "vocab": args.vocab, "seq": S, "batch": B,
                   "remat": args.remat, "remat_policy": args.remat_policy,
                   "loss": args.loss},
        "n_params": n_params,
    }
    flops_tok = lm_matmul_flops_per_token(cfg)
    report["train_flops_per_token"] = flops_tok

    # ---- loss on logits ---------------------------------------------
    def lm_loss(p, tokens, targets):
        pc = policy.cast_to_compute(p)
        if args.loss == "logsoftmax":
            logp, _ = model.apply({"params": pc, "state": {}}, tokens)
            logp = logp.astype(jnp.float32)
            picked = jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            return -picked.mean()
        # fused: model minus final log_softmax, chunked LSE loss
        return model.loss({"params": pc, "state": {}}, tokens, targets)

    # ---- components (in-jit chained loops: see chain_time) ----------
    from bigdl_tpu.ops.flash_attention import flash_attention

    if args.skip_components:
        _run_full(args, report, model, cfg, params, slots, method, policy,
                  toks, tgts, POOL, B, S, flops_tok, lm_loss)
        return

    k_c = jnp.asarray(rng.randn(B * H, S, D), jnp.bfloat16)
    v_c = jnp.asarray(rng.randn(B * H, S, D), jnp.bfloat16)
    q0 = jnp.asarray(rng.randn(B * H, S, D), jnp.bfloat16)

    # MXU ceiling through this tunnel: big chained bf16 matmul
    on_tpu = jax.devices()[0].platform == "tpu"
    mm = 4096 if on_tpu else 512
    mm_a0 = jnp.asarray(rng.randn(mm, mm), jnp.bfloat16)
    mm_b = jnp.asarray(rng.randn(mm, mm), jnp.bfloat16)
    measure_chain(report, "pure_matmul_ms", lambda a: a @ mm_b, mm_a0,
                  n=32 if on_tpu else 4)
    if isinstance(report.get("pure_matmul_ms"), float):
        fl = 2 * mm ** 3
        report["pure_matmul_tflops"] = round(
            fl / (report["pure_matmul_ms"] / 1e3) / 1e12, 1)
        print(json.dumps(
            {"pure_matmul_tflops": report["pure_matmul_tflops"]}),
            flush=True)

    measure_chain(report, "attn_fwd_ms_per_layer",
                  lambda q: flash_attention(q, k_c, v_c, causal=True), q0)

    att_grad = jax.grad(
        lambda q: flash_attention(q, k_c, v_c, causal=True)
        .astype(jnp.float32).sum())
    measure_chain(report, "attn_fwdbwd_ms_per_layer",
                  lambda q: att_grad(q).astype(jnp.bfloat16), q0)

    # XLA reference attention for comparison (materializes S×S)
    from bigdl_tpu.ops.flash_attention import attention_reference
    measure_chain(report, "attn_xla_fwd_ms_per_layer",
                  lambda q: attention_reference(q, k_c, v_c, causal=True)
                  .astype(jnp.bfloat16), q0)

    # one transformer block WITHOUT attention (matmul/LN/gelu chain)
    bp0 = jax.tree_util.tree_map(lambda p: p[0], params["blocks"])
    bp0 = policy.cast_to_compute(bp0)

    def block_noattn(x):
        from bigdl_tpu.nn.normalization import layer_norm

        y = layer_norm(x, bp0["ln1_g"], bp0["ln1_b"])
        y = (y @ bp0["wq"] + bp0["bq"])
        a = y @ bp0["wo"] + bp0["bo"]
        x = x + a
        y = layer_norm(x, bp0["ln2_g"], bp0["ln2_b"])
        y = jax.nn.gelu(y @ bp0["w1"] + bp0["b1"])
        y = y @ bp0["w2"] + bp0["b2"]
        return x + y

    x0 = jnp.asarray(rng.randn(B, S, e), jnp.bfloat16)
    measure_chain(report, "block_noattn_fwd_ms", block_noattn, x0)

    # loss head alone: hidden (B,S,e) -> scalar, fwd+bwd
    hidden = jnp.asarray(rng.randn(B, S, e), jnp.bfloat16)
    headw = policy.cast_to_compute(params["embed"]).T

    def head_loss(h, w, tg):
        if args.loss == "logsoftmax":
            logits = h @ w
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1)
            return -jnp.take_along_axis(
                logp, tg[..., None], axis=-1)[..., 0].mean()
        from bigdl_tpu.ops.losses import softmax_cross_entropy_chunked

        return softmax_cross_entropy_chunked(h, w, tg)

    head_g = jax.grad(lambda h: head_loss(h, headw, tgts[0]))
    measure_chain(report, "loss_head_fwdbwd_ms",
                  lambda h: (h - 1e-3 * head_g(h)).astype(jnp.bfloat16),
                  hidden, n=4)

    _run_full(args, report, model, cfg, params, slots, method, policy,
              toks, tgts, POOL, B, S, flops_tok, lm_loss)


def _run_full(args, report, model, cfg, params, slots, method, policy,
              toks, tgts, POOL, B, S, flops_tok, lm_loss):
    import jax
    import jax.numpy as jnp

    # full forward
    fwd = jax.jit(lm_loss)
    measure(report, "fwd_ms", fwd, (params, toks[0], tgts[0]), args.iters,
            lambda o: o)

    # fwd + bwd
    grad_fn = jax.jit(jax.value_and_grad(lm_loss))
    measure(report, "fwdbwd_ms", grad_fn, (params, toks[0], tgts[0]),
            args.iters, lambda o: o[0])

    # optimizer update alone
    zeros_g = jax.tree_util.tree_map(jnp.zeros_like, params)
    upd = jax.jit(lambda g, p, s: method.update(
        g, p, s, jnp.asarray(3e-4, jnp.float32), 1))
    measure(report, "optimizer_ms", upd, (zeros_g, params, slots),
            args.iters, lambda o: jax.tree_util.tree_leaves(o[0])[0].sum())

    # ---- full train step --------------------------------------------
    @jax.jit
    def step(p, s, tokens, targets):
        loss, g = jax.value_and_grad(lm_loss)(p, tokens, targets)
        new_p, new_s = method.update(g, p, s, jnp.asarray(3e-4), 1)
        return new_p, new_s, loss

    try:
        p, s = params, slots
        new = step(p, s, toks[0], tgts[0])
        float(new[2])
        p, s = new[0], new[1]

        if args.trace:
            with jax.profiler.trace(args.trace):
                p2, s2, loss = step(p, s, toks[1], tgts[1])
                float(loss)

        t0 = time.perf_counter()
        loss = None
        for i in range(args.iters):
            p, s, loss = step(p, s, toks[i % POOL], tgts[i % POOL])
        float(loss)
        step_s = (time.perf_counter() - t0) / args.iters
        tok_s = B * S / step_s
        report["step_ms"] = round(step_s * 1e3, 3)
        report["tokens_per_sec"] = round(tok_s, 1)
        report["achieved_tflops"] = round(tok_s * flops_tok / 1e12, 2)
        report["mfu"] = round(tok_s * flops_tok / PEAK_BF16, 4)
    except Exception as e:
        report["step_ms"] = f"FAILED: {str(e)[:160]}"
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
