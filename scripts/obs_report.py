"""Render a run summary from a telemetry JSONL event file (ISSUE 5).

Input: a file written by the structured event log
(`BIGDL_OBS_EVENTS=/tmp/run.jsonl python <anything>`, or an explicit
`EventLog(path=...)`). Output: a human-readable report —

* event counts by kind (the run's shape at a glance)
* training summary: steps, loss first→last, throughput, anomalies
* serving summary: requests by terminal status, tokens generated,
  degradations
* latency-SLO section (ISSUE 7): per-engine goodput, TTFT and
  per-token p50/p99, shed/expired/poisoned rates — computed from the
  `ttft_s`/`latency_s` lifecycle stamps the engine puts on every
  `request_terminal` event (engine clock, so a drill log yields
  bit-deterministic percentiles); ISSUE 11 adds each engine's tp/role
  and a per-layout rollup (sharded vs unsharded traffic split)
* journeys section (ISSUE 11): per-request cross-engine hop table
  reconstructed by obs/journey.py from the trace/hop stamps — engines
  visited, seat kind per hop, per-hop dwell (the cross-engine TTFT
  attribution), terminal outcome; `--perfetto PATH` exports one
  Perfetto track per request
* alerts / SLO section (ISSUE 14): per-objective compliance table and
  the firing→resolved timeline reconstructed from the
  `alert_firing`/`alert_resolved` events (obs/slo.py), cross-linked
  to the slo_burn incident bundles those firings dumped
* incidents section (ISSUE 11): flight-recorder bundles indexed by
  their `incident_dump` events (obs/flightrecorder.py)
* metrics tables + latency percentiles, when the file carries a
  `metrics_snapshot` event (`obs.log_metrics_snapshot()` embeds the
  registry, making the JSONL self-contained)
* a timeline tail (the last N events)

Measurement caveat (CLAUDE.md): wall-clock numbers recorded around
un-fenced device dispatch measure dispatch, not compute —
`block_until_ready` can lie through remote-device transports. Trust
`train_step`/`decode_step` timings only where the emitting loop fenced
them with a real device→host fetch (the shipped instrumentation does:
the loss fetch fences training steps, the token fetch fences decode).

Scale (ISSUE 20): the CLI stream-parses the JSONL (one pass,
`obs.stream_jsonl`, torn-tail tolerant) so a 10⁶-event simulator run
summarizes without materializing the file as one list; every rendered
section table is row-capped with an honest "N more rows not shown"
footer, and journey reconstruction — the one hold that needs every
trace-stamped event — is capped with a named skip, never a silent
subset.

Usage:
    python scripts/obs_report.py /tmp/run.jsonl [--tail 20]
"""

from __future__ import annotations

import argparse
import math
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# THE bucket-quantile estimator and series-key rendering, shared with
# the live registry so report percentiles/keys can never drift from
# engine.health()'s or bench-row provenance
from bigdl_tpu.obs.registry import (quantile_from_buckets,  # noqa: E402
                                    series_key)
# the machine-readable kind registry (ISSUE 13): the report flags any
# kind outside it instead of keeping its own hand-maintained list
from bigdl_tpu.obs.events import (EVENT_KINDS,  # noqa: E402
                                  validate_record)


# -------------------------------------------------- streaming digest
#
# ISSUE 20: a 10⁶-event simulator run must not be materialized as one
# list just to be summarized. `summarize` makes a SINGLE pass over any
# iterable of events (a list in tests, `obs.stream_jsonl(path)` from
# the CLI), keeping only what the sections need: trimmed terminal
# stamps, the low-volume section events, streamed accumulators for the
# high-volume counters, the LAST metrics snapshot, and a bounded
# timeline tail. The one unavoidable high-volume hold is journey
# reconstruction (every trace-stamped event) — that is capped at
# _JOURNEY_EVENT_CAP with an HONEST skipped note, never a silent
# truncation.

_JOURNEY_EVENT_CAP = 500_000   # trace-stamped events held for journeys
_JOURNEY_TABLE_CAP = 200       # per-request rows kept in the digest
_TAIL_KEEP = 64                # timeline tail held during the pass

# the only request_terminal fields any section reads — a million
# trimmed stamps is a few hundred MB smaller than a million full
# events with prompts and provenance attached
_TERM_FIELDS = ("kind", "status", "tokens", "ts", "ttft_s",
                "latency_s", "tp", "role", "engine", "tenant")


def summarize(events,
              journey_event_cap: int = _JOURNEY_EVENT_CAP
              ) -> Dict[str, object]:
    """Machine-readable digest of an event iterable (the report
    renders this; tests assert on it). Single pass, bounded memory
    modulo the per-request stamp lists and the capped journey hold."""
    from collections import deque

    total = 0
    by_kind: Dict[str, int] = {}
    nonconformant = 0
    ts_min = ts_max = None
    train = {"steps": 0, "first_loss": None, "last_loss": None,
             "thr_sum": 0.0, "updates": 0}
    term: List[dict] = []
    throttles: List[dict] = []
    alert_ev: List[dict] = []
    incident_ev: List[dict] = []
    prefix = {"hits": 0, "tokens_saved": 0, "blocks_reused": 0,
              "evicts": 0, "blocks_evicted": 0}
    kv = {"spills": 0, "spilled_blocks": 0, "readmits": 0,
          "readmitted_blocks": 0}
    migrate_ev: List[dict] = []
    spec_rounds: Dict[str, dict] = {}
    spec_fallbacks: List[dict] = []
    spec_adjusts: List[dict] = []
    spec_swaps: List[dict] = []
    faults: List[str] = []
    ckpt_ev: List[dict] = []
    snapshot = None
    trace_events: List[dict] = []
    trace_event_count = 0
    tail = deque(maxlen=_TAIL_KEEP)

    for e in events:
        total += 1
        kind = e.get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
        if validate_record(e):
            nonconformant += 1
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            ts_min = ts if ts_min is None else min(ts_min, ts)
            ts_max = ts if ts_max is None else max(ts_max, ts)
        tail.append(e)
        if e.get("trace") is not None:
            trace_event_count += 1
            if trace_event_count <= journey_event_cap:
                trace_events.append(e)
        if kind == "train_step":
            train["steps"] += 1
            if "loss" in e:
                if train["first_loss"] is None:
                    train["first_loss"] = e["loss"]
                train["last_loss"] = e["loss"]
            train["thr_sum"] += e.get("throughput", 0.0)
            if e.get("update_applied", True):
                train["updates"] += 1
        elif kind == "request_terminal":
            term.append({k: e.get(k) for k in _TERM_FIELDS})
        elif kind == "tenant_throttled":
            throttles.append({"kind": kind, "tenant": e.get("tenant"),
                              "action": e.get("action")})
        elif kind in ("alert_firing", "alert_resolved"):
            alert_ev.append(e)
        elif kind == "incident_dump":
            incident_ev.append(e)
        elif kind == "prefix_hit":
            prefix["hits"] += 1
            prefix["tokens_saved"] += e.get("matched_tokens", 0)
            prefix["blocks_reused"] += e.get("blocks", 0)
        elif kind == "prefix_evict":
            prefix["evicts"] += 1
            prefix["blocks_evicted"] += e.get("blocks", 0)
        elif kind == "kv_spill":
            kv["spills"] += 1
            kv["spilled_blocks"] += e.get("blocks", 0)
        elif kind == "kv_readmit":
            kv["readmits"] += 1
            kv["readmitted_blocks"] += e.get("blocks", 0)
        elif kind == "prefix_migrate":
            migrate_ev.append(e)
        elif kind == "spec_verify":
            eng = spec_rounds.setdefault(e.get("engine", "?"), {
                "draft": e.get("draft_engine"), "rounds": 0,
                "proposed": 0, "accepted": 0, "emitted": 0})
            eng["rounds"] += 1
            eng["proposed"] += e.get("proposed", 0)
            eng["accepted"] += e.get("accepted", 0)
            eng["emitted"] += e.get("emitted", 0)
        elif kind == "spec_fallback":
            spec_fallbacks.append(e)
        elif kind == "spec_k_adjust":
            spec_adjusts.append(e)
        elif kind == "draft_swap":
            spec_swaps.append(e)
        elif kind == "fault_injected":
            faults.append(f'{e["fault"]}@{e["step"]}')
        elif kind in ("checkpoint_save", "checkpoint_load",
                      "checkpoint_corrupt_skipped"):
            ckpt_ev.append(e)
        elif kind == "metrics_snapshot":
            snapshot = e["snapshot"]

    out: Dict[str, object] = {"total_events": total}
    out["by_kind"] = dict(sorted(by_kind.items()))
    unknown = sorted(k for k in by_kind if k not in EVENT_KINDS)
    if unknown:
        # schema drift: a producer emitted kinds the EVENT_KINDS
        # registry does not know (graftlint pins committed code, but a
        # JSONL file may come from anywhere)
        out["unknown_kinds"] = unknown
    if nonconformant:
        out["nonconformant_records"] = nonconformant

    if train["steps"]:
        # loss is omitted on non-fence steps (no summary/log sink
        # needed it, so the loop never fetched it) — report from the
        # steps that carry one
        out["training"] = {
            "steps": train["steps"],
            "first_loss": train["first_loss"],
            "last_loss": train["last_loss"],
            "mean_throughput": round(
                train["thr_sum"] / train["steps"], 2),
            "updates_applied": train["updates"],
            "anomalies": by_kind.get("anomaly", 0),
        }
    if term:
        by_status: Dict[str, int] = {}
        for e in term:
            by_status[e["status"]] = by_status.get(e["status"], 0) + 1
        out["serving"] = {
            "requests": len(term),
            "by_status": dict(sorted(by_status.items())),
            "tokens_generated": sum(e.get("tokens") or 0
                                    for e in term),
            "degradations": by_kind.get("engine_degraded", 0),
            "rejected": by_kind.get("request_rejected", 0),
        }
        out["slo"] = _slo_section(term)
    tenants = _tenant_section(term + throttles)
    if tenants:
        out["tenants"] = tenants
    if trace_event_count > journey_event_cap:
        # HONEST skip: reconstructing journeys needs every
        # trace-stamped event in memory at once — over the cap the
        # section names the overflow instead of silently tabling a
        # subset of requests
        out["journeys"] = {
            "skipped": f"{trace_event_count} trace-stamped events "
                       f"exceed the {journey_event_cap}-event journey "
                       f"hold — raise summarize(journey_event_cap=) "
                       f"to reconstruct"}
    else:
        journeys = _journeys_section(trace_events)
        if journeys:
            out["journeys"] = journeys
    alerts = _alerts_section(alert_ev + incident_ev,
                             span_ts=(ts_min, ts_max))
    if alerts:
        out["alerts"] = alerts
    incidents = _incidents_section(incident_ev)
    if incidents:
        out["incidents"] = incidents
    prefix_sec = _prefix_section(prefix, snapshot)
    if prefix_sec:
        out["prefix"] = prefix_sec
    kv_tier = _kv_tier_section(kv, migrate_ev, len(term),
                               prefix["hits"], snapshot)
    if kv_tier:
        out["kv_tier"] = kv_tier
    spec = _speculation_section(spec_rounds, spec_fallbacks,
                                spec_adjusts, spec_swaps)
    if spec:
        out["speculation"] = spec
    if faults:
        out["faults"] = faults
    ckpt = _checkpoint_section(ckpt_ev, snapshot)
    if ckpt:
        out["checkpoints"] = ckpt
    if snapshot is not None:
        out["metrics"] = _digest_snapshot(snapshot)
    out["timeline_tail"] = list(tail)
    return out


def _pctl(xs: List[float], q: float) -> Optional[float]:
    """Exact nearest-rank percentile over the raw event values (the
    terminal events carry every request's stamps, so no bucket
    estimation is needed here)."""
    if not xs:
        return None
    s = sorted(xs)
    return round(s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))],
                 6)


def _slo_digest(term: List[dict]) -> dict:
    """SLO numbers for one group of request_terminal events: goodput
    (tokens of 'done' requests; per-second over the events' ts span
    when it is nonzero), TTFT / end-to-end / per-token latency
    percentiles from the engine-clock stamps, and the bad-outcome
    rates."""
    done = [e for e in term if e["status"] == "done"]
    n = len(term)
    goodput = sum(e.get("tokens", 0) for e in done)
    ts = [e["ts"] for e in term if isinstance(e.get("ts"), (int, float))]
    span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    ttft = [e["ttft_s"] for e in done
            if e.get("ttft_s") is not None]
    lat = [e["latency_s"] for e in done
           if e.get("latency_s") is not None]
    per_tok = [(e["latency_s"] - e["ttft_s"])
               / max(e.get("tokens", 1) - 1, 1)
               for e in done
               if e.get("latency_s") is not None
               and e.get("ttft_s") is not None]

    def rate(status):
        return round(sum(1 for e in term if e["status"] == status) / n,
                     4)

    return {
        "requests": n, "done": len(done),
        "goodput_tokens": goodput,
        "goodput_tokens_per_s": (round(goodput / span, 3)
                                 if span > 0 else None),
        "ttft_p50_s": _pctl(ttft, 0.50),
        "ttft_p99_s": _pctl(ttft, 0.99),
        "latency_p50_s": _pctl(lat, 0.50),
        "latency_p99_s": _pctl(lat, 0.99),
        "per_token_p50_s": _pctl(per_tok, 0.50),
        "per_token_p99_s": _pctl(per_tok, 0.99),
        "shed_rate": rate("shed"),
        "expired_rate": rate("expired"),
        "poisoned_rate": rate("poisoned"),
        "failed_rate": rate("failed"),
    }


def _slo_section(term: List[dict]) -> dict:
    """Latency-SLO digest, fleet-wide, per engine label, and (ISSUE
    11) per tensor-parallel layout. Each per-engine digest carries the
    engine's tp/role (from its terminal events), so dashboards can
    split SLOs by sharding layout without new metric families."""
    engines = sorted({e.get("engine", "?") for e in term})
    per_engine = {}
    for eng in engines:
        evs = [e for e in term if e.get("engine", "?") == eng]
        d = _slo_digest(evs)
        # tp/role ride every request_terminal (engine-constant)
        d["tp"] = evs[-1].get("tp")
        d["role"] = evs[-1].get("role")
        per_engine[eng] = d
    out = {"fleet": _slo_digest(term), "per_engine": per_engine}
    layouts = sorted({e.get("tp") for e in term
                      if e.get("tp") is not None})
    if len(layouts) > 1:
        out["per_layout"] = {
            f"tp={tp}": _slo_digest([e for e in term
                                     if e.get("tp") == tp])
            for tp in layouts}
    return out


def _tenant_section(events: List[dict]) -> Optional[dict]:
    """Per-tenant compliance digest (ISSUE 19): the same SLO numbers
    the per-engine table carries, split by the tenant each terminal
    billed against, plus the tenant's throttle counts (token-bucket
    defers/sheds from the router's admission gate and kv_quota blocks
    from the engines). Only present when the run carried tenant
    stamps; untagged terminals roll up under '(untagged)'. Accepts
    any event list — summarize passes just the terminal + throttle
    records its streaming pass kept."""
    term = [e for e in events if e.get("kind") == "request_terminal"]
    throttles = [e for e in events
                 if e.get("kind") == "tenant_throttled"]
    if not any(e.get("tenant") for e in term) and not throttles:
        return None
    tenants = sorted({e.get("tenant") or "(untagged)" for e in term}
                     | {e["tenant"] for e in throttles})
    out = {}
    for t in tenants:
        evs = [e for e in term
               if (e.get("tenant") or "(untagged)") == t]
        d = _slo_digest(evs) if evs else {"requests": 0, "done": 0}
        thr = [e for e in throttles if e["tenant"] == t]
        by_action: Dict[str, int] = {}
        for e in thr:
            by_action[e["action"]] = by_action.get(e["action"], 0) + 1
        d["throttled"] = dict(sorted(by_action.items()))
        out[t] = d
    return out


def _journeys_section(events: List[dict]) -> Optional[dict]:
    """Request-journey digest (ISSUE 11): summary counts plus a
    per-request hop table — engines visited, seat kind and dwell per
    hop (the cross-engine TTFT/latency attribution)."""
    from bigdl_tpu.obs.journey import build_journeys, summarize_journeys

    journeys = build_journeys(events)
    if not journeys:
        return None
    table = []
    for j in journeys[:_JOURNEY_TABLE_CAP]:
        table.append({
            "trace": j["trace"], "request": j["request"],
            "status": j["status"], "tokens": j["tokens"],
            "ttft_s": j["ttft_s"], "latency_s": j["latency_s"],
            "hops": [
                {"engine": h["engine"], "tp": h["tp"],
                 "role": h["role"], "via": h["via"],
                 "dwell_s": h["dwell_s"]} for h in j["hops"]],
            "lost_hops": j["lost_hops"],
        })
    out = {"summary": summarize_journeys(journeys), "table": table}
    if len(journeys) > _JOURNEY_TABLE_CAP:
        # summary covers ALL journeys; the per-request table is capped
        # — name the overflow (no-silent-caps)
        out["table_more"] = len(journeys) - _JOURNEY_TABLE_CAP
    return out


def _alerts_section(events: List[dict],
                    span_ts: Optional[tuple] = None) -> Optional[dict]:
    """Alerts / SLO digest (ISSUE 14): the firing→resolved timeline
    reconstructed from `alert_firing`/`alert_resolved` events
    (obs/slo.py), per-objective compliance over the run (time spent
    firing vs the event span), and cross-links to the flight-recorder
    bundles those firings dumped (incident_dump events whose
    trigger_kind is alert_firing). `span_ts=(ts_min, ts_max)` lets the
    streaming pass supply the WHOLE run's span without handing over
    every event; without it the span is the passed events' ts extent
    (the original list-mode behavior, pinned by test_slo)."""
    firing = [e for e in events if e.get("kind") == "alert_firing"]
    resolved = [e for e in events if e.get("kind") == "alert_resolved"]
    if not (firing or resolved):
        return None
    if span_ts is not None and span_ts[0] is not None:
        lo, hi = span_ts
        ts = [lo, hi]
        span = hi - lo
    else:
        ts = [e["ts"] for e in events
              if isinstance(e.get("ts"), (int, float))]
        span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    timeline: List[dict] = []
    open_by_alert: Dict[str, dict] = {}
    for e in sorted(firing + resolved, key=lambda r: r.get("seq", 0)):
        if e["kind"] == "alert_firing":
            rec = {"alert": e.get("alert"),
                   "objective": e.get("objective"),
                   "fired_ts": e.get("ts"), "value": e.get("value"),
                   "target": e.get("target"),
                   "window_s": e.get("window_s"),
                   "rule_kind": e.get("rule_kind"),
                   "resolved_ts": None, "firing_s": None}
            timeline.append(rec)
            open_by_alert[e.get("alert")] = rec
        else:
            rec = open_by_alert.pop(e.get("alert"), None)
            if rec is not None:
                rec["resolved_ts"] = e.get("ts")
                rec["firing_s"] = e.get("firing_s")
    per_obj: Dict[str, dict] = {}
    intervals: Dict[str, List[tuple]] = {}
    for rec in timeline:
        key = rec["objective"] or "?"
        o = per_obj.setdefault(key, {
            "alerts": 0, "time_firing_s": 0.0, "still_firing": 0})
        o["alerts"] += 1
        if rec["resolved_ts"] is None and rec["firing_s"] is None:
            o["still_firing"] += 1
        if isinstance(rec["fired_ts"], (int, float)) and ts:
            # an open firing burns budget up to the log's end
            end = rec["resolved_ts"] \
                if isinstance(rec["resolved_ts"], (int, float)) \
                else max(ts)
            intervals.setdefault(key, []).append(
                (rec["fired_ts"], max(end, rec["fired_ts"])))
    for key, ivs in intervals.items():
        # UNION the firing intervals: two rules over one objective
        # (the standard burn_rate + threshold pairing) firing together
        # must not double-count budget and drive compliance negative
        total, cur_lo, cur_hi = 0.0, None, None
        for lo, hi in sorted(ivs):
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    total += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            total += cur_hi - cur_lo
        per_obj[key]["time_firing_s"] = total
    for o in per_obj.values():
        o["time_firing_s"] = round(o["time_firing_s"], 6)
        o["compliant_frac"] = (
            round(max(0.0, 1.0 - o["time_firing_s"] / span), 4)
            if span > 0 else None)
    out = {"firing_events": len(firing),
           "resolved_events": len(resolved),
           "objectives": dict(sorted(per_obj.items())),
           "timeline": timeline}
    bundles = [e.get("bundle") for e in events
               if e.get("kind") == "incident_dump"
               and e.get("trigger_kind") == "alert_firing"]
    if bundles:
        out["bundles"] = bundles
    return out


def _incidents_section(events: List[dict]) -> Optional[dict]:
    """Flight-recorder digest (ISSUE 11): every incident_dump event
    names its bundle directory, trigger and component."""
    dumps = [e for e in events if e.get("kind") == "incident_dump"]
    if not dumps:
        return None
    by_kind: Dict[str, int] = {}
    for e in dumps:
        k = e.get("incident", "?")
        by_kind[k] = by_kind.get(k, 0) + 1
    return {
        "count": len(dumps),
        "by_incident": dict(sorted(by_kind.items())),
        "bundles": [{"bundle": e.get("bundle"),
                     "incident": e.get("incident"),
                     "component": e.get("component"),
                     "trigger_kind": e.get("trigger_kind")}
                    for e in dumps],
    }


def _prefix_section(acc: dict, snapshot: Optional[dict]
                    ) -> Optional[dict]:
    """Prefix-cache digest (ISSUE 8): hit rate / tokens and bytes
    saved / pool occupancy, from the serving_prefix_* counters and the
    serving_kv_pool_blocks_in_use gauge of the last embedded
    metrics_snapshot, cross-checked against the raw prefix_hit /
    prefix_evict events (which carry per-hit matched token counts even
    when no snapshot was logged). `acc` is summarize's streamed
    hit/evict accumulator — the raw events are never held."""
    out: dict = {}
    if acc["hits"]:
        out["hits"] = acc["hits"]
        out["tokens_saved"] = acc["tokens_saved"]
        out["blocks_reused"] = acc["blocks_reused"]
    if acc["evicts"]:
        out["blocks_evicted"] = acc["blocks_evicted"]
    if snapshot is not None:
        metrics = snapshot.get("metrics", {})

        def total(name):
            fam = metrics.get(name)
            if fam is None:
                return None
            return sum(s["value"] for s in fam["series"])

        hits = total("serving_prefix_hits_total")
        prefills = total("serving_prefill_calls_total")
        if hits is not None:
            out.setdefault("hits", hits)
            out["hit_rate"] = (round(hits / prefills, 4)
                               if prefills else None)
        for key, name in (
                ("tokens_saved", "serving_prefix_tokens_saved_total"),
                ("bytes_saved", "serving_prefix_bytes_saved_total"),
                ("blocks_reused",
                 "serving_prefix_blocks_reused_total")):
            v = total(name)
            if v is not None:
                out.setdefault(key, v)
        occ = metrics.get("serving_kv_pool_blocks_in_use")
        if occ is not None:
            out["pool_blocks_in_use"] = {
                s["labels"].get("engine", "?"): s["value"]
                for s in occ["series"]}
    return out or None


def _kv_tier_section(acc: dict, migrate_ev: List[dict], n_term: int,
                     n_hits: int, snapshot: Optional[dict]
                     ) -> Optional[dict]:
    """Host spill-tier digest (ISSUE 16): spill/re-admit block flow
    from the kv_spill / kv_readmit events (streamed into `acc`),
    warm-state migrations from prefix_migrate (source -> target
    paths), per-tier occupancy from the serving_kv_tier_blocks_in_use
    gauge of the last embedded metrics snapshot, and the hit-source
    split — a prefix hit whose chain had spilled re-admits from host
    (one kv_readmit event per re-admitted hit), the rest serve
    straight from the device tree, and everything else prefilled cold
    (miss)."""
    if not (acc["spills"] or acc["readmits"] or migrate_ev):
        return None
    out: dict = {
        "spilled_blocks": acc["spilled_blocks"],
        "readmitted_blocks": acc["readmitted_blocks"],
        "migrations": len(migrate_ev),
        "migrated_blocks": sum(e.get("blocks", 0) for e in migrate_ev),
    }
    if migrate_ev:
        out["migration_paths"] = [
            {"source": e.get("source"), "target": e.get("target"),
             "blocks": e.get("blocks"), "chains": e.get("chains")}
            for e in migrate_ev]
    if n_term:
        out["hit_source"] = {
            "host": acc["readmits"],
            "device": max(n_hits - acc["readmits"], 0),
            "miss": max(n_term - n_hits, 0),
        }
    if snapshot is not None:
        occ = snapshot.get("metrics", {}).get(
            "serving_kv_tier_blocks_in_use")
        if occ is not None:
            out["tier_blocks_in_use"] = {
                f'{s["labels"].get("engine", "?")}'
                f'/{s["labels"].get("tier", "?")}': s["value"]
                for s in occ["series"]}
    return out


def _speculation_section(per_engine: Dict[str, dict],
                         fallbacks: List[dict], adjusts: List[dict],
                         swaps: List[dict]) -> Optional[dict]:
    """Speculative-decoding digest (ISSUE 15): per-engine accept rate
    and draft-overhead share streamed from the `spec_verify` round
    events (summarize accumulates them — verify rounds are per-token
    volume, never held), plus any `spec_fallback` degradations.
    `draft_overhead_share` is the fraction of draft proposals whose
    compute bought no token (wasted / proposed) — the price of
    misprediction; `tokens_per_round` is the amortization the verify
    pass achieved (1.0 = no better than target-only decode)."""
    if not (per_engine or fallbacks or adjusts or swaps):
        return None
    per_engine = {k: dict(v) for k, v in per_engine.items()}
    for eng in per_engine.values():
        prop = eng["proposed"]
        eng["accept_rate"] = (round(eng["accepted"] / prop, 4)
                              if prop else None)
        eng["draft_overhead_share"] = (
            round((prop - eng["accepted"]) / prop, 4) if prop else None)
        eng["tokens_per_round"] = (round(eng["emitted"] / eng["rounds"],
                                         4) if eng["rounds"] else None)
    out: dict = {"per_engine": dict(sorted(per_engine.items()))}
    if fallbacks:
        out["fallbacks"] = [{"engine": e.get("engine"),
                             "draft": e.get("draft_engine"),
                             "reason": e.get("reason")}
                            for e in fallbacks]
    if adjusts:
        # the adaptive-lookahead k-timeline (ISSUE 18): one entry per
        # ladder evaluation, in event order — obs_report's view of the
        # flywheel's k trajectory
        out["k_timeline"] = [
            {"engine": e.get("engine"), "round": e.get("round"),
             "k_from": e.get("k_from"), "k_to": e.get("k_to"),
             "accept": e.get("accept"),
             "suspended": e.get("suspended")}
            for e in adjusts]
    if swaps:
        # swap markers: accept_after is measured AFTER the event is
        # emitted, so pair each swap with its engine's NEXT ladder
        # evaluation (events are immutable)
        out["swaps"] = []
        for e in swaps:
            after = next(
                (a.get("accept") for a in adjusts
                 if a.get("engine") == e.get("engine")
                 and a.get("seq", 0) > e.get("seq", 0)), None)
            out["swaps"].append(
                {"engine": e.get("engine"),
                 "draft": e.get("draft_engine"),
                 "swap": e.get("swap"), "round": e.get("round"),
                 "source": e.get("source"),
                 "accept_before": e.get("accept_before"),
                 "accept_after": after})
    return out


def _checkpoint_section(events: List[dict],
                        snapshot: Optional[dict] = None
                        ) -> Optional[dict]:
    """Checkpoint digest (ISSUE 9): save cadence and durations from
    the enriched `checkpoint_save` events (`async`/`duration_s`/
    `shard`/`nshards` fields), load + corrupt-skip counts, and the
    `training_checkpoint_seconds` histogram of the last embedded
    metrics snapshot when one exists. Per-shard unit writes (events
    carrying a `shard` field) are tallied separately — the cadence and
    duration stats describe whole-checkpoint publishes only."""
    saves = [e for e in events if e.get("kind") == "checkpoint_save"]
    finals = [e for e in saves if "shard" not in e]
    units = [e for e in saves if "shard" in e]
    loads = [e for e in events if e.get("kind") == "checkpoint_load"]
    skipped = [e for e in events
               if e.get("kind") == "checkpoint_corrupt_skipped"]
    if not (saves or loads or skipped):
        return None
    out: dict = {"saves": len(finals), "loads": len(loads),
                 "corrupt_skipped": len(skipped)}
    if finals:
        out["async_saves"] = sum(1 for e in finals if e.get("async"))
        steps = sorted(e["step"] for e in finals
                       if isinstance(e.get("step"), (int, float)))
        gaps = [b - a for a, b in zip(steps, steps[1:]) if b > a]
        if gaps:
            out["save_cadence_steps"] = round(sum(gaps) / len(gaps), 2)
        durs = [e["duration_s"] for e in finals
                if isinstance(e.get("duration_s"), (int, float))]
        if durs:
            out["save_duration_p50_s"] = _pctl(durs, 0.50)
            out["save_duration_max_s"] = round(max(durs), 6)
    if units:
        out["shard_unit_writes"] = len(units)
        out["nshards"] = max(int(e.get("nshards", 1)) for e in units)
    if loads:
        out["sharded_loads"] = sum(1 for e in loads if e.get("sharded"))
    if snapshot is not None:
        fam = snapshot.get("metrics", {}).get(
            "training_checkpoint_seconds")
        if fam is not None:
            out["histogram"] = {
                s["labels"].get("mode", "?"): {
                    "count": s["count"],
                    "p50_s": quantile_from_buckets(
                        s["buckets"], s["counts"], 0.50),
                    "p95_s": quantile_from_buckets(
                        s["buckets"], s["counts"], 0.95)}
                for s in fam["series"]}
    return out


def _digest_snapshot(snapshot: dict) -> dict:
    """Counters/gauges verbatim; histograms → count/sum/p50/p95/p99."""
    out = {}
    for name, fam in sorted(snapshot.get("metrics", {}).items()):
        for s in fam["series"]:
            label = series_key(name, s["labels"])
            if fam["kind"] == "histogram":
                out[label] = {
                    "count": s["count"], "sum": round(s["sum"], 6),
                    "p50": quantile_from_buckets(
                        s["buckets"], s["counts"], 0.50),
                    "p95": quantile_from_buckets(
                        s["buckets"], s["counts"], 0.95),
                    "p99": quantile_from_buckets(
                        s["buckets"], s["counts"], 0.99)}
            else:
                out[label] = s["value"]
    return out


_SECTION_ROW_CAP = 24  # rendered rows per section table


def _capped(rows: List[tuple],
            cap: int = _SECTION_ROW_CAP) -> List[tuple]:
    """Cap a section's rendered rows with an HONEST footer naming how
    many were dropped (no-silent-caps) — a million-request run must
    not print a million per-engine lines, and must not pretend it
    printed them all either."""
    if len(rows) <= cap:
        return rows
    return rows[:cap] + [("…", f"{len(rows) - cap} more rows "
                               f"not shown")]


def _fmt_table(rows: List[tuple], indent: str = "  ") -> str:
    if not rows:
        return ""
    w = max(len(str(r[0])) for r in rows)
    return "\n".join(f"{indent}{str(k):<{w}}  {v}" for k, v in rows)


def render(events, tail: int = 15) -> str:
    """Render the report text from any event iterable (list or
    `obs.stream_jsonl` generator — one pass either way). Every
    section table is row-capped with an honest footer (_capped)."""
    s = summarize(events)
    lines = [f"telemetry report — {s['total_events']} events"]
    lines.append("\nevents by kind:")
    lines.append(_fmt_table(_capped(
        [(k + ("" if k in EVENT_KINDS else " [unregistered]"), n)
         for k, n in sorted(s["by_kind"].items())])))
    if "training" in s:
        t = s["training"]
        lines.append("\ntraining:")
        loss_txt = "n/a" if t["first_loss"] is None else \
            f"{t['first_loss']:.6g} -> {t['last_loss']:.6g}"
        lines.append(_fmt_table([
            ("steps", t["steps"]),
            ("loss", loss_txt),
            ("mean throughput", f"{t['mean_throughput']} rec/s"),
            ("updates applied", f"{t['updates_applied']}/{t['steps']}"),
            ("anomalies", t["anomalies"])]))
    if "serving" in s:
        v = s["serving"]
        lines.append("\nserving:")
        lines.append(_fmt_table(
            [("requests", v["requests"]),
             ("tokens generated", v["tokens_generated"]),
             ("degradations", v["degradations"]),
             ("rejected", v["rejected"])]
            + [(f"status {k}", n)
               for k, n in v["by_status"].items()]))
    if "slo" in s:
        def fmt_slo(d):
            def sec(v):
                return "-" if v is None else f"{v:.4g}s"
            gps = d["goodput_tokens_per_s"]
            return (f"done {d['done']}/{d['requests']}"
                    f"  goodput {d['goodput_tokens']} tok"
                    + (f" ({gps}/s)" if gps is not None else "")
                    + f"  ttft p50/p99 {sec(d['ttft_p50_s'])}"
                      f"/{sec(d['ttft_p99_s'])}"
                    + f"  per-tok {sec(d['per_token_p50_s'])}"
                      f"/{sec(d['per_token_p99_s'])}"
                    + f"  shed/exp/poison {d['shed_rate']}"
                      f"/{d['expired_rate']}/{d['poisoned_rate']}")
        lines.append("\nserving SLO:")
        rows = [("fleet", fmt_slo(s["slo"]["fleet"]))]
        for eng, d in s["slo"]["per_engine"].items():
            tag = eng
            if d.get("tp") is not None:
                tag += f" (tp={d['tp']}"
                tag += f", {d['role']})" if d.get("role") else ")"
            rows.append((tag, fmt_slo(d)))
        for layout, d in s["slo"].get("per_layout", {}).items():
            rows.append((layout, fmt_slo(d)))
        lines.append(_fmt_table(_capped(rows)))
    if "tenants" in s:
        lines.append("\ntenants:")
        rows = []
        for t, d in s["tenants"].items():
            thr = d.get("throttled", {})
            thr_txt = ("none" if not thr else
                       " ".join(f"{k}={n}"
                                for k, n in thr.items()))
            if d["requests"]:
                p99 = d.get("latency_p99_s")
                p99_txt = "-" if p99 is None else f"{p99:.4g}s"
                rows.append((t, f"done {d['done']}/{d['requests']}"
                                f"  goodput {d['goodput_tokens']} tok"
                                f"  p99 {p99_txt}"
                                f"  throttled {thr_txt}"))
            else:
                rows.append((t, f"no terminals  throttled {thr_txt}"))
        lines.append(_fmt_table(_capped(rows)))
    if "journeys" in s:
        lines.append("\nrequest journeys:")
        if "skipped" in s["journeys"]:
            lines.append(f"  skipped: {s['journeys']['skipped']}")
        else:
            jm = s["journeys"]["summary"]
            lines.append(_fmt_table([
                ("requests", jm["count"]),
                ("complete", jm["complete"]),
                ("cross-engine", jm["cross_engine"]),
                ("cross-layout", jm["cross_layout"]),
                ("max hops", jm["max_hops"]),
                ("lost hops", jm["lost_hops"]),
                ("superseded terminals", jm["superseded_terminals"])]))
            rows = []
            for j in s["journeys"]["table"][:20]:
                path = " -> ".join(
                    f"{h['engine'] or '?'}"
                    + (f"[tp{h['tp']}]"
                       if h["tp"] not in (None, 1) else "")
                    + (f"({h['dwell_s']:.3g}s)"
                       if h["dwell_s"] is not None else "")
                    for h in j["hops"])
                rows.append((j["trace"], f"{path} => {j['status']} "
                                         f"({j['tokens']} tok)"))
            # the digest table is itself capped — count BOTH cuts in
            # the footer so nothing is silently dropped
            more = (len(s["journeys"]["table"]) - 20
                    if len(s["journeys"]["table"]) > 20 else 0) \
                + s["journeys"].get("table_more", 0)
            if more:
                rows.append(("…", f"{more} more rows not shown"))
            lines.append(_fmt_table(rows))
    if "alerts" in s:
        al = s["alerts"]
        lines.append("\nalerts / SLO:")
        rows = []
        for obj, o in al["objectives"].items():
            comp = ("-" if o["compliant_frac"] is None
                    else f"{o['compliant_frac']:.2%}")
            extra = (f", {o['still_firing']} still firing"
                     if o["still_firing"] else "")
            rows.append((obj, f"{o['alerts']} alert(s), "
                              f"{o['time_firing_s']}s firing, "
                              f"compliant {comp}{extra}"))
        for rec in al["timeline"]:
            state = ("resolved after "
                     f"{rec['firing_s']}s" if rec["firing_s"]
                     is not None else "STILL FIRING")
            rows.append((
                f"{rec['alert']} @ {rec['fired_ts']}",
                f"{rec['objective']} value {rec['value']} > target "
                f"{rec['target']} (window {rec['window_s']}s, "
                f"{rec['rule_kind']}) -> {state}"))
        for b in al.get("bundles", []):
            rows.append((b, "post-mortem bundle (slo_burn)"))
        lines.append(_fmt_table(_capped(rows)))
    if "incidents" in s:
        inc = s["incidents"]
        lines.append("\nincidents (flight recorder):")
        rows = [(f"{k}", n) for k, n in inc["by_incident"].items()]
        rows += [(b["bundle"],
                  f"{b['incident']} @ {b['component']} "
                  f"(trigger {b['trigger_kind']})")
                 for b in inc["bundles"]]
        lines.append(_fmt_table(_capped(rows)))
    if "prefix" in s:
        p = s["prefix"]
        lines.append("\nprefix cache:")
        rows = [(k, v) for k, v in p.items()
                if k != "pool_blocks_in_use"]
        if "pool_blocks_in_use" in p:
            rows += [(f"pool in use [{eng}]", v)
                     for eng, v in p["pool_blocks_in_use"].items()]
        lines.append(_fmt_table(_capped(rows)))
    if "kv_tier" in s:
        kt = s["kv_tier"]
        lines.append("\nkv tier (host spill):")
        rows = [("spilled blocks", kt["spilled_blocks"]),
                ("re-admitted blocks", kt["readmitted_blocks"]),
                ("migrations", kt["migrations"]),
                ("migrated blocks", kt["migrated_blocks"])]
        if "hit_source" in kt:
            hs = kt["hit_source"]
            rows.append(("hit source",
                         f"device {hs['device']} / host {hs['host']}"
                         f" / miss {hs['miss']}"))
        for path in kt.get("migration_paths", []):
            rows.append((f"{path['source']} -> {path['target']}",
                         f"{path['blocks']} blocks "
                         f"({path['chains']} chains)"))
        for key, v in sorted(kt.get("tier_blocks_in_use",
                                    {}).items()):
            rows.append((f"tier in use [{key}]", v))
        lines.append(_fmt_table(_capped(rows)))
    if "speculation" in s:
        sp = s["speculation"]
        lines.append("\nspeculative decoding:")
        rows = []
        for eng, d in sp["per_engine"].items():
            ar = "-" if d["accept_rate"] is None \
                else f"{d['accept_rate']:.2%}"
            oh = "-" if d["draft_overhead_share"] is None \
                else f"{d['draft_overhead_share']:.2%}"
            rows.append((f"{eng} (draft {d['draft']})",
                         f"{d['rounds']} rounds, accept {ar}, "
                         f"{d['tokens_per_round']} tok/round, "
                         f"draft overhead {oh}"))
        for f in sp.get("fallbacks", []):
            rows.append((f"{f['engine']} FALLBACK",
                         f"draft {f['draft']} lost: {f['reason']}"))
        for w in sp.get("swaps", []):
            aft = "-" if w["accept_after"] is None \
                else f"{w['accept_after']:.2%}"
            bef = "-" if w["accept_before"] is None \
                else f"{w['accept_before']:.2%}"
            rows.append((f"{w['engine']} SWAP #{w['swap']}",
                         f"round {w['round']} ({w['source']}): "
                         f"accept {bef} -> {aft}"))
        lines.append(_fmt_table(_capped(rows)))
        if sp.get("k_timeline"):
            kt = sp["k_timeline"]
            traj = " ".join(
                f"{e['k_from']}->{e['k_to']}"
                + ("S" if e.get("suspended") else "")
                for e in kt[:24])
            if len(kt) > 24:
                traj += f" … (+{len(kt) - 24} more)"
            lines.append(f"  k-timeline ({len(kt)} evaluations): "
                         f"{traj}")
    if "faults" in s:
        lines.append("\ninjected faults: " + ", ".join(s["faults"]))
    if "checkpoints" in s:
        c = s["checkpoints"]
        lines.append("\ncheckpoints:")
        rows = [(k, v) for k, v in sorted(c.items())
                if k != "histogram"]
        for mode, h in sorted(c.get("histogram", {}).items()):
            def sec(v):
                return "-" if v is None else f"{v * 1e3:.3g}ms"
            rows.append((f"{mode} save (hist)",
                         f"n={h['count']} p50/p95="
                         f"{sec(h['p50_s'])}/{sec(h['p95_s'])}"))
        lines.append(_fmt_table(_capped(rows)))
    if "metrics" in s:
        lines.append("\nmetrics (last snapshot):")
        rows = []
        for k, v in s["metrics"].items():
            if isinstance(v, dict):
                pcts = "/".join(
                    "-" if v[p] is None else f"{v[p] * 1e3:.3g}ms"
                    for p in ("p50", "p95", "p99"))
                rows.append((k, f"n={v['count']} sum={v['sum']}s "
                                f"p50/p95/p99={pcts}"))
            else:
                rows.append((k, v))
        lines.append(_fmt_table(_capped(rows, cap=64)))
    tail_events = s.get("timeline_tail", [])
    if tail and tail_events:
        shown = tail_events[-min(tail, len(tail_events)):]
        lines.append(f"\ntimeline (last {len(shown)} of "
                     f"{s['total_events']}):")
        rows = []
        for e in shown:
            extra = {k: v for k, v in e.items()
                     if k not in ("schema", "ts", "seq", "kind",
                                  "snapshot")}
            rows.append((f"[{e.get('seq', '?')}] {e.get('kind')}",
                         " ".join(f"{k}={v}" for k, v in extra.items())))
        lines.append(_fmt_table(rows))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="JSONL event file (EventLog sink / "
                                 "BIGDL_OBS_EVENTS)")
    ap.add_argument("--tail", type=int, default=15,
                    help="timeline tail length (0 disables)")
    ap.add_argument("--perfetto", default=None, metavar="PATH",
                    help="also export the reconstructed request "
                         "journeys as a Perfetto/chrome-trace JSON "
                         "(one track per request, obs/journey.py)")
    args = ap.parse_args(argv)
    from bigdl_tpu.obs.events import stream_jsonl

    # stream, never materialize: a 10⁶-event sim run summarizes in
    # one pass with bounded holds (ISSUE 20)
    try:
        text = render(stream_jsonl(args.path), tail=args.tail)
    except OSError as e:
        print(f"obs-report: cannot read {args.path}: {e}")
        return 2
    if text.startswith("telemetry report — 0 events"):
        print(f"obs-report: no events in {args.path}")
        return 2
    print(text)
    if args.perfetto:
        import json as _json

        from bigdl_tpu.obs.journey import build_journeys, to_perfetto

        # second streaming pass: only the trace-stamped lifecycle
        # events feed the journey builder
        trace_events = [e for e in stream_jsonl(args.path)
                        if e.get("trace") is not None]
        with open(args.perfetto, "w") as f:
            _json.dump(to_perfetto(build_journeys(trace_events)), f)
        print(f"\nperfetto journey tracks -> {args.perfetto}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
