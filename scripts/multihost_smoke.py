"""Multi-process multi-host smoke + failure-recovery test on CPU.

Reference parity: the reference proves its distributed plane without a
cluster by running Spark `local[N]` (SURVEY.md §4 "Distributed-without-
a-cluster"); the TPU-native equivalent is N real `jax.distributed`
processes × M virtual CPU devices each — the same code path a v5e pod
runs (PJRT process group, global mesh, cross-process collectives),
minus the ICI.

Leg 1 (smoke): 2 procs × 4 devices, DP/ZeRO-1 training through
Optimizer.set_mesh → DistriOptimizer with per-host sharded data,
checkpoint + in-process resume, digests identical across processes.

Leg 2 (kill/resume — SURVEY §5.3, reference anchor DistriOptimizer
retry/getLatestFile): 4 procs × 2 devices. An uninterrupted 12-step
run records a sha256 parameter digest; a second run is SIGKILLed
mid-training (one worker first — the pod failure model: one host dies,
the synchronous collective wedges the rest, the launcher reaps the
job), then ALL processes restart with --resume and reload the latest
atomic checkpoint. Digests must be bit-identical to the uninterrupted
run on every process.

Leg 3 (ckpt_corrupt — ISSUE 1 verified checkpoint integrity): same
4×2 job, killed once checkpoint-6 publishes; the newest checkpoint's
model.npz is truncated on disk before the restart. Every process must
detect the damage (per-array checksums, serialization/checkpoint.py),
fall back to the newest VALID checkpoint, and finish bit-identical to
the uninterrupted run.

Leg 4 (zero2_resume — ISSUE 9 preemption-tolerant training plane):
2 procs × 4 devices with `set_mesh(zero=2)` (master fp32 weights
sharded across all 8 devices) and `set_checkpoint(sharded=True,
async_save=True)` — each host background-writes ONLY the shard units
its devices own, host 0 publishes MANIFEST.json last. One worker is
SIGKILLed after the first sharded checkpoint publishes (the
collective wedges, the launcher reaps the job — a preempted-host
model with possibly-torn in-flight saves on disk); the full restart
with --resume must reshard the published checkpoint and finish
bit-identical to the uninterrupted zero2 run.

    python scripts/multihost_smoke.py          # all legs
"""

import argparse
import json
import os
import subprocess
import sys

NUM_PROCESSES = 2
DEVICES_PER_PROC = 4
PORT = 12000 + (os.getpid() % 2000)  # avoid collisions across runs


def child(args):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count="
                               f"{args.devices_per_proc}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    # intentional inline copy of utils/engine.ensure_cpu_platform:
    # this runs before bigdl_tpu is importable (or with conditional
    # platform logic)
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    # the product bring-up path (utils/Engine.scala#Engine.init parity):
    # BIGDL_* env vars are what scripts/launch_pod.sh exports
    os.environ["BIGDL_COORDINATOR"] = f"localhost:{args.port}"
    os.environ["BIGDL_NUM_PROCESSES"] = str(args.num_processes)
    os.environ["BIGDL_PROCESS_ID"] = str(args.process_id)
    from bigdl_tpu.utils.engine import Engine

    Engine.init_distributed()
    assert jax.process_count() == args.num_processes, jax.process_count()
    assert jax.device_count() == (args.num_processes
                                  * args.devices_per_proc)

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import Adam, Optimizer, Trigger, Loss
    from bigdl_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)  # same data on every host, sharded below
    X = (rng.randn(128, 8).astype(np.float32) +
         np.repeat(np.eye(4, 8) * 3, 32, 0).astype(np.float32))
    Y = np.repeat(np.arange(4), 32)
    elements = [Sample(X[i], int(Y[i])) for i in range(128)]
    dataset = DataSet.sharded(elements, seed=3)      # per-process shard
    # 33 samples -> shards of 17 and 16: with local batches of 16 one
    # host runs 2 eval rounds, the other 1 — exercises the uneven-shard
    # equalization in DistriOptimizer._validate_mesh (no deadlock)
    val = DataSet.sharded(elements[:33], seed=3)

    def build():
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                             nn.Linear(16, 4),
                             nn.LogSoftMax()).build(jax.random.PRNGKey(0))

    mesh = make_mesh({"data": jax.device_count()})
    ckpt = os.path.join(args.workdir, "ckpt")

    def train(end_iter, resume):
        opt = (Optimizer(build(), dataset, nn.ClassNLLCriterion(),
                         batch_size=32)                # GLOBAL batch
               .set_optim_method(Adam(learningrate=1e-2))
               .set_gradient_accumulation(2)
               .set_end_when(Trigger.max_iteration(end_iter))
               .set_validation(Trigger.several_iteration(3), val,
                               [Loss(nn.ClassNLLCriterion())], 32)
               .set_checkpoint(ckpt, Trigger.several_iteration(3),
                               sharded=args.zero2, async_save=args.zero2)
               .set_mesh(mesh, zero=2 if args.zero2 else 1))
        if resume:
            opt.resume_from_checkpoint()
        return opt.optimize(), opt

    if args.leg == "smoke":
        m1, _ = train(3, resume=False)   # 3 steps + checkpoint
        m2, opt = train(6, resume=True)  # resume, 3 more steps
    else:  # kill_resume: one uninterrupted (or resumed) run to the end
        m2, opt = train(args.end_iter, resume=args.resume)

    flat = np.concatenate([np.ravel(np.asarray(a, np.float32))
                           for _, a in m2.parameters()])
    assert np.isfinite(flat).all(), "non-finite parameters"

    # parameters must be IDENTICAL across processes (replicated plane):
    # compare digests via the filesystem. sha256 of the raw bytes is the
    # bit-identity check; the float sum stays for human logs.
    import hashlib

    digest = float(np.sum(np.abs(flat)))
    sha = hashlib.sha256(flat.tobytes()).hexdigest()
    out = {"process_id": args.process_id, "digest": digest,
           "sha256": sha,
           "processes": jax.process_count(),
           "devices": jax.device_count(),
           "checkpoint_resumed": args.leg == "smoke" or args.resume,
           # recovery provenance for the ckpt_corrupt leg: which dir
           # the resume actually loaded, and which it skipped as
           # corrupt (serialization/checkpoint.py fallback)
           "resumed_from": os.path.basename(
               opt.checkpoint._last_loaded or "") if args.resume else None,
           "corrupt_skipped": [os.path.basename(d) for d
                               in opt.checkpoint.corrupt_skipped]}
    with open(os.path.join(args.workdir, f"proc{args.process_id}.json"),
              "w") as f:
        json.dump(out, f)
    print(f"[proc {args.process_id}] OK digest={digest:.6f} sha={sha[:12]}")


def _spawn_group(leg, n_procs, devices_per_proc, port, workdir,
                 end_iter=6, resume=False, zero2=False):
    procs = []
    for pid in range(n_procs):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--process-id", str(pid), "--num-processes", str(n_procs),
               "--devices-per-proc", str(devices_per_proc),
               "--port", str(port), "--workdir", workdir,
               "--leg", leg, "--end-iter", str(end_iter)]
        if resume:
            cmd.append("--resume")
        if zero2:
            cmd.append("--zero2")
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT))
    return procs


def _reap(procs, timeout=420):
    try:
        outs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    except subprocess.TimeoutExpired:
        # a hung child must not leak (it holds the coordinator port)
        for p in procs:
            p.kill()
        outs = [p.communicate()[0].decode() for p in procs]
    codes = [p.returncode for p in procs]
    for pid, (c, o) in enumerate(zip(codes, outs)):
        if c != 0:
            print(f"--- proc {pid} (rc={c}) ---\n{o[-2000:]}")
    return codes


def _collect(workdir, n_procs):
    digests, shas = [], []
    for pid in range(n_procs):
        with open(os.path.join(workdir, f"proc{pid}.json")) as f:
            d = json.load(f)
        digests.append(d["digest"])
        shas.append(d["sha256"])
    return digests, shas


def _leg_smoke(port):
    import tempfile

    workdir = tempfile.mkdtemp(prefix="multihost_smoke_")
    procs = _spawn_group("smoke", NUM_PROCESSES, DEVICES_PER_PROC, port,
                         workdir)
    codes = _reap(procs)
    ok = all(c == 0 for c in codes)
    digests = []
    if ok:
        digests, _ = _collect(workdir, NUM_PROCESSES)
        ok = len(set(digests)) == 1
    return {"ok": ok, "processes": NUM_PROCESSES,
            "devices_per_process": DEVICES_PER_PROC,
            "return_codes": codes, "digests": digests,
            "steps": 6, "grad_accum": 2, "checkpoint_resume": True}


def _leg_kill_resume(port):
    """4-process job, one worker SIGKILLed mid-training, full restart
    with --resume: parameter sha256 must equal the uninterrupted run's
    on every process."""
    import tempfile
    import time

    n, dpp, end = 4, 2, 12
    # uninterrupted reference run
    wd_ref = tempfile.mkdtemp(prefix="multihost_ref_")
    codes_ref = _reap(_spawn_group("kill_resume", n, dpp, port, wd_ref,
                                   end_iter=end))
    if any(c != 0 for c in codes_ref):
        return {"ok": False, "stage": "reference", "return_codes": codes_ref}
    _, shas_ref = _collect(wd_ref, n)

    # interrupted run: kill worker 2 as soon as the FIRST checkpoint
    # (checkpoint-3 of 12 steps) is published — earliest point where a
    # resume is possible, widest remaining-training window for the kill
    # to land mid-run. Poll fast: the whole CPU job takes seconds.
    wd = tempfile.mkdtemp(prefix="multihost_kill_")
    procs = _spawn_group("kill_resume", n, dpp, port + 1, wd,
                         end_iter=end)
    ckdir = os.path.join(wd, "ckpt")
    marker = os.path.join(ckdir, "checkpoint-3")
    deadline = time.time() + 300
    saw_ckpt = False
    while time.time() < deadline:
        if os.path.isdir(marker):
            saw_ckpt = True
            break
        if any(p.poll() is not None for p in procs):
            break  # someone already exited — fail below
        time.sleep(0.05)
    killed_mid_training = False
    latest_at_kill = None
    if saw_ckpt and all(p.poll() is None for p in procs):
        procs[2].kill()              # the dying host
        killed_mid_training = True
        import re
        published = [d for d in os.listdir(ckdir)
                     if re.fullmatch(r"checkpoint-(\d+)", d)]
        latest_at_kill = max(published,
                             key=lambda d: int(d.split("-")[1]))
        time.sleep(5)                # collective wedges; reap the job
    for p in procs:
        if p.poll() is None:
            p.kill()
    _reap(procs, timeout=30)
    if not killed_mid_training:
        return {"ok": False, "stage": "kill",
                "detail": "training finished (or a worker exited) before "
                          "the kill could land after checkpoint-3 — "
                          "no mid-training recovery was exercised"}

    # full restart with --resume: reload latest checkpoint, finish
    codes_res = _reap(_spawn_group("kill_resume", n, dpp, port + 2, wd,
                                   end_iter=end, resume=True))
    if any(c != 0 for c in codes_res):
        return {"ok": False, "stage": "resume", "return_codes": codes_res}
    _, shas_res = _collect(wd, n)

    ok = (len(set(shas_res)) == 1 and len(set(shas_ref)) == 1
          and shas_res[0] == shas_ref[0])
    return {"ok": ok, "processes": n, "devices_per_process": dpp,
            "steps": end, "killed_process": 2,
            "latest_checkpoint_at_kill": latest_at_kill,
            "sha256_uninterrupted": shas_ref[0][:16],
            "sha256_resumed": shas_res[0][:16],
            "bit_identical": ok}


def _leg_ckpt_corrupt(port):
    """kill_resume variant for checkpoint INTEGRITY (ISSUE 1): the whole
    job is killed once checkpoint-6 publishes, the newest checkpoint's
    model arrays are truncated on disk (torn flush / bit rot), and the
    restart must detect the damage (per-array checksums + zip
    structure), fall back to the newest VALID checkpoint on every
    process, and still finish bit-identical to the uninterrupted run."""
    import re
    import tempfile
    import time

    n, dpp, end = 4, 2, 12
    wd_ref = tempfile.mkdtemp(prefix="multihost_ckref_")
    codes_ref = _reap(_spawn_group("kill_resume", n, dpp, port, wd_ref,
                                   end_iter=end))
    if any(c != 0 for c in codes_ref):
        return {"ok": False, "stage": "reference", "return_codes": codes_ref}
    _, shas_ref = _collect(wd_ref, n)

    # run until two checkpoints exist (3 and 6), then kill the job
    wd = tempfile.mkdtemp(prefix="multihost_ckcorrupt_")
    procs = _spawn_group("kill_resume", n, dpp, port + 1, wd,
                         end_iter=end)
    ckdir = os.path.join(wd, "ckpt")
    marker = os.path.join(ckdir, "checkpoint-6")
    deadline = time.time() + 300
    saw = False
    while time.time() < deadline:
        if os.path.isdir(marker):
            saw = True
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.05)
    for p in procs:
        p.kill()
    _reap(procs, timeout=30)
    if not saw:
        return {"ok": False, "stage": "kill",
                "detail": "checkpoint-6 never appeared (or a worker "
                          "exited first) — nothing to corrupt"}

    # truncate the newest published checkpoint's model arrays (inline —
    # the launcher stays free of jax imports; same damage model as
    # utils.faults.corrupt_file 'truncate')
    published = sorted(
        (d for d in os.listdir(ckdir)
         if re.fullmatch(r"checkpoint-(\d+)", d)),
        key=lambda d: int(d.split("-")[1]))
    newest, expect_fallback = published[-1], published[-2]
    npz = os.path.join(ckdir, newest, "model.npz")
    size = os.path.getsize(npz)
    with open(npz, "r+b") as f:
        f.truncate(max(size // 2, 1))

    codes_res = _reap(_spawn_group("kill_resume", n, dpp, port + 2, wd,
                                   end_iter=end, resume=True))
    if any(c != 0 for c in codes_res):
        return {"ok": False, "stage": "resume", "return_codes": codes_res}
    _, shas_res = _collect(wd, n)
    resumed_from, skipped = [], []
    for pid in range(n):
        with open(os.path.join(wd, f"proc{pid}.json")) as f:
            d = json.load(f)
        resumed_from.append(d.get("resumed_from"))
        skipped.append(d.get("corrupt_skipped", []))
    fell_back = (all(r == expect_fallback for r in resumed_from)
                 and all(newest in s for s in skipped))
    ok = (fell_back and len(set(shas_res)) == 1
          and len(set(shas_ref)) == 1 and shas_res[0] == shas_ref[0])
    return {"ok": ok, "processes": n, "devices_per_process": dpp,
            "steps": end, "corrupted": newest,
            "resumed_from": resumed_from[0],
            "fell_back_on_every_process": fell_back,
            "sha256_uninterrupted": shas_ref[0][:16],
            "sha256_resumed": shas_res[0][:16],
            "bit_identical": shas_res[0] == shas_ref[0]}


def _leg_zero2_resume(port):
    """ISSUE 9: kill/resume over the FULL elastic-training plane —
    ZeRO-2 weight sharding across both hosts' devices, each host
    background-writing only its own shard units, manifest-last
    publish. One worker SIGKILLed after the first sharded checkpoint
    publishes; full restart with --resume must finish bit-identical
    to the uninterrupted zero2 run."""
    import re
    import tempfile
    import time

    n, dpp, end = 2, 4, 12
    wd_ref = tempfile.mkdtemp(prefix="multihost_z2ref_")
    codes_ref = _reap(_spawn_group("kill_resume", n, dpp, port, wd_ref,
                                   end_iter=end, zero2=True))
    if any(c != 0 for c in codes_ref):
        return {"ok": False, "stage": "reference",
                "return_codes": codes_ref}
    _, shas_ref = _collect(wd_ref, n)

    wd = tempfile.mkdtemp(prefix="multihost_z2kill_")
    procs = _spawn_group("kill_resume", n, dpp, port + 1, wd,
                         end_iter=end, zero2=True)
    ckdir = os.path.join(wd, "ckpt")
    # a sharded checkpoint only EXISTS once MANIFEST.json lands (the
    # manifest-last publish point) — the dir alone is a torn save
    marker = os.path.join(ckdir, "checkpoint-3", "MANIFEST.json")
    deadline = time.time() + 300
    saw_ckpt = False
    while time.time() < deadline:
        if os.path.exists(marker):
            saw_ckpt = True
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.05)
    killed = False
    if saw_ckpt and all(p.poll() is None for p in procs):
        procs[1].kill()              # the preempted host
        killed = True
        time.sleep(5)                # collective wedges; reap the job
    for p in procs:
        if p.poll() is None:
            p.kill()
    _reap(procs, timeout=30)
    if not killed:
        return {"ok": False, "stage": "kill",
                "detail": "no published sharded checkpoint before the "
                          "job ended — nothing to resume from"}
    published = [d for d in os.listdir(ckdir)
                 if re.fullmatch(r"checkpoint-(\d+)", d)
                 and os.path.exists(os.path.join(ckdir, d,
                                                 "MANIFEST.json"))]
    shard_units = [f for f in os.listdir(os.path.join(
        ckdir, "checkpoint-3")) if f.startswith("optim-shard")
        and f.endswith(".npz")]

    codes_res = _reap(_spawn_group("kill_resume", n, dpp, port + 2, wd,
                                   end_iter=end, resume=True,
                                   zero2=True))
    if any(c != 0 for c in codes_res):
        return {"ok": False, "stage": "resume",
                "return_codes": codes_res}
    _, shas_res = _collect(wd, n)
    ok = (len(set(shas_res)) == 1 and len(set(shas_ref)) == 1
          and shas_res[0] == shas_ref[0] and len(shard_units) == 8)
    return {"ok": ok, "processes": n, "devices_per_process": dpp,
            "steps": end, "zero": 2, "killed_process": 1,
            "sharded_checkpoints_at_kill": sorted(published),
            "shard_units_in_first_ckpt": len(shard_units),
            "sha256_uninterrupted": shas_ref[0][:16],
            "sha256_resumed": shas_res[0][:16],
            "bit_identical": shas_res[0] == shas_ref[0]}


def launcher(legs):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTIHOST.json")
    # merge-preserving: running a subset of legs keeps the other legs'
    # last recorded results in the artifact
    result = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                result = json.load(f)
        except Exception:
            result = {}
    ok = True
    if "smoke" in legs:
        smoke = _leg_smoke(PORT)
        prev = {k: result[k] for k in ("kill_resume", "ckpt_corrupt",
                                       "zero2_resume")
                if k in result}
        result = dict(smoke)  # legacy top-level shape for leg 1
        result.update(prev)
        ok = ok and smoke["ok"]
    if "kill_resume" in legs:
        kill = _leg_kill_resume(PORT + 10)
        result["kill_resume"] = kill
        ok = ok and kill.get("ok", False)
    if "ckpt_corrupt" in legs:
        corrupt = _leg_ckpt_corrupt(PORT + 20)
        result["ckpt_corrupt"] = corrupt
        ok = ok and corrupt.get("ok", False)
    if "zero2_resume" in legs:
        z2 = _leg_zero2_resume(PORT + 30)
        result["zero2_resume"] = z2
        ok = ok and z2.get("ok", False)
    result["ok"] = bool(ok and result.get("ok", True))
    with open(path, "w") as f:
        json.dump(result, f)
    print(json.dumps(result))
    sys.exit(0 if ok else 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--num-processes", type=int, default=NUM_PROCESSES)
    ap.add_argument("--devices-per-proc", type=int,
                    default=DEVICES_PER_PROC)
    ap.add_argument("--port", type=int, default=PORT)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--leg", default="smoke",
                    choices=["smoke", "kill_resume"])
    ap.add_argument("--legs",
                    default="smoke,kill_resume,ckpt_corrupt,zero2_resume",
                    help="launcher mode: comma subset of legs to run")
    ap.add_argument("--end-iter", type=int, default=6)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--zero2", action="store_true",
                    help="child mode: ZeRO-2 weight sharding + sharded "
                         "async checkpoints (ISSUE 9)")
    args = ap.parse_args()
    if args.process_id is None:
        launcher(set(args.legs.split(",")))
    else:
        child(args)


if __name__ == "__main__":
    main()
