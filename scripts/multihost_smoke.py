"""Multi-process multi-host smoke test on CPU (no cluster needed).

Reference parity: the reference proves its distributed plane without a
cluster by running Spark `local[N]` (SURVEY.md §4 "Distributed-without-
a-cluster"); the TPU-native equivalent is N real `jax.distributed`
processes × M virtual CPU devices each — the same code path a v5e pod
runs (PJRT process group, global mesh, cross-process collectives),
minus the ICI.

Launcher mode (no --process-id): spawns NUM_PROCESSES children of this
script, waits, and writes MULTIHOST.json. Child mode: initializes the
process group through Engine.init_distributed (the product path), runs
DP/ZeRO-1 training steps through Optimizer.set_mesh → DistriOptimizer
with per-host sharded data, checkpoints, resumes, and verifies losses
are finite and identical across processes.

    python scripts/multihost_smoke.py          # 2 procs x 4 devices
"""

import argparse
import json
import os
import subprocess
import sys

NUM_PROCESSES = 2
DEVICES_PER_PROC = 4
PORT = 12000 + (os.getpid() % 2000)  # avoid collisions across runs


def child(args):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count="
                               f"{DEVICES_PER_PROC}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    # the product bring-up path (utils/Engine.scala#Engine.init parity):
    # BIGDL_* env vars are what scripts/launch_pod.sh exports
    os.environ["BIGDL_COORDINATOR"] = f"localhost:{args.port}"
    os.environ["BIGDL_NUM_PROCESSES"] = str(args.num_processes)
    os.environ["BIGDL_PROCESS_ID"] = str(args.process_id)
    from bigdl_tpu.utils.engine import Engine

    Engine.init_distributed()
    assert jax.process_count() == args.num_processes, jax.process_count()
    assert jax.device_count() == args.num_processes * DEVICES_PER_PROC

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import Adam, Optimizer, Trigger, Loss
    from bigdl_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)  # same data on every host, sharded below
    X = (rng.randn(128, 8).astype(np.float32) +
         np.repeat(np.eye(4, 8) * 3, 32, 0).astype(np.float32))
    Y = np.repeat(np.arange(4), 32)
    elements = [Sample(X[i], int(Y[i])) for i in range(128)]
    dataset = DataSet.sharded(elements, seed=3)      # per-process shard
    # 33 samples -> shards of 17 and 16: with local batches of 16 one
    # host runs 2 eval rounds, the other 1 — exercises the uneven-shard
    # equalization in DistriOptimizer._validate_mesh (no deadlock)
    val = DataSet.sharded(elements[:33], seed=3)

    def build():
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                             nn.Linear(16, 4),
                             nn.LogSoftMax()).build(jax.random.PRNGKey(0))

    mesh = make_mesh({"data": jax.device_count()})
    ckpt = os.path.join(args.workdir, "ckpt")

    def train(end_iter, resume):
        opt = (Optimizer(build(), dataset, nn.ClassNLLCriterion(),
                         batch_size=32)                # GLOBAL batch
               .set_optim_method(Adam(learningrate=1e-2))
               .set_gradient_accumulation(2)
               .set_end_when(Trigger.max_iteration(end_iter))
               .set_validation(Trigger.several_iteration(3), val,
                               [Loss(nn.ClassNLLCriterion())], 32)
               .set_checkpoint(ckpt, Trigger.several_iteration(3))
               .set_mesh(mesh))
        if resume:
            opt.resume_from_checkpoint()
        return opt.optimize()

    m1 = train(3, resume=False)       # 3 steps + checkpoint
    m2 = train(6, resume=True)        # resume, 3 more steps

    flat = np.concatenate([np.ravel(np.asarray(a))
                           for _, a in m2.parameters()])
    assert np.isfinite(flat).all(), "non-finite parameters"

    # parameters must be IDENTICAL across processes (replicated plane):
    # compare a digest via the filesystem
    digest = float(np.sum(np.abs(flat)))
    out = {"process_id": args.process_id, "digest": digest,
           "processes": jax.process_count(),
           "devices": jax.device_count(),
           "checkpoint_resumed": True}
    with open(os.path.join(args.workdir, f"proc{args.process_id}.json"),
              "w") as f:
        json.dump(out, f)
    print(f"[proc {args.process_id}] OK digest={digest:.6f}")


def launcher():
    import tempfile

    workdir = tempfile.mkdtemp(prefix="multihost_smoke_")
    procs = []
    for pid in range(NUM_PROCESSES):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process-id", str(pid),
             "--num-processes", str(NUM_PROCESSES),
             "--port", str(PORT), "--workdir", workdir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    try:
        outs = [p.communicate(timeout=420)[0].decode() for p in procs]
    except subprocess.TimeoutExpired:
        # a hung child must not leak (it holds the coordinator port)
        for p in procs:
            p.kill()
        outs = [p.communicate()[0].decode() for p in procs]
    codes = [p.returncode for p in procs]
    for pid, (c, o) in enumerate(zip(codes, outs)):
        if c != 0:
            print(f"--- proc {pid} (rc={c}) ---\n{o}")
    ok = all(c == 0 for c in codes)
    digests = []
    if ok:
        for pid in range(NUM_PROCESSES):
            with open(os.path.join(workdir, f"proc{pid}.json")) as f:
                digests.append(json.load(f)["digest"])
        ok = len(set(digests)) == 1
    result = {"ok": ok, "processes": NUM_PROCESSES,
              "devices_per_process": DEVICES_PER_PROC,
              "return_codes": codes, "digests": digests,
              "steps": 6, "grad_accum": 2, "checkpoint_resume": True}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTIHOST.json")
    with open(path, "w") as f:
        json.dump(result, f)
    print(json.dumps(result))
    sys.exit(0 if ok else 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--num-processes", type=int, default=NUM_PROCESSES)
    ap.add_argument("--port", type=int, default=PORT)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()
    if args.process_id is None:
        launcher()
    else:
        child(args)


if __name__ == "__main__":
    main()
