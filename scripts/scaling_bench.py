"""DP weak-scaling harness — the ≥90% AllReduce-scaling north star
(BASELINE.json "north_star"; VERDICT r3 weak item 4).

Measures, for mesh sizes 1, 2, 4, … N on whatever devices exist:
  - weak-scaled DP training step time (per-chip batch held constant, so
    perfect scaling = flat step time; efficiency_N = t_1 / t_N),
  - the gradient collective alone (reduce-scatter + all-gather at the
    flat-parameter size, the exact shape DistriOptimizer issues),
  - the analytic ring bound for that collective on the ICI
    (2·(N−1)/N · bytes / link_bw), and the north-star check
    efficiency ≥ 0.9.

Emits one JSON line per mesh size and a final summary line.

On real hardware (a pod slice) the numbers are the measurement; on the
virtual CPU mesh (--xla_force_host_platform_device_count) the absolute
times are meaningless but every code path — mesh construction, sharding,
collectives, efficiency math, JSON contract — runs, so pod time is spent
measuring, not debugging (CI covers it in tests/test_scaling_bench.py).

Usage:
    python scripts/scaling_bench.py                  # all local devices
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/scaling_bench.py --model mlp  # plumbing check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    from bigdl_tpu.utils.engine import ensure_cpu_platform

    ensure_cpu_platform()

# TPU v5e ICI: ~400 GB/s aggregate off-chip bandwidth per chip
# (2 links/axis bidirectional). Override per topology with --ici-gbps.
DEFAULT_ICI_GBPS = 400.0


def build_model(name):
    from bigdl_tpu import nn
    from bigdl_tpu.models import resnet

    if name == "resnet50":
        return resnet.build_imagenet(50, 1000), (224, 224, 3), 1000
    if name == "resnet8":
        return resnet.build_cifar(8, 10), (32, 32, 3), 10
    # tiny mlp: fastest plumbing check
    return (nn.Sequential(nn.Reshape([64]), nn.Linear(64, 128), nn.ReLU(),
                          nn.Linear(128, 10), nn.LogSoftMax()),
            (8, 8, 1), 10)


def measure_mesh(n, model_name, per_chip_batch, iters, ici_gbps):
    """One mesh size: DP step time + collective-only time + bounds."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel import (FlatParamSpec, make_dp_train_step,
                                    make_mesh)
    from bigdl_tpu.utils.precision import DEFAULT_MIXED

    devices = jax.devices()[:n]
    mesh = make_mesh({"data": n}, devices=devices)
    model, shape, classes = build_model(model_name)
    variables = model.init(jax.random.PRNGKey(0))
    method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0)
    spec = FlatParamSpec(variables["params"], n)

    step = make_dp_train_step(model, nn.ClassNLLCriterion(), method, mesh,
                              spec, axis="data", grad_dtype="bfloat16",
                              precision=DEFAULT_MIXED)
    replicated = NamedSharding(mesh, P())
    batch = per_chip_batch * n
    rng = np.random.RandomState(0)
    pool = [(jax.device_put(
                 rng.rand(batch, *shape).astype(np.float32),
                 NamedSharding(mesh, P("data", None, None, None))),
             jax.device_put(
                 rng.randint(0, classes, batch).astype(np.int32),
                 NamedSharding(mesh, P("data"))))
            for _ in range(2)]

    def run(bx, by, carry):
        flat_w, slots, mod_state = carry
        flat_w, slots, mod_state, loss = step(
            flat_w, slots, mod_state, bx, by,
            jnp.asarray(0.1, jnp.float32), jnp.asarray(0, jnp.int32),
            jax.random.PRNGKey(1))
        return (flat_w, slots, mod_state), loss

    carry = (jax.device_put(spec.flatten(variables["params"]), replicated),
             jax.tree_util.tree_map(
                 lambda s: jax.device_put(s, NamedSharding(mesh, P("data"))),
                 method.init_slots(jnp.zeros((spec.padded,), jnp.float32))),
             jax.device_put(variables["state"], replicated))

    def stepper(i_carry):
        i, carry = i_carry
        carry, loss = run(*pool[i % 2], carry)
        return (i + 1, carry), loss

    # fenced step timing
    (_, carry), loss = stepper((0, carry))
    float(loss)
    t0 = time.perf_counter()
    ic = (1, carry)
    for _ in range(iters):
        ic, loss = stepper(ic)
    float(loss)
    step_s = (time.perf_counter() - t0) / iters

    # collective alone: psum_scatter + all_gather at the wire size the
    # DP step uses (bf16 chunks), via shard_map like the real step
    from bigdl_tpu.parallel.shard_map_compat import shard_map
    from jax import lax

    # chained inside one jit AND value-varying every iteration: the
    # remote-TPU transport may memoize byte-identical executions
    # (CLAUDE.md), so each collective consumes the previous one's output
    coll_iters = max(iters, 4)

    def coll_chain(flat):
        def body(c, _):
            g = lax.psum_scatter(c.astype(jnp.bfloat16), "data",
                                 scatter_dimension=0, tiled=True)
            out = lax.all_gather(g.astype(jnp.float32), "data", axis=0,
                                 tiled=True)
            return out / n, None  # /n keeps the chained values bounded

        return lax.scan(body, flat, None, length=coll_iters)[0]

    coll_fn = jax.jit(shard_map(coll_chain, mesh=mesh, in_specs=P(),
                                out_specs=P(), check_vma=False))
    flat0 = jax.device_put(spec.flatten(variables["params"]) + 1.0,
                           replicated)
    warm = coll_fn(flat0)  # compile + warmup
    float(jnp.sum(warm[:1]).astype(jnp.float32))
    t0 = time.perf_counter()
    out = coll_fn(warm)  # chained on warmup's output: fresh values
    float(jnp.sum(out[:1]).astype(jnp.float32))
    coll_s = (time.perf_counter() - t0) / coll_iters

    # analytic ring bound: reduce-scatter + all-gather each move
    # (N-1)/N of the buffer over the slowest link
    wire_bytes = spec.padded * 2  # bf16 wire
    bound_s = (0.0 if n == 1 else
               2 * (n - 1) / n * wire_bytes / (ici_gbps * 1e9))
    return {
        "devices": n,
        "global_batch": batch,
        "step_ms": round(step_s * 1e3, 3),
        "collective_ms": round(coll_s * 1e3, 3),
        "ici_ring_bound_ms": round(bound_s * 1e3, 4),
        "wire_mb": round(wire_bytes / 1e6, 2),
    }


def measure_zero2(n, model_name, per_chip_batch, iters, ckpt_every=50,
                  windows=3, workdir=None):
    """ZeRO-2 row (ISSUE 9): per-step time of the weight-sharded DP
    step at the full mesh size, plus CHECKPOINT-OVERLAP provenance —
    the identical step window re-timed (a) without checkpointing,
    (b) with ASYNC sharded saves every `ckpt_every` steps (host
    snapshot + enqueue on the step path; the disk write overlaps the
    following steps on the background thread), and (c) with
    synchronous saves (the step stalls on the full write — the cost
    async buys back). Each mode takes the median of `windows` timed
    windows (CPU hosts jitter; CLAUDE.md). Acceptance: async-vs-nosave
    per-step within 5%. `ckpt_every` defaults to a realistic cadence:
    the async contract is "steps never stall on I/O", not "snapshots
    are free" — the synchronous host snapshot (device fetch of model
    + shard slices) is the irreducible on-path cost, and the write
    must fit inside `ckpt_every * step_time` of background time to
    fully overlap (at cadence 2 on a 2-core host nothing can hide a
    37 ms write behind 14 ms of compute). The saves go through the REAL sharded path —
    Checkpoint.save_sharded over DistriOptimizer._local_shard_slices
    with the manifest-last publish — so the row measures the shipping
    code, not a stand-in."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel import (FlatParamSpec, make_dp_train_step,
                                    make_mesh)
    from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
    from bigdl_tpu.serialization.checkpoint import Checkpoint

    devices = jax.devices()[:n]
    mesh = make_mesh({"data": n}, devices=devices)
    model, shape, classes = build_model(model_name)
    variables = model.init(jax.random.PRNGKey(0))
    method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0)
    spec = FlatParamSpec(variables["params"], n)
    step = make_dp_train_step(model, nn.ClassNLLCriterion(), method, mesh,
                              spec, axis="data", grad_dtype="bfloat16",
                              zero=2)
    unflatten = jax.jit(spec.unflatten)
    sharded = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())
    batch = per_chip_batch * n
    rng = np.random.RandomState(0)
    pool = [(jax.device_put(
                 rng.rand(batch, *shape).astype(np.float32),
                 NamedSharding(mesh, P("data", None, None, None))),
             jax.device_put(
                 rng.randint(0, classes, batch).astype(np.int32),
                 NamedSharding(mesh, P("data"))))
            for _ in range(2)]
    optim_meta = {"layout": "zero2_flat", "num_shards": n,
                  "total": spec.total, "padded": spec.padded}
    tmp = workdir or tempfile.mkdtemp(prefix="scaling_zero2_")

    def fresh_carry():
        return (jax.device_put(spec.flatten(variables["params"]), sharded),
                jax.tree_util.tree_map(
                    lambda s: jax.device_put(s, sharded),
                    method.init_slots(
                        jnp.zeros((spec.padded,), jnp.float32))),
                jax.device_put(variables["state"], replicated))

    def window(mode, tag):
        ck = (None if mode == "nosave" else
              Checkpoint(os.path.join(tmp, tag),
                         sharded=True, async_save=(mode == "async")))
        flat_w, slots, mod_state = fresh_carry()
        loss = None
        t0 = time.perf_counter()
        for i in range(iters):
            flat_w, slots, mod_state, loss = step(
                flat_w, slots, mod_state, *pool[i % 2],
                jnp.asarray(0.1, jnp.float32), jnp.asarray(i, jnp.int32),
                jax.random.PRNGKey(1))
            if ck is not None and (i + 1) % ckpt_every == 0:
                # the real save path: gather/unflatten the model tree,
                # hand per-shard slot slices to the manifest-last writer
                saved = {"params": jax.device_get(unflatten(flat_w)),
                         "state": jax.device_get(mod_state)}
                ck.save_sharded(
                    i + 1, saved,
                    DistriOptimizer._local_shard_slices(slots, spec),
                    nshards=n, optim_meta=optim_meta)
        if ck is not None:
            ck.wait()  # conservative: any un-overlapped tail is charged
        float(loss)    # fence (block_until_ready lies through tunnels)
        return (time.perf_counter() - t0) / iters

    # compile + warm the write path outside every timed window
    window("sync", "warmup")
    # windows INTERLEAVED across modes: this host's speed drifts on
    # the tens-of-seconds scale, so mode-batched timing would fold the
    # drift into the mode comparison
    samples = {m: [] for m in ("nosave", "async", "sync")}
    for w in range(windows):
        for mode in samples:
            samples[mode].append(window(mode, f"{mode}{w}"))
    times = {m: sorted(v)[windows // 2] for m, v in samples.items()}
    if workdir is None:
        shutil.rmtree(tmp, ignore_errors=True)
    nosave, async_t, sync_t = (times["nosave"], times["async"],
                               times["sync"])
    return {
        "devices": n, "zero": 2, "global_batch": batch,
        "step_ms": round(nosave * 1e3, 3),
        "ckpt_overlap": {
            "cadence_steps": ckpt_every,
            "nosave_step_ms": round(nosave * 1e3, 3),
            "async_step_ms": round(async_t * 1e3, 3),
            "sync_step_ms": round(sync_t * 1e3, 3),
            "async_overhead_frac": round(async_t / nosave - 1.0, 4),
            "sync_overhead_frac": round(sync_t / nosave - 1.0, 4),
            "async_within_5pct": bool(async_t <= nosave * 1.05),
        },
        "provenance": {"layout": "zero2_flat", "nshards": n,
                       "sharded_ckpt": True, "manifest_last": True,
                       "windows": windows, "iters": iters},
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet8",
                    choices=["mlp", "resnet8", "resnet50"])
    ap.add_argument("--per-chip-batch", type=int, default=None)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--ici-gbps", type=float, default=DEFAULT_ICI_GBPS)
    ap.add_argument("--out", default=None, help="also write JSON here")
    ap.add_argument("--no-zero2", action="store_true",
                    help="skip the zero2 checkpoint-overlap row (it "
                         "needs >=120 steps per window regardless of "
                         "--iters, so quick plumbing runs can opt out)")
    args = ap.parse_args()

    import jax

    n_all = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    per_chip = args.per_chip_batch or (
        {"mlp": 64, "resnet8": 32, "resnet50": 128}[args.model]
        if on_tpu else {"mlp": 16, "resnet8": 8, "resnet50": 2}[args.model])

    sizes = []
    n = 1
    while n <= n_all:
        sizes.append(n)
        n *= 2
    if sizes[-1] != n_all:
        sizes.append(n_all)

    rows = []
    for n in sizes:
        row = measure_mesh(n, args.model, per_chip, args.iters,
                           args.ici_gbps)
        rows.append(row)
        print(json.dumps(row), flush=True)

    # ZeRO-2 + checkpoint-overlap row at the full mesh size (ISSUE 9);
    # enough steps per window for >=2 saves at the default cadence
    zero2_row = None
    if not args.no_zero2:
        zero2_row = measure_zero2(n_all, args.model, per_chip,
                                  max(args.iters, 120))
        print(json.dumps(zero2_row), flush=True)

    t1 = rows[0]["step_ms"]
    summary = {
        "model": args.model,
        "platform": jax.devices()[0].platform,
        "per_chip_batch": per_chip,
        "weak_scaling_efficiency": {
            str(r["devices"]): round(t1 / r["step_ms"], 4) for r in rows},
        "north_star_ge_90pct": bool(
            t1 / rows[-1]["step_ms"] >= 0.9) if len(rows) > 1 else None,
        "note": ("absolute times are meaningless off-TPU; this run "
                 "validates plumbing only" if not on_tpu else
                 "fenced-fetch methodology, bf16 gradient wire"),
        "rows": rows,
        "zero2": zero2_row,
    }
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)


if __name__ == "__main__":
    main()
