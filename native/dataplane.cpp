// Native host-side data plane for bigdl_tpu.
//
// Reference parity: the reference's native layer is C/C++ behind JNI
// (BigDL-core: libjmkl / mkldnn / bigquant .so, SURVEY.md §2.1); its data
// plane rides Spark executors (JVM). On TPU the device compute belongs to
// XLA, so the native layer moves to where it still matters: the HOST input
// pipeline that has to keep the chips fed (SURVEY.md §7 "Input pipeline
// throughput" hard part). This library provides:
//
//   * batched image preprocessing kernels (u8→f32 normalize, random crop
//     with zero padding, horizontal flip) parallelized with std::thread
//   * IDX (MNIST) and CIFAR-10 binary decoding
//   * a multithreaded prefetcher: worker threads produce shuffled,
//     augmented, normalized f32 batches into a bounded ring buffer while
//     the training loop (and the TPU) consume previous ones.
//
// C ABI throughout — consumed from Python via ctypes
// (bigdl_tpu/dataset/native.py), no pybind11 dependency.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- kernels

// u8 (N,H,W,C) -> f32 (N,H,W,C), per-channel (x - mean[c]) / std[c]
void bdl_normalize_u8(const uint8_t* src, float* dst, int64_t n_pix,
                      int c, const float* mean, const float* stdd,
                      int n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::vector<float> inv(c);
  for (int i = 0; i < c; ++i) inv[i] = 1.0f / stdd[i];
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int ch = static_cast<int>(i % c);
      dst[i] = (static_cast<float>(src[i]) - mean[ch]) * inv[ch];
    }
  };
  int64_t total = n_pix * c;
  if (n_threads == 1 || total < (1 << 16)) {
    work(0, total);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (total + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(total, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// f32 NHWC batch horizontal flip in place for rows where flags[i] != 0
void bdl_hflip(float* img, const uint8_t* flags, int n, int h, int w,
               int c) {
  for (int i = 0; i < n; ++i) {
    if (!flags[i]) continue;
    float* base = img + static_cast<int64_t>(i) * h * w * c;
    for (int y = 0; y < h; ++y) {
      float* row = base + static_cast<int64_t>(y) * w * c;
      for (int x = 0; x < w / 2; ++x)
        for (int ch = 0; ch < c; ++ch)
          std::swap(row[x * c + ch], row[(w - 1 - x) * c + ch]);
    }
  }
}

// f32 NHWC random crop with zero padding: src (n,h,w,c) -> dst (n,h,w,c)
// shifted by per-image offsets in [-pad, pad] (offy/offx arrays).
void bdl_shift_crop(const float* src, float* dst, const int* offy,
                    const int* offx, int n, int h, int w, int c) {
  const int64_t img_sz = static_cast<int64_t>(h) * w * c;
  for (int i = 0; i < n; ++i) {
    const float* s = src + i * img_sz;
    float* d = dst + i * img_sz;
    std::memset(d, 0, img_sz * sizeof(float));
    int dy = offy[i], dx = offx[i];
    int y0 = std::max(0, dy), y1 = std::min(h, h + dy);
    int x0 = std::max(0, dx), x1 = std::min(w, w + dx);
    for (int y = y0; y < y1; ++y) {
      const float* srow = s + (static_cast<int64_t>(y - dy) * w + (x0 - dx)) * c;
      float* drow = d + (static_cast<int64_t>(y) * w + x0) * c;
      std::memcpy(drow, srow, static_cast<int64_t>(x1 - x0) * c * sizeof(float));
    }
  }
}

// ---------------------------------------------------------------- decoders

// IDX3 images: returns 0 on success; out must hold n*rows*cols bytes.
int bdl_decode_idx_images(const uint8_t* buf, int64_t len, uint8_t* out,
                          int64_t* out_n, int64_t* out_rows,
                          int64_t* out_cols) {
  if (len < 16) return -1;
  auto be32 = [&](int64_t off) {
    return (static_cast<uint32_t>(buf[off]) << 24) |
           (static_cast<uint32_t>(buf[off + 1]) << 16) |
           (static_cast<uint32_t>(buf[off + 2]) << 8) |
           static_cast<uint32_t>(buf[off + 3]);
  };
  if (be32(0) != 2051) return -2;
  int64_t n = be32(4), rows = be32(8), cols = be32(12);
  if (len < 16 + n * rows * cols) return -3;
  *out_n = n; *out_rows = rows; *out_cols = cols;
  if (out) std::memcpy(out, buf + 16, n * rows * cols);
  return 0;
}

int bdl_decode_idx_labels(const uint8_t* buf, int64_t len, uint8_t* out,
                          int64_t* out_n) {
  if (len < 8) return -1;
  uint32_t magic = (static_cast<uint32_t>(buf[0]) << 24) |
                   (static_cast<uint32_t>(buf[1]) << 16) |
                   (static_cast<uint32_t>(buf[2]) << 8) |
                   static_cast<uint32_t>(buf[3]);
  if (magic != 2049) return -2;
  int64_t n = (static_cast<uint32_t>(buf[4]) << 24) |
              (static_cast<uint32_t>(buf[5]) << 16) |
              (static_cast<uint32_t>(buf[6]) << 8) |
              static_cast<uint32_t>(buf[7]);
  if (len < 8 + n) return -3;
  *out_n = n;
  if (out) std::memcpy(out, buf + 8, n);
  return 0;
}

// CIFAR-10 binary: records of [label u8][3072 u8 CHW] -> NHWC u8 + labels
int bdl_decode_cifar10(const uint8_t* buf, int64_t len, uint8_t* images,
                       uint8_t* labels, int64_t* out_n) {
  const int64_t rec = 1 + 3 * 32 * 32;
  int64_t n = len / rec;
  if (n * rec != len) return -1;
  *out_n = n;
  if (!images) return 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* r = buf + i * rec;
    labels[i] = r[0];
    const uint8_t* chw = r + 1;
    uint8_t* img = images + i * 3072;
    for (int y = 0; y < 32; ++y)
      for (int x = 0; x < 32; ++x)
        for (int ch = 0; ch < 3; ++ch)
          img[(y * 32 + x) * 3 + ch] = chw[ch * 1024 + y * 32 + x];
  }
  return 0;
}

// -------------------------------------------------------------- prefetcher

struct Batch {
  std::vector<float> images;
  std::vector<int32_t> labels;
};

struct Prefetcher {
  const uint8_t* images;   // (n, h, w, c) u8, borrowed from caller
  const int32_t* labels;   // (n,), borrowed
  int64_t n;
  int h, w, c, batch;
  int pad;                 // random-shift augmentation range (0 = off)
  bool hflip;
  std::vector<float> mean, stdd;

  std::deque<Batch> ring;
  size_t capacity;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::mt19937 index_rng;
  std::vector<int64_t> order;
  int64_t cursor = 0;
  std::mutex order_mu;

  void refill_order() {  // order_mu held
    if (order.empty()) {
      order.resize(n);
      for (int64_t i = 0; i < n; ++i) order[i] = i;
    }
    std::shuffle(order.begin(), order.end(), index_rng);
    cursor = 0;
  }

  void take_indices(std::vector<int64_t>* idx) {
    std::lock_guard<std::mutex> lk(order_mu);
    idx->clear();
    for (int i = 0; i < batch; ++i) {
      if (cursor >= n) refill_order();
      idx->push_back(order[cursor++]);
    }
  }

  void worker(unsigned seed) {
    std::mt19937 rng(seed);
    std::vector<int64_t> idx;
    const int64_t img_px = static_cast<int64_t>(h) * w;
    while (!stop.load()) {
      take_indices(&idx);
      Batch b;
      b.images.resize(static_cast<int64_t>(batch) * img_px * c);
      b.labels.resize(batch);
      std::vector<uint8_t> u8img(img_px * c);
      for (int i = 0; i < batch; ++i) {
        const uint8_t* src = images + idx[i] * img_px * c;
        b.labels[i] = labels[idx[i]];
        float* dst = b.images.data() + static_cast<int64_t>(i) * img_px * c;
        bdl_normalize_u8(src, dst, img_px, c, mean.data(), stdd.data(), 1);
        if (pad > 0) {
          std::uniform_int_distribution<int> d(-pad, pad);
          int offy = d(rng), offx = d(rng);
          std::vector<float> tmp(dst, dst + img_px * c);
          bdl_shift_crop(tmp.data(), dst, &offy, &offx, 1, h, w, c);
        }
        if (hflip && (rng() & 1)) {
          uint8_t f = 1;
          bdl_hflip(dst, &f, 1, h, w, c);
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_full.wait(lk, [&] { return ring.size() < capacity || stop.load(); });
      if (stop.load()) return;
      ring.push_back(std::move(b));
      cv_empty.notify_one();
    }
  }
};

void* bdl_prefetcher_create(const uint8_t* images, const int32_t* labels,
                            int64_t n, int h, int w, int c, int batch,
                            int capacity, int n_threads, uint64_t seed,
                            int pad, int hflip, const float* mean,
                            const float* stdd) {
  auto* p = new Prefetcher();
  p->images = images; p->labels = labels;
  p->n = n; p->h = h; p->w = w; p->c = c; p->batch = batch;
  p->capacity = capacity > 0 ? capacity : 4;
  p->pad = pad; p->hflip = hflip != 0;
  p->mean.assign(mean, mean + c);
  p->stdd.assign(stdd, stdd + c);
  p->index_rng.seed(seed);
  {
    std::lock_guard<std::mutex> lk(p->order_mu);
    p->refill_order();
  }
  if (n_threads < 1) n_threads = 1;
  for (int t = 0; t < n_threads; ++t)
    p->workers.emplace_back(&Prefetcher::worker, p,
                            static_cast<unsigned>(seed + 1000003ULL * (t + 1)));
  return p;
}

// Blocks until a batch is ready; copies into caller buffers.
void bdl_prefetcher_next(void* handle, float* out_images,
                         int32_t* out_labels) {
  auto* p = static_cast<Prefetcher*>(handle);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_empty.wait(lk, [&] { return !p->ring.empty(); });
    b = std::move(p->ring.front());
    p->ring.pop_front();
    p->cv_full.notify_one();
  }
  std::memcpy(out_images, b.images.data(), b.images.size() * sizeof(float));
  std::memcpy(out_labels, b.labels.data(), b.labels.size() * sizeof(int32_t));
}

void bdl_prefetcher_destroy(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  p->stop.store(true);
  p->cv_full.notify_all();
  p->cv_empty.notify_all();
  for (auto& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
