// Native host-side data plane for bigdl_tpu.
//
// Reference parity: the reference's native layer is C/C++ behind JNI
// (BigDL-core: libjmkl / mkldnn / bigquant .so, SURVEY.md §2.1); its data
// plane rides Spark executors (JVM). On TPU the device compute belongs to
// XLA, so the native layer moves to where it still matters: the HOST input
// pipeline that has to keep the chips fed (SURVEY.md §7 "Input pipeline
// throughput" hard part). This library provides:
//
//   * batched image preprocessing kernels (u8→f32 normalize, random crop
//     with zero padding, horizontal flip) parallelized with std::thread
//   * IDX (MNIST) and CIFAR-10 binary decoding
//   * a multithreaded prefetcher: worker threads produce shuffled,
//     augmented, normalized f32 batches into a bounded ring buffer while
//     the training loop (and the TPU) consume previous ones.
//
// C ABI throughout — consumed from Python via ctypes
// (bigdl_tpu/dataset/native.py), no pybind11 dependency.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- kernels

// u8 (N,H,W,C) -> f32 (N,H,W,C), per-channel (x - mean[c]) / std[c]
void bdl_normalize_u8(const uint8_t* src, float* dst, int64_t n_pix,
                      int c, const float* mean, const float* stdd,
                      int n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::vector<float> inv(c);
  for (int i = 0; i < c; ++i) inv[i] = 1.0f / stdd[i];
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int ch = static_cast<int>(i % c);
      dst[i] = (static_cast<float>(src[i]) - mean[ch]) * inv[ch];
    }
  };
  int64_t total = n_pix * c;
  if (n_threads == 1 || total < (1 << 16)) {
    work(0, total);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (total + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(total, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// f32 NHWC batch horizontal flip in place for rows where flags[i] != 0
void bdl_hflip(float* img, const uint8_t* flags, int n, int h, int w,
               int c) {
  for (int i = 0; i < n; ++i) {
    if (!flags[i]) continue;
    float* base = img + static_cast<int64_t>(i) * h * w * c;
    for (int y = 0; y < h; ++y) {
      float* row = base + static_cast<int64_t>(y) * w * c;
      for (int x = 0; x < w / 2; ++x)
        for (int ch = 0; ch < c; ++ch)
          std::swap(row[x * c + ch], row[(w - 1 - x) * c + ch]);
    }
  }
}

// f32 NHWC random crop with zero padding: src (n,h,w,c) -> dst (n,h,w,c)
// shifted by per-image offsets in [-pad, pad] (offy/offx arrays).
void bdl_shift_crop(const float* src, float* dst, const int* offy,
                    const int* offx, int n, int h, int w, int c) {
  const int64_t img_sz = static_cast<int64_t>(h) * w * c;
  for (int i = 0; i < n; ++i) {
    const float* s = src + i * img_sz;
    float* d = dst + i * img_sz;
    std::memset(d, 0, img_sz * sizeof(float));
    int dy = offy[i], dx = offx[i];
    int y0 = std::max(0, dy), y1 = std::min(h, h + dy);
    int x0 = std::max(0, dx), x1 = std::min(w, w + dx);
    for (int y = y0; y < y1; ++y) {
      const float* srow = s + (static_cast<int64_t>(y - dy) * w + (x0 - dx)) * c;
      float* drow = d + (static_cast<int64_t>(y) * w + x0) * c;
      std::memcpy(drow, srow, static_cast<int64_t>(x1 - x0) * c * sizeof(float));
    }
  }
}

// f32 HWC bilinear resize (align_corners=False, the TF/torch default):
// src (h, w, c) -> dst (oh, ow, c). Multithreaded over output rows.
// Matches dataset/vision.py's pure-numpy implementation.
void bdl_resize_bilinear(const float* src, float* dst, int h, int w,
                         int c, int oh, int ow, int n_threads) {
  const float sy = static_cast<float>(h) / oh;
  const float sx = static_cast<float>(w) / ow;
  auto work = [&](int lo, int hi) {
    std::vector<int> x0s(ow), x1s(ow);
    std::vector<float> fxs(ow);
    for (int x = 0; x < ow; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      x0s[x] = x0;
      x1s[x] = std::min(x0 + 1, w - 1);
      fxs[x] = fx - x0;
    }
    for (int y = lo; y < hi; ++y) {
      float fy = (y + 0.5f) * sy - 0.5f;
      if (fy < 0) fy = 0;
      int y0 = static_cast<int>(fy);
      int y1 = std::min(y0 + 1, h - 1);
      float wy = fy - y0;
      const float* r0 = src + static_cast<int64_t>(y0) * w * c;
      const float* r1 = src + static_cast<int64_t>(y1) * w * c;
      float* out = dst + static_cast<int64_t>(y) * ow * c;
      for (int x = 0; x < ow; ++x) {
        const float* a = r0 + x0s[x] * c;
        const float* b = r0 + x1s[x] * c;
        const float* d = r1 + x0s[x] * c;
        const float* e = r1 + x1s[x] * c;
        float wx = fxs[x];
        for (int ch = 0; ch < c; ++ch) {
          float top = a[ch] + (b[ch] - a[ch]) * wx;
          float bot = d[ch] + (e[ch] - d[ch]) * wx;
          out[x * c + ch] = top + (bot - top) * wy;
        }
      }
    }
  };
  if (n_threads < 2 || oh < 2 * n_threads) {
    work(0, oh);
    return;
  }
  std::vector<std::thread> ts;
  int chunk = (oh + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int lo = t * chunk, hi = std::min(oh, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// ---------------------------------------------------------------- decoders

// IDX3 images: returns 0 on success; out must hold n*rows*cols bytes.
int bdl_decode_idx_images(const uint8_t* buf, int64_t len, uint8_t* out,
                          int64_t* out_n, int64_t* out_rows,
                          int64_t* out_cols) {
  if (len < 16) return -1;
  auto be32 = [&](int64_t off) {
    return (static_cast<uint32_t>(buf[off]) << 24) |
           (static_cast<uint32_t>(buf[off + 1]) << 16) |
           (static_cast<uint32_t>(buf[off + 2]) << 8) |
           static_cast<uint32_t>(buf[off + 3]);
  };
  if (be32(0) != 2051) return -2;
  int64_t n = be32(4), rows = be32(8), cols = be32(12);
  if (len < 16 + n * rows * cols) return -3;
  *out_n = n; *out_rows = rows; *out_cols = cols;
  if (out) std::memcpy(out, buf + 16, n * rows * cols);
  return 0;
}

int bdl_decode_idx_labels(const uint8_t* buf, int64_t len, uint8_t* out,
                          int64_t* out_n) {
  if (len < 8) return -1;
  uint32_t magic = (static_cast<uint32_t>(buf[0]) << 24) |
                   (static_cast<uint32_t>(buf[1]) << 16) |
                   (static_cast<uint32_t>(buf[2]) << 8) |
                   static_cast<uint32_t>(buf[3]);
  if (magic != 2049) return -2;
  int64_t n = (static_cast<uint32_t>(buf[4]) << 24) |
              (static_cast<uint32_t>(buf[5]) << 16) |
              (static_cast<uint32_t>(buf[6]) << 8) |
              static_cast<uint32_t>(buf[7]);
  if (len < 8 + n) return -3;
  *out_n = n;
  if (out) std::memcpy(out, buf + 8, n);
  return 0;
}

// CIFAR-10 binary: records of [label u8][3072 u8 CHW] -> NHWC u8 + labels
int bdl_decode_cifar10(const uint8_t* buf, int64_t len, uint8_t* images,
                       uint8_t* labels, int64_t* out_n) {
  const int64_t rec = 1 + 3 * 32 * 32;
  int64_t n = len / rec;
  if (n * rec != len) return -1;
  *out_n = n;
  if (!images) return 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* r = buf + i * rec;
    labels[i] = r[0];
    const uint8_t* chw = r + 1;
    uint8_t* img = images + i * 3072;
    for (int y = 0; y < 32; ++y)
      for (int x = 0; x < 32; ++x)
        for (int ch = 0; ch < 3; ++ch)
          img[(y * 32 + x) * 3 + ch] = chw[ch * 1024 + y * 32 + x];
  }
  return 0;
}

// ------------------------------------------------------- BDLS shard files
//
// Disk-resident fixed-record image shards (the TPU-era counterpart of
// the reference's ImageNet sequence files, dataset/image/ + SURVEY.md
// §2.4): 32-byte header then n records of [label i32 LE][h*w*c u8].
// Shards are mmap()ed, so datasets far larger than RAM stream through
// the OS page cache with zero-copy reads in the workers.

struct BdlsHeader {
  char magic[4];      // "BDLS"
  uint32_t version;   // 1
  uint64_t n;
  uint32_t h, w, c;
  uint32_t reserved;
};
static_assert(sizeof(BdlsHeader) == 32, "BDLS header must be 32 bytes");

struct MappedShard {
  int fd = -1;
  void* map = nullptr;
  size_t len = 0;
  const uint8_t* base = nullptr;  // first record
  int64_t n = 0;
};

// Returns 0 on success. Fills header fields; on success the shard is
// mapped read-only with MADV_WILLNEED left to the kernel's readahead.
static int map_shard(const char* path, MappedShard* out, BdlsHeader* hdr) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(BdlsHeader)) {
    ::close(fd);
    return -2;
  }
  void* m = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) {
    ::close(fd);
    return -3;
  }
  std::memcpy(hdr, m, sizeof(BdlsHeader));
  if (std::memcmp(hdr->magic, "BDLS", 4) != 0 || hdr->version != 1) {
    ::munmap(m, st.st_size);
    ::close(fd);
    return -4;
  }
  // bound each dim before multiplying: h*w*c of hostile u32 headers can
  // overflow int64 and wrap to a small positive rec, defeating the
  // division-form check below (65535^3 alone is within int64, but the
  // bound also keeps rec sane for the prefetch arithmetic downstream)
  if (hdr->h == 0 || hdr->w == 0 || hdr->c == 0 ||
      hdr->h > (1u << 16) || hdr->w > (1u << 16) || hdr->c > (1u << 10)) {
    ::munmap(m, st.st_size);
    ::close(fd);
    return -4;
  }
  const int64_t rec = 4 + static_cast<int64_t>(hdr->h) * hdr->w * hdr->c;
  // division form: the multiplication `rec * n` could wrap for a
  // corrupt/hostile header and bypass validation
  const uint64_t payload = st.st_size - sizeof(BdlsHeader);
  if (hdr->n > payload / static_cast<uint64_t>(rec)) {
    ::munmap(m, st.st_size);
    ::close(fd);
    return -5;
  }
  out->fd = fd;
  out->map = m;
  out->len = st.st_size;
  out->base = static_cast<const uint8_t*>(m) + sizeof(BdlsHeader);
  out->n = static_cast<int64_t>(hdr->n);
  return 0;
}

// -------------------------------------------------------------- prefetcher

struct Batch {
  std::vector<float> images;      // f32 mode (normalized on host)
  std::vector<uint8_t> images_u8; // u8 mode (normalize on device —
                                  // 4x less host->device wire)
  std::vector<int32_t> labels;
};

// u8 in-place horizontal flip of one (h, w, c) image
static void flip_u8(uint8_t* img, int h, int w, int c) {
  for (int y = 0; y < h; ++y) {
    uint8_t* row = img + static_cast<int64_t>(y) * w * c;
    for (int x = 0; x < w / 2; ++x)
      for (int ch = 0; ch < c; ++ch)
        std::swap(row[x * c + ch], row[(w - 1 - x) * c + ch]);
  }
}

// u8 shift-crop, src -> dst, one image. Padding fills with the
// per-channel MEAN byte so device-side normalization maps borders to
// 0.0 — identical augmentation distribution to the f32 plane, whose
// zero-fill happens post-normalize.
static void shift_crop_u8(const uint8_t* src, uint8_t* dst, int dy, int dx,
                          int h, int w, int c, const uint8_t* fill) {
  for (int y = 0; y < h; ++y)
    for (int x = 0; x < w; ++x)
      std::memcpy(dst + (static_cast<int64_t>(y) * w + x) * c, fill, c);
  int y0 = std::max(0, dy), y1 = std::min(h, h + dy);
  int x0 = std::max(0, dx), x1 = std::min(w, w + dx);
  for (int y = y0; y < y1; ++y)
    std::memcpy(dst + (static_cast<int64_t>(y) * w + x0) * c,
                src + (static_cast<int64_t>(y - dy) * w + (x0 - dx)) * c,
                static_cast<int64_t>(x1 - x0) * c);
}

struct Prefetcher {
  const uint8_t* images;   // (n, h, w, c) u8, borrowed (nullptr: files)
  const int32_t* labels;   // (n,), borrowed (nullptr: files)
  std::vector<MappedShard> shards;       // disk-resident mode
  std::vector<int64_t> shard_starts;     // cumulative record offsets
  int64_t rec_bytes = 0;                 // 4 + h*w*c (file mode)
  int64_t n;
  int h, w, c, batch;
  int pad;                 // random-shift augmentation range (0 = off)
  bool hflip;
  bool u8_out = false;     // emit raw u8 batches (device-side normalize)
  std::vector<float> mean, stdd;

  std::deque<Batch> ring;
  size_t capacity;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::mt19937 index_rng;
  std::vector<int64_t> order;
  int64_t cursor = 0;
  std::mutex order_mu;

  void refill_order() {  // order_mu held
    if (order.empty()) {
      order.resize(n);
      for (int64_t i = 0; i < n; ++i) order[i] = i;
    }
    std::shuffle(order.begin(), order.end(), index_rng);
    cursor = 0;
  }

  void take_indices(std::vector<int64_t>* idx) {
    std::lock_guard<std::mutex> lk(order_mu);
    idx->clear();
    for (int i = 0; i < batch; ++i) {
      if (cursor >= n) refill_order();
      idx->push_back(order[cursor++]);
    }
  }

  // record accessor spanning both sources (in-memory / mmap'd shards)
  const uint8_t* record_image(int64_t i, int32_t* label) const {
    if (images) {
      *label = labels[i];
      return images + i * static_cast<int64_t>(h) * w * c;
    }
    auto it = std::upper_bound(shard_starts.begin(), shard_starts.end(), i);
    const size_t s = (it - shard_starts.begin()) - 1;
    const uint8_t* rec = shards[s].base + (i - shard_starts[s]) * rec_bytes;
    std::memcpy(label, rec, sizeof(int32_t));
    return rec + sizeof(int32_t);
  }

  void worker(unsigned seed) {
    std::mt19937 rng(seed);
    std::vector<int64_t> idx;
    const int64_t img_px = static_cast<int64_t>(h) * w;
    while (!stop.load()) {
      take_indices(&idx);
      Batch b;
      b.labels.resize(batch);
      const int64_t img_sz = img_px * c;
      if (u8_out) {
        b.images_u8.resize(static_cast<int64_t>(batch) * img_sz);
        std::vector<uint8_t> fill(c);
        for (int ch = 0; ch < c; ++ch)
          fill[ch] = static_cast<uint8_t>(
              std::min(255.0f, std::max(0.0f, mean[ch] + 0.5f)));
        for (int i = 0; i < batch; ++i) {
          const uint8_t* src = record_image(idx[i], &b.labels[i]);
          uint8_t* dst = b.images_u8.data() +
                         static_cast<int64_t>(i) * img_sz;
          if (pad > 0) {
            std::uniform_int_distribution<int> d(-pad, pad);
            shift_crop_u8(src, dst, d(rng), d(rng), h, w, c, fill.data());
          } else {
            std::memcpy(dst, src, img_sz);
          }
          if (hflip && (rng() & 1)) flip_u8(dst, h, w, c);
        }
      } else {
        b.images.resize(static_cast<int64_t>(batch) * img_sz);
        for (int i = 0; i < batch; ++i) {
          const uint8_t* src = record_image(idx[i], &b.labels[i]);
          float* dst = b.images.data() + static_cast<int64_t>(i) * img_sz;
          bdl_normalize_u8(src, dst, img_px, c, mean.data(), stdd.data(),
                           1);
          if (pad > 0) {
            std::uniform_int_distribution<int> d(-pad, pad);
            int offy = d(rng), offx = d(rng);
            std::vector<float> tmp(dst, dst + img_sz);
            bdl_shift_crop(tmp.data(), dst, &offy, &offx, 1, h, w, c);
          }
          if (hflip && (rng() & 1)) {
            uint8_t f = 1;
            bdl_hflip(dst, &f, 1, h, w, c);
          }
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_full.wait(lk, [&] { return ring.size() < capacity || stop.load(); });
      if (stop.load()) return;
      ring.push_back(std::move(b));
      cv_empty.notify_one();
    }
  }
};

// Disk-resident prefetcher over BDLS shard files. Returns nullptr on
// any open/map/header failure (caller falls back). All shards must
// share (h, w, c); out_* report the dataset geometry.
void* bdl_file_prefetcher_create(const char* const* paths, int n_paths,
                                 int batch, int capacity, int n_threads,
                                 uint64_t seed, int pad, int hflip,
                                 int u8_out, const float* mean,
                                 const float* stdd, int64_t* out_n,
                                 int* out_h, int* out_w, int* out_c) {
  auto* p = new Prefetcher();
  BdlsHeader first{};
  int64_t total = 0;
  auto fail = [&](MappedShard* extra) {
    if (extra && extra->map) {
      ::munmap(extra->map, extra->len);
      ::close(extra->fd);
    }
    for (auto& s : p->shards) {
      ::munmap(s.map, s.len);
      ::close(s.fd);
    }
    delete p;
    return static_cast<void*>(nullptr);
  };
  for (int i = 0; i < n_paths; ++i) {
    MappedShard ms;
    BdlsHeader hdr{};
    if (map_shard(paths[i], &ms, &hdr) != 0) return fail(nullptr);
    if (i > 0 && (hdr.h != first.h || hdr.w != first.w ||
                  hdr.c != first.c))
      return fail(&ms);  // the just-mapped shard is not in p->shards yet
    if (i == 0) first = hdr;
    p->shard_starts.push_back(total);
    total += ms.n;
    p->shards.push_back(ms);
  }
  if (total == 0) return fail(nullptr);
  p->images = nullptr;
  p->labels = nullptr;
  p->n = total;
  p->h = first.h; p->w = first.w; p->c = first.c;
  p->rec_bytes = 4 + static_cast<int64_t>(first.h) * first.w * first.c;
  p->batch = batch;
  p->capacity = capacity > 0 ? capacity : 4;
  p->pad = pad; p->hflip = hflip != 0;
  p->u8_out = u8_out != 0;
  p->mean.assign(mean, mean + first.c);
  p->stdd.assign(stdd, stdd + first.c);
  p->index_rng.seed(seed);
  {
    std::lock_guard<std::mutex> lk(p->order_mu);
    p->refill_order();
  }
  if (n_threads < 1) n_threads = 1;
  for (int t = 0; t < n_threads; ++t)
    p->workers.emplace_back(&Prefetcher::worker, p,
                            static_cast<unsigned>(seed + 1000003ULL * (t + 1)));
  *out_n = total;
  *out_h = first.h; *out_w = first.w; *out_c = first.c;
  return p;
}

// u8-mode consumer (pair with u8_out=1 at create time)
void bdl_prefetcher_next_u8(void* handle, uint8_t* out_images,
                            int32_t* out_labels) {
  auto* p = static_cast<Prefetcher*>(handle);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_empty.wait(lk, [&] { return !p->ring.empty(); });
    b = std::move(p->ring.front());
    p->ring.pop_front();
    p->cv_full.notify_one();
  }
  std::memcpy(out_images, b.images_u8.data(), b.images_u8.size());
  std::memcpy(out_labels, b.labels.data(),
              b.labels.size() * sizeof(int32_t));
}

void* bdl_prefetcher_create(const uint8_t* images, const int32_t* labels,
                            int64_t n, int h, int w, int c, int batch,
                            int capacity, int n_threads, uint64_t seed,
                            int pad, int hflip, const float* mean,
                            const float* stdd) {
  auto* p = new Prefetcher();
  p->images = images; p->labels = labels;
  p->n = n; p->h = h; p->w = w; p->c = c; p->batch = batch;
  p->capacity = capacity > 0 ? capacity : 4;
  p->pad = pad; p->hflip = hflip != 0;
  p->mean.assign(mean, mean + c);
  p->stdd.assign(stdd, stdd + c);
  p->index_rng.seed(seed);
  {
    std::lock_guard<std::mutex> lk(p->order_mu);
    p->refill_order();
  }
  if (n_threads < 1) n_threads = 1;
  for (int t = 0; t < n_threads; ++t)
    p->workers.emplace_back(&Prefetcher::worker, p,
                            static_cast<unsigned>(seed + 1000003ULL * (t + 1)));
  return p;
}

// Blocks until a batch is ready; copies into caller buffers.
void bdl_prefetcher_next(void* handle, float* out_images,
                         int32_t* out_labels) {
  auto* p = static_cast<Prefetcher*>(handle);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_empty.wait(lk, [&] { return !p->ring.empty(); });
    b = std::move(p->ring.front());
    p->ring.pop_front();
    p->cv_full.notify_one();
  }
  std::memcpy(out_images, b.images.data(), b.images.size() * sizeof(float));
  std::memcpy(out_labels, b.labels.data(), b.labels.size() * sizeof(int32_t));
}

void bdl_prefetcher_destroy(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  p->stop.store(true);
  p->cv_full.notify_all();
  p->cv_empty.notify_all();
  for (auto& t : p->workers) t.join();
  for (auto& s : p->shards) {
    ::munmap(s.map, s.len);
    ::close(s.fd);
  }
  delete p;
}

}  // extern "C"
