"""Torch7 .t7 wire format (reference: utils/TorchFile.scala#load/save).

The fixture in test_load_hand_authored_bytes is built with raw struct
packing — independent of our writer — so the reader is checked against
the wire format itself, not against our own serialization. Round-trips
then cover writer+reader together, and the imported modules' forward is
oracled against torch-CPU layers.
"""

import struct

import jax
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.torch_file import (TorchObject, load_t7, save_t7)

KEY = jax.random.PRNGKey(0)


# --------------------------------------------------- hand-authored fixture

def _i(v):
    return struct.pack("<i", v)


def _l(v):
    return struct.pack("<q", v)


def _d(v):
    return struct.pack("<d", float(v))


def _s(s):
    raw = s.encode()
    return _i(len(raw)) + raw


def _float_tensor(idx, arr):
    """TYPE_TORCH FloatTensor + its FloatStorage, heap ids idx, idx+1."""
    arr = np.asarray(arr, np.float32)
    strides = []
    st = 1
    for s in reversed(arr.shape):
        strides.append(st)
        st *= s
    out = _i(4) + _i(idx) + _s("V 1") + _s("torch.FloatTensor")
    out += _i(arr.ndim)
    out += b"".join(_l(s) for s in arr.shape)
    out += b"".join(_l(s) for s in reversed(strides))
    out += _l(1)
    out += _i(4) + _i(idx + 1) + _s("V 1") + _s("torch.FloatStorage")
    out += _l(arr.size) + arr.tobytes()
    return out


def test_load_hand_authored_bytes(tmp_path):
    """A Sequential{Linear(3->2), ReLU} .t7 built byte-by-byte."""
    w = np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32)  # (out,in)
    b = np.asarray([0.5, -0.5], np.float32)

    linear = _i(4) + _i(10) + _s("V 1") + _s("nn.Linear")
    linear += _i(3) + _i(11) + _i(2)          # field table, 2 entries
    linear += _i(2) + _s("weight") + _float_tensor(12, w)
    linear += _i(2) + _s("bias") + _float_tensor(14, b)

    relu = _i(4) + _i(20) + _s("V 1") + _s("nn.ReLU")
    relu += _i(3) + _i(21) + _i(0)            # empty field table

    modules = _i(3) + _i(30) + _i(2)
    modules += _i(1) + _d(1) + linear         # [1] = linear
    modules += _i(1) + _d(2) + relu           # [2] = relu

    seq = _i(4) + _i(40) + _s("V 1") + _s("nn.Sequential")
    seq += _i(3) + _i(41) + _i(1) + _i(2) + _s("modules") + modules

    path = tmp_path / "seq.t7"
    path.write_bytes(seq)

    module, variables = load_t7(str(path))
    x = np.asarray([[1.0, -1.0, 2.0]], np.float32)
    out, _ = module.apply(variables, x)
    want = np.maximum(x @ w.T + b, 0.0)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


def test_load_raw_tensor_and_table(tmp_path):
    data = _i(3) + _i(1) + _i(2)                       # table, 2 entries
    data += _i(2) + _s("t") + _float_tensor(2, np.arange(6).reshape(2, 3))
    data += _i(2) + _s("n") + _i(1) + _d(7)
    path = tmp_path / "tbl.t7"
    path.write_bytes(data)
    obj = load_t7(str(path))
    assert obj["n"] == 7
    np.testing.assert_array_equal(obj["t"],
                                  np.arange(6, dtype=np.float32).reshape(2, 3))


def test_noncontiguous_tensor_strides(tmp_path):
    """A transposed (column-major-strided) tensor reads correctly."""
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = _i(4) + _i(1) + _s("V 1") + _s("torch.FloatTensor")
    out += _i(2) + _l(3) + _l(2)          # shape (3, 2) ...
    out += _l(1) + _l(3)                  # ... with transposed strides
    out += _l(1)
    out += _i(4) + _i(2) + _s("V 1") + _s("torch.FloatStorage")
    out += _l(arr.size) + arr.tobytes()
    path = tmp_path / "tr.t7"
    path.write_bytes(out)
    got = load_t7(str(path))
    np.testing.assert_array_equal(got, arr.T)


# ---------------------------------------------------------------- roundtrip

def test_tensor_roundtrip(tmp_path):
    for arr in (np.random.RandomState(0).rand(4, 5).astype(np.float32),
                np.arange(24, dtype=np.int64).reshape(2, 3, 4)):
        p = tmp_path / "t.t7"
        save_t7(str(p), arr)
        got = load_t7(str(p))
        assert got.shape == arr.shape
        np.testing.assert_array_equal(got, arr)
    # Torch7 has no 0-d tensors: scalars travel as Lua numbers
    p = tmp_path / "s.t7"
    save_t7(str(p), np.asarray(3.5, np.float64))
    assert load_t7(str(p)) == 3.5


def test_oversized_tensor_header_rejected(tmp_path):
    """A tensor whose shape/strides exceed its storage must raise, not
    read out-of-bounds memory."""
    out = _i(4) + _i(1) + _s("V 1") + _s("torch.FloatTensor")
    out += _i(2) + _l(1000) + _l(1000)
    out += _l(1000) + _l(1)
    out += _l(1)
    out += _i(4) + _i(2) + _s("V 1") + _s("torch.FloatStorage")
    arr = np.zeros(4, np.float32)
    out += _l(arr.size) + arr.tobytes()
    path = tmp_path / "evil.t7"
    path.write_bytes(out)
    with pytest.raises(ValueError, match="exceeds its storage"):
        load_t7(str(path))


def test_truncated_storage_rejected(tmp_path):
    out = _i(4) + _i(1) + _s("V 1") + _s("torch.FloatStorage")
    out += _l(100) + np.zeros(4, np.float32).tobytes()  # claims 100, has 4
    path = tmp_path / "trunc.t7"
    path.write_bytes(out)
    with pytest.raises(ValueError, match="truncated"):
        load_t7(str(path))


def test_table_roundtrip_with_shared_reference(tmp_path):
    shared = np.ones((2, 2), np.float32)
    obj = {"a": shared, "b": shared, "n": 3, "flag": True,
           "nested": {"x": "hello"}}
    p = tmp_path / "tbl.t7"
    save_t7(str(p), obj)
    got = load_t7(str(p))
    assert got["n"] == 3 and got["flag"] is True
    assert got["nested"]["x"] == "hello"
    # the shared tensor is heap-deduplicated: same object back
    assert got["a"] is got["b"]


def test_module_roundtrip_mlp(tmp_path):
    m = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Dropout(0.3),
                      nn.Linear(8, 4), nn.LogSoftMax()).build(KEY)
    p = tmp_path / "mlp.t7"
    save_t7(str(p), m)
    loaded, lvars = load_t7(str(p))
    x = np.random.RandomState(1).rand(3, 6).astype(np.float32)
    a, _ = m.apply(m.variables, x)
    b, _ = loaded.apply(lvars, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_module_roundtrip_convnet(tmp_path):
    m = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([8 * 4 * 4]),
        nn.Linear(8 * 4 * 4, 5),
    ).build(KEY)
    x = np.random.RandomState(2).rand(2, 8, 8, 3).astype(np.float32)
    p = tmp_path / "cnn.t7"
    save_t7(str(p), m)
    loaded, lvars = load_t7(str(p))
    a, _ = m.apply(m.variables, x)
    b, _ = loaded.apply(lvars, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------ torch oracle

def test_conv_layout_against_torch_oracle(tmp_path):
    """Write a Lua-style SpatialConvolution (OIHW weights), load it, and
    check the forward against torch.nn.functional.conv2d."""
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(3)
    w = rng.rand(4, 3, 3, 3).astype(np.float32)       # OIHW
    b = rng.rand(4).astype(np.float32)
    obj = TorchObject("nn.SpatialConvolution", {
        "nInputPlane": 3, "nOutputPlane": 4, "kW": 3, "kH": 3,
        "dW": 1, "dH": 1, "padW": 1, "padH": 1,
        "weight": w, "bias": b})
    p = tmp_path / "conv.t7"
    save_t7(str(p), obj)
    module, variables = load_t7(str(p))

    x = rng.rand(2, 6, 6, 3).astype(np.float32)       # NHWC
    out, _ = module.apply(variables, x)

    ref = torch.nn.functional.conv2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)),
        torch.from_numpy(w), torch.from_numpy(b), padding=1)
    np.testing.assert_allclose(np.asarray(out),
                               ref.numpy().transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-5)


def test_unsupported_class_raises(tmp_path):
    p = tmp_path / "bad.t7"
    save_t7(str(p), TorchObject("nn.FancyUnknownLayer", {}))
    with pytest.raises(ValueError, match="FancyUnknownLayer"):
        load_t7(str(p))


def test_binary_string_lossless_roundtrip(tmp_path):
    # Lua strings are byte strings; non-UTF8 payloads must survive
    # load/save unchanged (ADVICE r3: errors='replace' corrupted them)
    payload = bytes(range(256)).decode("utf-8", errors="surrogateescape")
    p = tmp_path / "bin.t7"
    save_t7(str(p), {"blob": payload, "name": "ok",
                     "raw": bytes(range(256))})  # bytes also writable
    out = load_t7(str(p), to_module=False)
    assert out["name"] == "ok"
    for k in ("blob", "raw"):
        assert out[k].encode("utf-8", errors="surrogateescape") == \
            bytes(range(256))
