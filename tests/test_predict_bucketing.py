"""Predictor shape-bucketed compile cache: a dataset whose size is not
a batch multiple must compile the forward ONCE (the ragged final batch
pads to a bucket instead of presenting jit a novel shape)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim.evaluator import Predictor


def _model():
    m = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    m.build(jax.random.PRNGKey(0)).evaluate()
    return m


def _sample_ds(n):
    rng = np.random.RandomState(0)
    feats = rng.rand(n, 4).astype(np.float32)
    samples = [Sample(feats[i], np.int32(rng.randint(3)))
               for i in range(n)]
    return DataSet.array(samples), feats


def _ragged_minibatch_ds(n, batch):
    """Datasets that yield MiniBatch objects directly skip
    SampleToMiniBatch's padding — the final batch arrives RAGGED at
    the Predictor (the shape that used to trigger a second compile)."""
    rng = np.random.RandomState(1)
    feats = rng.rand(n, 4).astype(np.float32)
    mbs = [MiniBatch(feats[i:i + batch],
                     rng.randint(0, 3, min(batch, n - i)).astype(np.int32))
           for i in range(0, n, batch)]
    assert mbs[-1].size < batch     # genuinely ragged tail
    return DataSet.array(mbs), feats


def test_single_compile_on_ragged_minibatches():
    # 19 rows at batch 8 → MiniBatches of 8, 8, 3: the ragged 3-row
    # tail pads to the 8-bucket instead of compiling a second forward
    m = _model()
    ds, feats = _ragged_minibatch_ds(19, 8)
    pred = Predictor(m, batch_size=8)
    out = pred.predict(ds)
    assert out.shape == (19, 3)
    assert pred.n_traces == 1, pred.n_traces
    # padded rows are sliced off: outputs equal the direct forward
    ref, _ = m.apply(m.variables, jnp.asarray(feats))
    np.testing.assert_allclose(out, np.asarray(ref), atol=1e-6)


def test_sample_dataset_still_single_compile():
    m = _model()
    ds, feats = _sample_ds(19)
    pred = Predictor(m, batch_size=8)
    out = pred.predict(ds)
    assert out.shape == (19, 3)
    assert pred.n_traces == 1
    ref, _ = m.apply(m.variables, jnp.asarray(feats))
    np.testing.assert_allclose(out, np.asarray(ref), atol=1e-6)


def test_predict_class_consistent():
    m = _model()
    ds, _ = _sample_ds(13)
    pred = Predictor(m, batch_size=8)
    cls = pred.predict_class(ds)
    assert cls.shape == (13,)
    assert pred.n_traces == 1


def test_explicit_bucket_sizes():
    # buckets (4, 8): full batches hit 8, the 3-row ragged tail pads
    # to 4 — two buckets used, two compiles, never a third
    m = _model()
    ds, feats = _ragged_minibatch_ds(19, 8)
    pred = Predictor(m, batch_size=8, bucket_sizes=(4, 8))
    out = pred.predict(ds)
    assert pred.n_traces == 2
    ref, _ = m.apply(m.variables, jnp.asarray(feats))
    np.testing.assert_allclose(out, np.asarray(ref), atol=1e-6)
    # a second pass reuses both executables
    out2 = pred.predict(ds)
    assert pred.n_traces == 2
    np.testing.assert_allclose(out2, out, atol=0)


def test_bucket_validation():
    import pytest

    with pytest.raises(ValueError, match="cover"):
        Predictor(_model(), batch_size=8, bucket_sizes=(2, 4))
