"""graftlint tier-1 gate + rule/engine mechanics (ISSUE 6 + 13).

Four layers:

* fixtures — every per-file rule has a known-bad snippet (must fire,
  on exactly the `# BAD`-marked lines) and a known-clean snippet
  (false-positive guard), judged under a fake path inside the rule's
  scope;
* project fixtures (ISSUE 13) — every cross-module ProjectRule has a
  `project_*_bad` / `project_*_clean` mini-package tree (producer /
  consumer / registration split across files) checked the same way;
  the coverage pin makes a 13th rule without fixtures fail;
* mechanics — inline suppressions, baseline parse/format/apply,
  shrink-only staleness, the single-parse/single-build contract of the
  two-pass engine;
* the GATE — the full tree must lint clean modulo the committed
  baseline with ALL rules armed, the baseline may only shrink (stale
  entries fail), and the full-tree two-pass run must stay under the
  ~10 s budget on the 1-core host.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from bigdl_tpu.analysis import (BASELINE_PATH, RULES, apply_baseline,
                                format_baseline, lint_source,
                                load_baseline, parse_baseline, run_lint)
from bigdl_tpu.analysis.engine import BaselineEntry, FileContext, \
    _ensure_rules_loaded

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "graftlint")

_ensure_rules_loaded()

# rule -> (fixture stem, fake in-scope path the snippet is judged at)
RULE_FIXTURES = {
    "trace-env-read": ("trace_env_read", "bigdl_tpu/ops/fixture.py"),
    "telemetry-bypass": ("telemetry_bypass",
                         "bigdl_tpu/models/fixture.py"),
    "hidden-device-sync": ("hidden_device_sync",
                           "bigdl_tpu/serving/fixture.py"),
    "unfenced-timing": ("unfenced_timing", "bigdl_tpu/utils/fixture.py"),
    "retrace-hazard": ("retrace_hazard", "bigdl_tpu/ops/fixture.py"),
    "tf-import-in-core": ("tf_import_in_core",
                          "bigdl_tpu/dataset/fixture.py"),
    "missing-reference-docstring": ("missing_reference_docstring",
                                    "bigdl_tpu/nn/fixture.py"),
    "nondeterministic-drill": ("nondeterministic_drill",
                               "bigdl_tpu/serving/fixture.py"),
}

# ProjectRule -> fixture mini-package stem: tests/fixtures/graftlint/
# <stem>_bad/ and <stem>_clean/ hold a multi-file project tree each
PROJECT_RULE_FIXTURES = {
    "event-kind-contract": "project_event_kind",
    "metric-family-contract": "project_metric_family",
    "donation-flow": "project_donation_flow",
    "lock-discipline": "project_lock_discipline",
}


def _fixture(stem: str, kind: str) -> str:
    with open(os.path.join(FIXTURES, f"{stem}_{kind}.py")) as f:
        return f.read()


def _lint_with(rule_name: str, path: str, source: str):
    return lint_source(path, source, rules=[RULES[rule_name]])


def _expected_lines(source: str):
    return {i for i, line in enumerate(source.splitlines(), start=1)
            if "# BAD" in line}


class TestRuleFixtures:
    def test_every_rule_has_a_fixture(self):
        # adding a rule without fixture coverage fails here: per-file
        # rules need a bad/clean snippet pair, ProjectRules a
        # project_* bad/clean mini-package pair — a 13th rule with
        # neither fails this pin
        from bigdl_tpu.analysis import ProjectRule
        project = {n for n, r in RULES.items()
                   if isinstance(r, ProjectRule)}
        assert set(PROJECT_RULE_FIXTURES) == project
        assert set(RULE_FIXTURES) == set(RULES) - project

    @pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
    def test_true_positives_fire_at_marked_lines(self, rule):
        stem, path = RULE_FIXTURES[rule]
        src = _fixture(stem, "bad")
        expected = _expected_lines(src)
        assert expected, f"{stem}_bad.py has no # BAD markers"
        findings = _lint_with(rule, path, src)
        assert {f.line for f in findings} == expected
        assert all(f.rule == rule and f.path == path for f in findings)
        sev = RULES[rule].severity
        assert all(f.severity == sev for f in findings)

    @pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
    def test_clean_fixture_is_clean(self, rule):
        stem, path = RULE_FIXTURES[rule]
        findings = _lint_with(rule, path, _fixture(stem, "clean"))
        assert findings == [], "\n".join(f.text() for f in findings)

    def test_out_of_scope_path_not_checked(self):
        # the nn docstring rule must never judge serving code
        src = _fixture("missing_reference_docstring", "bad")
        assert _lint_with("missing-reference-docstring",
                          "bigdl_tpu/serving/fixture.py", src) == []


def _project_fixture_paths(stem: str, kind: str):
    d = os.path.join(FIXTURES, f"{stem}_{kind}")
    return sorted(
        os.path.relpath(os.path.join(d, f), ROOT).replace(os.sep, "/")
        for f in os.listdir(d) if f.endswith(".py"))


def _project_expected(paths):
    out = set()
    for rel in paths:
        with open(os.path.join(ROOT, rel)) as f:
            for i, line in enumerate(f, start=1):
                if "# BAD" in line:
                    out.add((rel, i))
    return out


class TestProjectRuleFixtures:
    """ISSUE 13: each cross-module rule fires on its bad mini-package
    at exactly the `# BAD` lines (across files) and stays silent on
    the clean variant."""

    @pytest.mark.parametrize("rule", sorted(PROJECT_RULE_FIXTURES))
    def test_true_positives_fire_at_marked_lines(self, rule):
        stem = PROJECT_RULE_FIXTURES[rule]
        paths = _project_fixture_paths(stem, "bad")
        expected = _project_expected(paths)
        assert expected, f"{stem}_bad has no # BAD markers"
        findings = run_lint(ROOT, paths=paths, rule_names=[rule],
                            project_scope=paths)
        assert {(f.path, f.line) for f in findings} == expected, \
            "\n".join(f.text() for f in findings)
        assert all(f.rule == rule and f.severity == "error"
                   for f in findings)

    @pytest.mark.parametrize("rule", sorted(PROJECT_RULE_FIXTURES))
    def test_clean_fixture_is_clean(self, rule):
        stem = PROJECT_RULE_FIXTURES[rule]
        paths = _project_fixture_paths(stem, "clean")
        findings = run_lint(ROOT, paths=paths, rule_names=[rule],
                            project_scope=paths)
        assert findings == [], "\n".join(f.text() for f in findings)

    def test_bare_subset_run_skips_project_rules(self):
        # without an explicit project_scope, a path-subset run must
        # not judge cross-module questions it cannot answer
        paths = _project_fixture_paths("project_event_kind", "bad")
        findings = run_lint(ROOT, paths=paths,
                            rule_names=["event-kind-contract"])
        assert findings == []

    def test_project_findings_not_filtered_to_path_subset(self):
        # the --changed-only contract: a changed file can break a
        # cross-module contract whose finding anchors in an UNCHANGED
        # file (edit only the registry → orphaned emit sites
        # elsewhere fire) — project findings are reported wherever
        # they land, never filtered to the `paths` subset
        all_paths = _project_fixture_paths("project_event_kind", "bad")
        registry_only = [p for p in all_paths if p.endswith("events.py")]
        findings = run_lint(ROOT, paths=registry_only,
                            rule_names=["event-kind-contract"],
                            project_scope=all_paths)
        assert {(f.path, f.line) for f in findings} \
            == _project_expected(all_paths)


class TestSuppressions:
    SRC = ("def f(step, loss):\n"
           "    print(loss)  # graftlint: disable=telemetry-bypass\n"
           "    print(step)\n")

    def test_same_line_suppression(self):
        found = _lint_with("telemetry-bypass", "bigdl_tpu/x.py",
                           self.SRC)
        assert [f.line for f in found] == [3]  # only the unsuppressed

    def test_previous_comment_line_suppression(self):
        src = ("def f(loss):\n"
               "    # graftlint: disable=telemetry-bypass\n"
               "    print(loss)\n")
        assert _lint_with("telemetry-bypass", "bigdl_tpu/x.py",
                          src) == []

    def test_bare_disable_waives_all_rules(self):
        src = "def f(loss):\n    print(loss)  # graftlint: disable\n"
        assert _lint_with("telemetry-bypass", "bigdl_tpu/x.py",
                          src) == []

    def test_unrelated_rule_name_does_not_suppress(self):
        src = ("def f(loss):\n"
               "    print(loss)  # graftlint: disable=trace-env-read\n")
        found = _lint_with("telemetry-bypass", "bigdl_tpu/x.py", src)
        assert [f.line for f in found] == [2]

    def test_disable_file(self):
        src = ("# graftlint: disable-file=telemetry-bypass\n"
               "def f(a, b):\n    print(a)\n    print(b)\n")
        assert _lint_with("telemetry-bypass", "bigdl_tpu/x.py",
                          src) == []

    def test_suppression_table_parsing(self):
        ctx = FileContext("bigdl_tpu/x.py", self.SRC)
        assert ctx.suppressions.suppressed("telemetry-bypass", 2)
        assert not ctx.suppressions.suppressed("telemetry-bypass", 3)
        assert not ctx.suppressions.suppressed("trace-env-read", 2)


class TestBaseline:
    TEXT = ('# comment\n\n[[finding]]\nrule = "telemetry-bypass"\n'
            'path = "bigdl_tpu/a.py"\ncount = 2\n'
            'reason = "legacy CLI"\n\n[[finding]]\n'
            'rule = "trace-env-read"\npath = "bigdl_tpu/b.py"\n')

    def test_parse(self):
        entries = parse_baseline(self.TEXT)
        assert [(e.rule, e.path, e.count) for e in entries] == [
            ("telemetry-bypass", "bigdl_tpu/a.py", 2),
            ("trace-env-read", "bigdl_tpu/b.py", 1)]
        assert entries[0].reason == "legacy CLI"

    def test_format_roundtrip(self):
        entries = parse_baseline(self.TEXT)
        assert parse_baseline(format_baseline(entries)) == entries

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_baseline("rule = oops, no table header")

    def test_parse_hash_inside_string_value(self):
        # '#' inside a quoted value is data, not a comment
        text = ('[[finding]]\nrule = "telemetry-bypass"\n'
                'path = "bigdl_tpu/a.py"\n'
                'reason = "fixed by PR #12"  # trailing comment ok\n'
                'count = 2  # inline comment on an int\n')
        (e,) = parse_baseline(text)
        assert e.reason == "fixed by PR #12" and e.count == 2

    def test_parse_rejects_unterminated_string(self):
        with pytest.raises(ValueError, match="unterminated"):
            parse_baseline('[[finding]]\nrule = "oops\npath = "a"\n')

    def test_parse_rejects_trailing_garbage_after_string(self):
        with pytest.raises(ValueError, match="trailing"):
            parse_baseline('[[finding]]\nrule = "a" junk\npath = "b"\n')

    def _findings(self, n, rule="telemetry-bypass",
                  path="bigdl_tpu/a.py"):
        from bigdl_tpu.analysis import Finding
        return [Finding(rule, path, 10 + i, 1, "m", "error")
                for i in range(n)]

    def test_apply_subtracts_counts(self):
        baseline = [BaselineEntry("telemetry-bypass",
                                  "bigdl_tpu/a.py", 2)]
        left, stale = apply_baseline(self._findings(3), baseline)
        assert len(left) == 1 and stale == []

    def test_stale_entry_detected(self):
        # the finding was fixed -> the entry must be deleted
        baseline = [BaselineEntry("telemetry-bypass",
                                  "bigdl_tpu/a.py", 2)]
        left, stale = apply_baseline(self._findings(1), baseline)
        assert left == [] and stale == baseline

    def test_duplicate_entries_sum_counts(self):
        # hand-split entries for one (rule, path) must pool, not
        # overwrite each other
        baseline = [
            BaselineEntry("telemetry-bypass", "bigdl_tpu/a.py", 1,
                          "first"),
            BaselineEntry("telemetry-bypass", "bigdl_tpu/a.py", 1,
                          "second")]
        left, stale = apply_baseline(self._findings(2), baseline)
        assert left == [] and stale == []
        # and staleness of a pooled key reports once
        left, stale = apply_baseline(self._findings(1), baseline)
        assert left == [] and len(stale) == 1

    def test_missing_baseline_file_is_empty(self):
        assert load_baseline(os.path.join(ROOT, "no/such/file.toml")) \
            == []


class TestFullTreeGate:
    """THE tier-1 contract: tree clean modulo baseline with all 12
    rules armed, baseline only shrinks, the two-pass run parses every
    file exactly once and builds ONE ProjectContext, and the pass
    stays inside the runtime budget."""

    def test_full_tree_clean_and_budget(self):
        from bigdl_tpu.analysis import engine as eng
        from bigdl_tpu.analysis import project as prj
        parse_counts: dict = {}
        builds = []
        eng.PARSE_OBSERVERS.append(
            lambda p: parse_counts.__setitem__(
                p, parse_counts.get(p, 0) + 1))
        prj.BUILD_OBSERVERS.append(builds.append)
        try:
            t0 = time.perf_counter()
            findings = run_lint(ROOT)
            elapsed = time.perf_counter() - t0
        finally:
            eng.PARSE_OBSERVERS.pop()
            prj.BUILD_OBSERVERS.pop()
        baseline = load_baseline(os.path.join(ROOT, BASELINE_PATH))
        left, stale = apply_baseline(findings, baseline)
        assert left == [], "unbaselined graftlint findings:\n" + \
            "\n".join(f.text() for f in left)
        assert stale == [], (
            "stale baseline entries (finding fixed -> DELETE the "
            "entry; the baseline only shrinks): " +
            ", ".join(f"{e.rule}@{e.path}" for e in stale))
        # the shared-single-parse contract (ISSUE 13): pass 2 reuses
        # pass 1's FileContexts — no file is ever parsed twice, and
        # exactly one ProjectContext is built per run
        multi = {p: n for p, n in parse_counts.items() if n != 1}
        assert not multi, f"files parsed more than once: {multi}"
        assert parse_counts, "parse observer saw no files"
        assert len(builds) == 1, \
            f"ProjectContext built {len(builds)}x (expected once)"
        assert len(builds[0].files) == len(parse_counts)
        # ~10 s contract for the full-tree two-pass run on the 1-core
        # host with all 12 rules armed (pure ast walk; measured ~4 s —
        # 10 s leaves load headroom)
        assert elapsed < 10.0, f"graftlint full tree took {elapsed:.1f}s"

    def test_all_twelve_rules_armed(self):
        # the gate means nothing if a rule silently fell out of the
        # registry: 8 per-file rules (ISSUE 6) + 4 ProjectRules
        # (ISSUE 13)
        from bigdl_tpu.analysis import ProjectRule
        project = {n for n, r in RULES.items()
                   if isinstance(r, ProjectRule)}
        assert len(RULES) == 12
        assert project == set(PROJECT_RULE_FIXTURES)

    def test_baseline_entries_reference_real_rules(self):
        baseline = load_baseline(os.path.join(ROOT, BASELINE_PATH))
        for e in baseline:
            assert e.rule in RULES, f"unknown rule in baseline: {e.rule}"


class TestCli:
    def test_cli_full_tree_json_exits_zero(self):
        # the acceptance-criteria invocation, via the real entry point
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "graftlint.py"),
             "--format", "json"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["findings"] == []
        assert payload["stale_baseline"] == []
        assert payload["counts"] == {"error": 0, "warning": 0}

    def test_cli_write_baseline_refuses_subset_runs(self):
        # a subset snapshot would silently drop out-of-subset entries
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graftlint_cli", os.path.join(ROOT, "scripts",
                                          "graftlint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main(["--write-baseline", "bigdl_tpu/ops"]) == 2
        assert mod.main(["--write-baseline",
                         "--rules", "telemetry-bypass"]) == 2

    def test_cli_sarif_format(self):
        # SARIF over a subtree (fast): valid 2.1.0 skeleton, every
        # registered rule advertised, zero results on clean code
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "graftlint.py"),
             "bigdl_tpu/obs", "--format", "sarif"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert set(RULES) <= rule_ids
        assert run["results"] == []

    def test_cli_changed_only(self):
        # against HEAD the changed set is whatever the working tree
        # carries — a clean tree must stay clean (and an empty set
        # short-circuits); a bad ref is usage trouble (exit 2)
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "graftlint.py"),
             "--changed-only", "HEAD"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=ROOT)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "graftlint.py"),
             "--changed-only", "no-such-ref-xyz"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=ROOT)
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "Traceback" not in proc.stderr

    def test_cli_missing_path_exits_two(self):
        # usage trouble is the documented exit code 2, not a traceback
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts",
                                          "graftlint.py"),
             "bigdl_tpu/no_such_file.py"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "Traceback" not in proc.stderr
        assert "not a python file" in proc.stderr
