"""Multi-tenant fleet isolation (ISSUE 19): deterministic token-bucket
admission, weighted-fair release, per-tenant KV quotas, model-tagged
engine groups (cross-group failover refusal), and the fleet-wide
compile contract with tenancy armed.

The headline guarantee — a noisy tenant contained by ITS OWN budget
while the quiet tenant's tokens stay bitwise identical — is drilled
end-to-end in scripts/fault_drill.py (tenant_noisy leg, tier-1 via
test_fault_drill); this file covers the machinery at unit granularity.
"""

import jax
import pytest

from bigdl_tpu import obs
from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.serving import (EngineRouter, InferenceEngine, Request,
                               TenancyController, TenantSpec,
                               TokenBucket, VisionEngine)
from bigdl_tpu.utils import faults

_LM = None


def _lm():
    global _LM
    if _LM is None:
        _LM = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=2,
                       max_len=64)
        _LM.build(jax.random.PRNGKey(0))
    return _LM


def _engine(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_buckets", (8,))
    return InferenceEngine(_lm(), **kw)


@pytest.fixture(autouse=True)
def _fresh_obs():
    prev = obs.set_enabled(True)
    obs.reset_all()
    faults.set_plan(None)
    yield
    faults.set_plan(None)
    obs.reset_all()
    obs.set_enabled(prev)


# --------------------------------------------------------- token bucket

class TestTokenBucket:
    def test_deterministic_refill_under_injected_clock(self):
        clk = {"t": 0.0}
        b = TokenBucket(2.0, 0.5, clock=lambda: clk["t"])
        assert b.try_take(1.0) and b.try_take(1.0)
        assert not b.try_take(1.0)          # empty at t=0
        clk["t"] = 1.0
        assert b.peek() == pytest.approx(0.5)
        assert not b.try_take(1.0)          # half a token is not one
        clk["t"] = 2.0
        assert b.try_take(1.0)
        clk["t"] = 100.0                    # refill caps at capacity
        assert b.peek() == pytest.approx(2.0)
        # two buckets replaying the same clock script agree exactly
        clk2 = {"t": 0.0}
        b2 = TokenBucket(2.0, 0.5, clock=lambda: clk2["t"])
        for t in (0.0, 0.7, 1.3, 2.9, 4.0):
            clk["t"] = clk2["t"] = 200.0 + t
            assert b.try_take(1.0) == b2.try_take(1.0)
            assert b.peek() == b2.peek()

    def test_give_refunds_within_capacity(self):
        clk = {"t": 0.0}
        b = TokenBucket(1.0, 1.0, clock=lambda: clk["t"])
        assert b.try_take(1.0)
        b.give(1.0)
        assert b.try_take(1.0)              # refunded token spendable
        b.give(5.0)                         # refund never overfills
        assert b.peek() == pytest.approx(1.0)

    def test_validates_constructor(self):
        clk = {"t": 0.0}
        with pytest.raises(ValueError):
            TokenBucket(0.0, 1.0, clock=lambda: clk["t"])
        with pytest.raises(ValueError):
            TokenBucket(1.0, -1.0, clock=lambda: clk["t"])


# ------------------------------------------------------------------ WFQ

def _ctl(specs, clk):
    return TenancyController(specs, clock=lambda: clk["t"])


def _treq(i, tenant, **kw):
    kw.setdefault("prompt", [1 + i % 7, 2 + i % 5])
    kw.setdefault("max_new_tokens", 2)
    return Request(id=i, tenant=tenant, **kw)


class TestWFQ:
    def test_service_shares_follow_weights(self):
        """Both tenants fully backlogged with generous buckets: the
        release sequence interleaves by finish tag, so a weight-2
        tenant drains exactly twice as fast as a weight-1 tenant."""
        clk = {"t": 0.0}
        ctl = _ctl([TenantSpec("fast", weight=2.0, bucket_capacity=64,
                               refill_rate=64),
                    TenantSpec("slow", weight=1.0, bucket_capacity=64,
                               refill_rate=64)], clk)
        for i in range(12):
            ctl.offer(_treq(i, "fast"))
            ctl.offer(_treq(100 + i, "slow"))
        out = ctl.release({"default": 12})
        by = [ctl.resolve(e.request.tenant) for e in out]
        assert by.count("fast") == 8 and by.count("slow") == 4

    def test_noisy_submit_ratio_never_starves_quiet(self):
        """10:1 noisy/quiet submit ratio, equal weights: the quiet
        tenant's single head releases among the FIRST TWO released —
        arrival mass buys no extra share."""
        clk = {"t": 0.0}
        ctl = _ctl([TenantSpec("noisy", bucket_capacity=64,
                               refill_rate=64),
                    TenantSpec("quiet", bucket_capacity=64,
                               refill_rate=64)], clk)
        for i in range(10):
            ctl.offer(_treq(i, "noisy"))
        ctl.offer(_treq(50, "quiet"))
        out = ctl.release({"default": 2})
        assert {ctl.resolve(e.request.tenant) for e in out} \
            == {"noisy", "quiet"}

    def test_empty_bucket_skipped_not_waited_on(self):
        """A throttled tenant's head must never head-of-line-block the
        others: with 'broke' unable to pay, every release goes to
        'funded' even though broke's finish tags are smaller."""
        clk = {"t": 0.0}
        ctl = _ctl([TenantSpec("broke", bucket_capacity=1.0,
                               refill_rate=0.001),
                    TenantSpec("funded", bucket_capacity=64,
                               refill_rate=64)], clk)
        ctl.offer(_treq(0, "broke"))
        ctl.offer(_treq(1, "broke"))        # tags 1, 2
        for i in range(4):
            ctl.offer(_treq(10 + i, "funded"))
        first = ctl.release({"default": 1})
        assert [e.request.tenant for e in first] == ["broke"]
        rest = ctl.release({"default": 3})  # broke's bucket now empty
        assert [e.request.tenant for e in rest] == ["funded"] * 3
        assert ctl.queued("broke") == 1

    def test_group_room_is_scoped(self):
        """Release honours per-GROUP room: a room with only vision
        capacity releases the vision-tagged head and leaves the LM
        head queued, and vice versa (a full group never blocks the
        other group's tenants)."""
        clk = {"t": 0.0}
        ctl = _ctl([TenantSpec("lmt", bucket_capacity=64,
                               refill_rate=64),
                    TenantSpec("vist", bucket_capacity=64,
                               refill_rate=64)], clk)
        ctl.offer(_treq(0, "lmt"))
        ctl.offer(_treq(1, "vist", model_tag="vision"))
        out = ctl.release({"vision": 4})
        assert [e.request.model_tag for e in out] == ["vision"]
        out = ctl.release({"default": 4})
        assert [e.request.model_tag for e in out] == [None]

    def test_two_controllers_replay_identically(self):
        """Same offer/clock/release script on two fresh controllers →
        identical release id sequences and stats (the byte-identity
        the drills pin, at unit granularity)."""
        def script(ctl, clk):
            order = []
            for i in range(6):
                ctl.offer(_treq(i, "a" if i % 3 else "b"))
            for t in (0.5, 1.0, 2.5):
                clk["t"] = t
                order += [e.request.id
                          for e in ctl.release({"default": 1})]
            return order, {n: ctl.stats(n) for n in ctl.tenants}

        specs = [TenantSpec("a", bucket_capacity=2.0, refill_rate=1.0),
                 TenantSpec("b", bucket_capacity=2.0, refill_rate=1.0)]
        clk1, clk2 = {"t": 0.0}, {"t": 0.0}
        r1 = script(_ctl(specs, clk1), clk1)
        r2 = script(_ctl(specs, clk2), clk2)
        assert r1 == r2

    def test_unknown_tenant_rejected(self):
        clk = {"t": 0.0}
        ctl = _ctl([TenantSpec("a")], clk)
        with pytest.raises(ValueError):
            ctl.offer(_treq(0, "ghost"))
        with pytest.raises(ValueError):
            ctl.offer(_treq(1, None))       # no 'default' spec either


# -------------------------------------------------------------- quotas

class TestKVQuota:
    def test_quota_bounds_concurrent_blocks_per_tenant(self):
        """Tenant 'a' is capped at one exclusive KV block: its second
        request waits for the first to finish while tenant 'b' admits
        immediately — and everyone still completes."""
        eng = _engine(slots=3, tenant_kv_quotas={"a": 1})
        reqs = [Request(id=0, prompt=[1, 2, 3], max_new_tokens=6,
                        tenant="a", seed=1),
                Request(id=1, prompt=[4, 5, 6], max_new_tokens=6,
                        tenant="a", seed=2),
                Request(id=2, prompt=[7, 8, 9], max_new_tokens=6,
                        tenant="b", seed=3)]
        for r in reqs:
            eng.submit(r)
        eng.step()
        active = {r.id for r in eng._req if r is not None}
        assert 0 in active and 2 in active      # b admits beside a
        assert 1 not in active                  # a's second: quota
        throttles = obs.get_event_log().events("tenant_throttled")
        assert [e["action"] for e in throttles] == ["kv_quota"]
        assert throttles[0]["tenant"] == "a"
        assert throttles[0]["request"] == 1
        out = {r.id: r for r in eng.run()}
        assert all(r.status == "done" for r in out.values())
        # one throttle event per request id, not per blocked round
        throttles = obs.get_event_log().events("tenant_throttled")
        assert len(throttles) == 1

    def test_quota_validates_constructor(self):
        with pytest.raises(ValueError):
            _engine(tenant_kv_quotas={"a": 0})


# ------------------------------------------------- groups and failover

class TestEngineGroups:
    def test_dispatch_routes_by_model_tag(self):
        lm = _engine()                      # group "default"
        vis = VisionEngine(lambda f: f @ jax.numpy.ones((4, 3)),
                           batch=2, feature_len=4)
        router = EngineRouter([lm, vis])
        assert sorted(router.groups) == ["default", "vision"]
        a = router.submit(Request(prompt=[1, 2, 3], max_new_tokens=2,
                                  seed=1))
        b = router.submit(Request(prompt=[1, 2], model_tag="vision"))
        out = {r.id: r for r in router.run()}
        assert out[a].status == "done" and len(out[a].tokens) == 2
        assert out[b].status == "done"
        assert out[b].finish_reason == "classified"
        assert lm.stats["requests_done"] == 1
        assert vis.stats["requests_done"] == 1
        assert vis.stats["classified"] == 1

    def test_no_engine_for_group_raises(self):
        router = EngineRouter([_engine()])
        with pytest.raises(Exception) as ei:
            router.submit(Request(prompt=[1, 2], model_tag="vision"))
        assert "vision" in str(ei.value)

    def test_cross_group_failover_refused(self):
        """The only engine in the request's group dies mid-decode; a
        HEALTHY engine in another group must NOT pick the request up
        (PR-16 layout_family discipline, group-scoped): the request
        fails rather than crossing groups."""
        e0 = _engine(step_timeout_s=0.05)              # "default"
        e1 = _engine(model_tag="other")                # healthy
        router = EngineRouter([e0, e1])
        faults.set_plan(faults.FaultPlan("serve_slow@1"))
        try:
            out = router.run([Request(prompt=[1, 2, 3],
                                      max_new_tokens=4, seed=1)])
        finally:
            faults.set_plan(None)
        assert e0.degraded is not None
        assert e1.degraded is None                     # untouched
        assert [r.status for r in out] == ["failed"]
        assert router.stats["failover_lost"] == 1
        assert e1.stats["requests_done"] == 0

    def test_add_engine_resolves_group_factory(self):
        def lm_factory():
            return _engine()

        router = EngineRouter([_engine()],
                              engine_factory={"default": lm_factory})
        e = router.add_engine(group="default")
        # the untagged newcomer is tagged with its group at admission
        assert len(router.engines) == 2
        assert EngineRouter._group_of(e) == "default"
        with pytest.raises(ValueError) as ei:
            router.add_engine(group="vision")
        assert "default" in str(ei.value)   # names known groups

    def test_move_engine_requires_same_model(self):
        e0, e1 = _engine(), _engine(model_tag="replica")
        fresh = build_lm(vocab_size=50, dim=16, num_heads=2,
                         num_layers=1, max_len=32)
        fresh.build(jax.random.PRNGKey(9))
        alien = InferenceEngine(fresh, slots=2, prefill_buckets=(8,),
                                model_tag="alien")
        router = EngineRouter([e0, e1, alien])
        with pytest.raises(ValueError):
            router.move_engine(e0, "alien")   # different model object
        router.move_engine(e0, "replica")     # same model: allowed
        assert e0.model_tag == "replica"
        ev = obs.get_event_log().events("group_rebalance")
        assert len(ev) == 1 and ev[0]["action"] == "move"


# ----------------------------------------------------- compile contract

class TestCompileContractWithTenancy:
    def test_group_switch_compiles_nothing(self):
        """Tenancy armed over two groups sharing one model: wave 1
        pays #buckets prefills + 1 decode IN TOTAL; a second wave
        through the OTHER group — and a move_engine group switch —
        compile zero new executables."""
        fresh = build_lm(vocab_size=50, dim=16, num_heads=2,
                         num_layers=1, max_len=32)
        fresh.build(jax.random.PRNGKey(1))

        def eng(**kw):
            return InferenceEngine(fresh, slots=2,
                                   prefill_buckets=(8, 16), **kw)

        clk = {"t": 0.0}
        tick = lambda: clk["t"]  # noqa: E731
        ctl = TenancyController(
            [TenantSpec("a", bucket_capacity=64, refill_rate=64),
             TenantSpec("b", bucket_capacity=64, refill_rate=64)],
            clock=tick)
        e0, e1 = eng(clock=tick), eng(model_tag="replica", clock=tick)
        router = EngineRouter([e0, e1], clock=tick, tenancy=ctl)

        from bigdl_tpu.serving.engine import _TRACES
        traces0 = dict(_TRACES)

        def wave(tag, base):
            # prompt lengths straddle both buckets (8 and 16)
            ids = [router.submit(Request(
                prompt=[(base + i + j) % 40 + 1
                        for j in range(3 if i % 2 else 10)],
                max_new_tokens=3, seed=base + i, model_tag=tag,
                tenant="a" if i % 2 else "b")) for i in range(4)]
            rounds = 0
            while not all(i in router.completed for i in ids):
                rounds += 1
                assert rounds < 200
                clk["t"] += 0.5
                router.step()
            return [router.completed[i] for i in ids]

        out = wave(None, 1)                     # group "default"
        assert all(r.status == "done" for r in out)
        assert _TRACES["prefill"] - traces0["prefill"] == 2
        assert _TRACES["decode"] - traces0["decode"] == 1
        traces1 = dict(_TRACES)
        out = wave("replica", 20)               # group switch: wave 2
        assert all(r.status == "done" for r in out)
        router.move_engine(e0, "replica")       # and a group move
        out = wave("replica", 40)
        assert all(r.status == "done" for r in out)
        assert dict(_TRACES) == traces1         # zero new executables


# -------------------------------------------------------- vision engine

class TestVisionEngine:
    def _predict(self, feature_len=4, classes=3):
        w = jax.random.normal(jax.random.PRNGKey(2),
                              (feature_len, classes))

        def predict_fn(feats, _w=w):
            return feats @ _w
        return predict_fn

    def test_classifies_deterministically(self):
        fn = self._predict()
        eng = VisionEngine(fn, batch=2, feature_len=4)
        reqs = [Request(prompt=[i + 1, i + 2], id=i) for i in range(3)]
        out = {r.id: r for r in eng.run(reqs)}
        assert all(r.status == "done" for r in out.values())
        assert all(len(r.tokens) == 1 for r in out.values())
        eng2 = VisionEngine(fn, batch=2, feature_len=4)
        out2 = {r.id: r for r in eng2.run(
            [Request(prompt=[i + 1, i + 2], id=i) for i in range(3)])}
        assert [out[i].tokens for i in range(3)] \
            == [out2[i].tokens for i in range(3)]
        # same predict_fn + shape → the jitted forward is SHARED
        assert eng2.stats["forward_traces"] == 0

    def test_rejects_oversize_and_empty_prompts(self):
        eng = VisionEngine(self._predict(), batch=2, feature_len=4)
        with pytest.raises(ValueError):
            eng.submit(Request(prompt=[]))
        with pytest.raises(ValueError):
            eng.submit(Request(prompt=[1, 2, 3, 4, 5]))


# ------------------------------------------------------ router tenancy

class TestRouterTenancy:
    def test_clock_identity_enforced(self):
        clk = {"t": 0.0}
        ctl = TenancyController([TenantSpec("a")],
                                clock=lambda: clk["t"])
        with pytest.raises(ValueError):
            EngineRouter([_engine()], clock=lambda: clk["t"],
                         tenancy=ctl)

    def test_shed_rides_step_and_bills_its_tenant(self):
        """A max_pending shed settles through step() with status
        'shed' (the loadgen accounting contract) and bumps only its
        own tenant's counters."""
        clk = {"t": 0.0}
        tick = lambda: clk["t"]  # noqa: E731
        ctl = TenancyController(
            [TenantSpec("t", bucket_capacity=1.0, refill_rate=0.25,
                        max_pending=2)], clock=tick)
        router = EngineRouter([_engine(clock=tick)], clock=tick,
                              tenancy=ctl)
        a = router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                  tenant="t", seed=1))
        b = router.submit(Request(prompt=[3, 4], max_new_tokens=2,
                                  tenant="t", seed=2))   # queues (2)
        c = router.submit(Request(prompt=[5, 6], max_new_tokens=2,
                                  tenant="t", seed=3))   # shed
        out = {}
        rounds = 0
        while len(out) < 3:
            rounds += 1
            assert rounds < 100
            clk["t"] += 0.5
            for r in router.step():
                out[r.id] = r
        assert out[a].status == "done"
        assert out[b].status == "done"       # refill eventually pays
        assert out[c].status == "shed"
        assert out[c].finish_reason == "throttled"
        assert ctl.stats("t")["shed"] == 1
        assert router.health()["tenants"]["t"]["shed"] == 1
