"""Persistent-RNN fused scan kernel parity (ops/fused_rnn.py).

CPU tier-1 coverage for the Mosaic kernels via Pallas interpret mode
(the flash-attention testing convention): forward AND gradients against
the `lax.scan` fallback (the exact math the kernel replaces) and the
torch oracle, in fp32 and bf16. The kernels' grid/index-map machinery
runs unchanged under interpret — only the Mosaic lowering itself needs
the real chip (scripts/validate_tpu.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.ops import fused_rnn

KEY = jax.random.PRNGKey(0)


def _rand(rng, *shape, dtype=np.float32, scale=1.0):
    return jnp.asarray((scale * rng.randn(*shape)).astype(dtype))


class TestLSTMScan:
    @pytest.mark.parametrize("n,t,h,block_n", [
        (4, 6, 8, None),      # single tile
        (5, 7, 8, 4),         # odd batch → sublane padding
        (32, 5, 8, 16),       # genuine multi-tile grid (n//block_n = 2)
        (3, 1, 8, None),      # T == 1 edge (init and emit same step)
    ])
    def test_fwd_matches_xla(self, n, t, h, block_n):
        rng = np.random.RandomState(0)
        zx = _rand(rng, n, t, 4 * h)
        w = _rand(rng, h, 4 * h, scale=0.3)
        out = fused_rnn.lstm_scan(zx, w, impl="interpret",
                                  block_n=block_n)
        ref = fused_rnn._lstm_scan_xla(zx, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("n,block_n", [
        (5, 4),    # padding
        (32, 16),  # multi-tile grid: per-tile dW emission + sum
    ])
    def test_grads_match_xla(self, n, block_n):
        rng = np.random.RandomState(1)
        zx = _rand(rng, n, 6, 32)
        w = _rand(rng, 8, 32, scale=0.3)

        def loss(fn):
            return lambda zx, w: jnp.sum(jnp.sin(fn(zx, w)))

        gk = jax.grad(loss(lambda zx, w: fused_rnn.lstm_scan(
            zx, w, impl="interpret", block_n=block_n)),
            argnums=(0, 1))(zx, w)
        gr = jax.grad(loss(fused_rnn._lstm_scan_xla),
                      argnums=(0, 1))(zx, w)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_bf16_close_to_fp32_oracle(self):
        """bf16 kernel vs the fp32 scan: agreement within bf16
        resolution (the training path's dtype)."""
        rng = np.random.RandomState(2)
        zx = _rand(rng, 4, 5, 32)
        w = _rand(rng, 8, 32, scale=0.3)
        out = fused_rnn.lstm_scan(zx.astype(jnp.bfloat16),
                                  w.astype(jnp.bfloat16),
                                  impl="interpret")
        ref = fused_rnn._lstm_scan_xla(zx, w)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=0.05, atol=0.05)
        g = jax.grad(lambda z: jnp.sum(fused_rnn.lstm_scan(
            z, w.astype(jnp.bfloat16), impl="interpret")))(
                zx.astype(jnp.bfloat16))
        gr = jax.grad(lambda z: jnp.sum(
            fused_rnn._lstm_scan_xla(z, w)))(zx)
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(gr), rtol=0.1, atol=0.1)

    def test_wired_recurrent_matches_torch(self):
        """The full hoisted LSTM path through Recurrent with the fused
        kernel forced (interpret) against torch.nn.LSTM — the same
        oracle as test_recurrent.test_lstm_matches_torch."""
        torch = pytest.importorskip("torch")
        m = nn.Recurrent(nn.LSTM(3, 4), fused="interpret").build(KEY)
        m = m.evaluate()
        p = m.variables["params"]["cell"]
        w = np.asarray(p["weight"])  # (3+4, 4*4) order i,f,g,o
        b = np.asarray(p["bias"])
        x = np.random.RandomState(0).randn(2, 6, 3).astype(np.float32)
        ours = np.asarray(m.forward(jnp.asarray(x)))

        ref = torch.nn.LSTM(3, 4, batch_first=True)
        with torch.no_grad():
            ref.weight_ih_l0.copy_(torch.tensor(w[:3].T))
            ref.weight_hh_l0.copy_(torch.tensor(w[3:].T))
            ref.bias_ih_l0.copy_(torch.tensor(b))
            ref.bias_hh_l0.zero_()
        out, _ = ref(torch.tensor(x))
        np.testing.assert_allclose(ours, out.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestBiLSTMScan:
    @staticmethod
    def _ref(zxf, zxb, wf, wb):
        ys_f = fused_rnn._lstm_scan_xla(zxf, wf)
        ys_b = jnp.flip(fused_rnn._lstm_scan_xla(
            jnp.flip(zxb, axis=1), wb), axis=1)
        return ys_f, ys_b

    def test_fwd_matches_flip_scan(self):
        rng = np.random.RandomState(3)
        zxf, zxb = (_rand(rng, 4, 6, 32) for _ in range(2))
        wf, wb = (_rand(rng, 8, 32, scale=0.3) for _ in range(2))
        yf, yb = fused_rnn.bilstm_scan(zxf, zxb, wf, wb,
                                       impl="interpret")
        rf, rb = self._ref(zxf, zxb, wf, wb)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(rf),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(yb), np.asarray(rb),
                                   rtol=1e-5, atol=1e-6)
        # the xla fallback branch (what validate_tpu oracles the chip
        # against) must itself match this independent flip-scan oracle
        ff, fb = fused_rnn.bilstm_scan(zxf, zxb, wf, wb, impl="xla")
        np.testing.assert_allclose(np.asarray(ff), np.asarray(rf),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(fb), np.asarray(rb),
                                   rtol=1e-6)

    def test_grads_match_flip_scan(self):
        rng = np.random.RandomState(4)
        args = (_rand(rng, 3, 5, 32), _rand(rng, 3, 5, 32),
                _rand(rng, 8, 32, scale=0.3),
                _rand(rng, 8, 32, scale=0.3))

        def loss(fn):
            def f(*a):
                yf, yb = fn(*a)
                return jnp.sum(jnp.sin(yf)) + jnp.sum(jnp.cos(yb))
            return f

        gk = jax.grad(loss(lambda *a: fused_rnn.bilstm_scan(
            *a, impl="interpret")), argnums=(0, 1, 2, 3))(*args)
        gr = jax.grad(loss(self._ref), argnums=(0, 1, 2, 3))(*args)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_wired_birecurrent_one_launch(self):
        """BiRecurrent with fused='interpret' takes the one-launch path
        and matches the lax.scan BiRecurrent exactly."""
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(2, 7, 5).astype(np.float32))
        base = nn.BiRecurrent(nn.LSTM(5, 6), fused=False)
        v = base.init(jax.random.PRNGKey(7))
        ref, _ = base.apply(v, x)
        m = nn.BiRecurrent(nn.LSTM(5, 6), fused="interpret")
        got = m._fused_bidir(v, x)
        assert got is not None, "fused bidirectional path not taken"
        out, _ = m.apply(v, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


class TestGRUScan:
    @staticmethod
    def _args(rng, n=4, t=6, h=8):
        return (_rand(rng, n, t, 2 * h), _rand(rng, n, t, h),
                _rand(rng, h, 2 * h, scale=0.3),
                _rand(rng, h, h, scale=0.3))

    def test_fwd_matches_xla(self):
        args = self._args(np.random.RandomState(6))
        out = fused_rnn.gru_scan(*args, impl="interpret")
        ref = fused_rnn._gru_scan_xla(*args)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match_xla(self):
        args = self._args(np.random.RandomState(7))

        def loss(fn):
            return lambda *a: jnp.sum(jnp.sin(fn(*a)))

        gk = jax.grad(loss(lambda *a: fused_rnn.gru_scan(
            *a, impl="interpret")), argnums=(0, 1, 2, 3))(*args)
        gr = jax.grad(loss(fused_rnn._gru_scan_xla),
                      argnums=(0, 1, 2, 3))(*args)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_wired_recurrent_matches_scan(self):
        """Recurrent(GRU, fused='interpret') == the lax.scan GRU path
        (which test_recurrent oracles against numpy)."""
        rng = np.random.RandomState(8)
        x = jnp.asarray(rng.randn(2, 5, 3).astype(np.float32))
        base = nn.Recurrent(nn.GRU(3, 4), fused=False)
        v = base.init(jax.random.PRNGKey(9))
        ref, _ = base.apply(v, x)
        m = nn.Recurrent(nn.GRU(3, 4), fused="interpret")
        out, _ = m.apply(v, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_bench_shape_sweep_interpret():
    """The bench.py BiLSTM hidden size (H=128) through the kernel at
    several batch tiles (~2 s interpreted — cheap enough for tier-1);
    the on-chip counterpart lives in scripts/validate_tpu.py."""
    rng = np.random.RandomState(0)
    h = 128
    zxf, zxb = (_rand(rng, 8, 16, 4 * h, scale=0.1) for _ in range(2))
    wf, wb = (_rand(rng, h, 4 * h, scale=0.05) for _ in range(2))
    rf = fused_rnn._lstm_scan_xla(zxf, wf)
    rb = jnp.flip(fused_rnn._lstm_scan_xla(jnp.flip(zxb, axis=1), wb),
                  axis=1)
    for bn in (8, 16):
        yf, yb = fused_rnn.bilstm_scan(zxf, zxb, wf, wb,
                                       impl="interpret", block_n=bn)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(rf),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(yb), np.asarray(rb),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_full_bench_shape_interpret():
    """The FULL bench.py BiLSTM shape (B=128, T=128, H=128) through the
    fused bidirectional kernel + backward in interpret mode — genuinely
    long on one CPU core, so tier-2 (`-m slow`): run before trusting a
    kernel change enough to burn a TPU measurement session on it."""
    rng = np.random.RandomState(0)
    h = 128
    zxf, zxb = (_rand(rng, 128, 128, 4 * h, scale=0.05)
                for _ in range(2))
    wf, wb = (_rand(rng, h, 4 * h, scale=0.02) for _ in range(2))

    def loss(fn):
        def f(*a):
            yf, yb = fn(*a)
            return jnp.sum(jnp.sin(yf)) + jnp.sum(jnp.cos(yb))
        return f

    def ref(zxf, zxb, wf, wb):
        return (fused_rnn._lstm_scan_xla(zxf, wf),
                jnp.flip(fused_rnn._lstm_scan_xla(
                    jnp.flip(zxb, axis=1), wb), axis=1))

    gk = jax.grad(loss(lambda *a: fused_rnn.bilstm_scan(
        *a, impl="interpret")), argnums=(0, 2))(zxf, zxb, wf, wb)
    gr = jax.grad(loss(ref), argnums=(0, 2))(zxf, zxb, wf, wb)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


class TestDispatch:
    def test_auto_resolves_to_xla_off_tpu(self):
        # CPU test env: auto must pick the scan fallback, kernels only
        # by explicit request — the default model path is unchanged
        assert fused_rnn.resolve_impl(128) == "xla"

    def test_ineligible_hidden_sizes(self):
        for h in (96, 2048):  # not lane-tileable / over VMEM budget
            assert fused_rnn.resolve_impl(h, None) == "xla"
        # explicit impl is honored as-is
        assert fused_rnn.resolve_impl(96, "interpret") == "interpret"

    def test_env_kill_switch(self, monkeypatch):
        # the knob is snapshotted at import (utils/envknobs, graftlint
        # trace-env-read) — mutating the env requires an explicit
        # refresh, and the snapshot must be restored afterwards
        from bigdl_tpu.utils import envknobs

        ambient = envknobs.FUSED_RNN_ENABLED  # may be off in the shell
        monkeypatch.setenv("BIGDL_FUSED_RNN", "0")
        envknobs.refresh()
        try:
            assert not envknobs.FUSED_RNN_ENABLED
            assert fused_rnn.resolve_impl(128, None) == "xla"
        finally:
            monkeypatch.undo()
            envknobs.refresh()
        assert envknobs.FUSED_RNN_ENABLED == ambient

    def test_unknown_impl_raises(self):
        # a typo must not silently measure the fallback path
        with pytest.raises(ValueError, match="expected"):
            fused_rnn.resolve_impl(128, "palas")

    def test_fused_scan_protocol_returns_none_on_fallback(self):
        cell = nn.LSTM(3, 4)
        p = cell.init_params(KEY)
        zx = jnp.zeros((2, 3, 16))
        assert cell.fused_scan(p, zx) is None  # CPU → scan path
