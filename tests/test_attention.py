"""Flash attention kernel + MultiHeadAttention tests.

The Pallas kernel runs in interpreter mode on CPU (interpret=True) and is
checked against the jnp oracle `attention_reference` — the same
oracle-based strategy the reference uses with Torch7 (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.ops.flash_attention import (
    attention_reference,
    flash_attention,
    flash_attention_with_lse,
)


def _rand_qkv(rng, bh=2, sq=64, sk=64, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (bh, sq, d), dtype)
    k = jax.random.normal(kk, (bh, sk, d), dtype)
    v = jax.random.normal(kv, (bh, sk, d), dtype)
    return q, k, v


class TestFlashKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, causal):
        q, k, v = _rand_qkv(jax.random.PRNGKey(0))
        ref = attention_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32, impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_unaligned_seq_and_dim(self):
        # S and D not multiples of the block/lane sizes → padding path
        q, k, v = _rand_qkv(jax.random.PRNGKey(1), sq=50, sk=70, d=24)
        ref = attention_reference(q, k, v)
        out = flash_attention(q, k, v, block_q=32, block_k=32,
                              impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_lse_matches_oracle(self):
        q, k, v = _rand_qkv(jax.random.PRNGKey(2), sq=48, sk=48)
        _, lse_ref = attention_reference(q, k, v, return_lse=True)
        _, lse = flash_attention_with_lse(q, k, v, block_q=16, block_k=16,
                                          impl="interpret")
        np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_oracle(self, causal):
        q, k, v = _rand_qkv(jax.random.PRNGKey(3), sq=32, sk=32, d=8)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal, block_q=16,
                                  block_k=16, impl="reference")
            return jnp.sum(out * jnp.cos(out))

        def loss_ref(q, k, v):
            out = attention_reference(q, k, v, causal=causal)
            return jnp.sum(out * jnp.cos(out))

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_grads_through_interpret_kernel(self):
        # custom VJP over the Pallas forward (interpret) — the full path
        q, k, v = _rand_qkv(jax.random.PRNGKey(4), sq=32, sk=32, d=8)

        def loss(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=16,
                                  block_k=16, impl="interpret")
            return jnp.sum(out ** 2)

        def loss_ref(q, k, v):
            out = attention_reference(q, k, v, causal=True)
            return jnp.sum(out ** 2)

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_cross_attention_lengths(self):
        q, k, v = _rand_qkv(jax.random.PRNGKey(5), sq=16, sk=80)
        ref = attention_reference(q, k, v)
        out = flash_attention(q, k, v, block_q=16, block_k=32,
                              impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("sq,sk", [(8, 16), (16, 8), (24, 40)])
    def test_causal_cross_attention_bottom_right_aligned(self, sq, sk):
        # causal with seq_q != seq_k: query i sees keys ≤ i + (sk - sq),
        # the KV-cache decode convention; kernel must match the oracle
        q, k, v = _rand_qkv(jax.random.PRNGKey(6), sq=sq, sk=sk)
        ref = attention_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8,
                              impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestMultiHeadAttention:
    def test_forward_shape_and_oracle(self):
        m = nn.MultiHeadAttention(32, 4, name="mha")
        variables = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 32))
        y, _ = m.apply(variables, x)
        assert y.shape == (2, 10, 32)

    def test_causal_is_autoregressive(self):
        m = nn.MultiHeadAttention(16, 2, causal=True)
        variables = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16))
        y1, _ = m.apply(variables, x)
        # perturbing future positions must not change earlier outputs
        x2 = x.at[:, 5:].set(jax.random.normal(jax.random.PRNGKey(2),
                                               (1, 3, 16)))
        y2, _ = m.apply(variables, x2)
        np.testing.assert_allclose(np.asarray(y1[:, :5]),
                                   np.asarray(y2[:, :5]), atol=1e-5)

    def test_cross_attention(self):
        m = nn.MultiHeadAttention(16, 2)
        variables = m.init(jax.random.PRNGKey(0))
        xq = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 16))
        xkv = jax.random.normal(jax.random.PRNGKey(2), (2, 9, 16))
        y, _ = m.apply(variables, [xq, xkv])
        assert y.shape == (2, 5, 16)

    def test_grad_flows(self):
        m = nn.MultiHeadAttention(16, 2, causal=True)
        variables = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 16))

        def loss(p):
            y, _ = m.apply({"params": p, "state": {}}, x)
            return jnp.mean(y ** 2)

        g = jax.grad(loss)(variables["params"])
        norms = [float(jnp.linalg.norm(v)) for v in
                 jax.tree_util.tree_leaves(g)]
        assert all(np.isfinite(n) for n in norms)
        assert any(n > 0 for n in norms)

    def test_dropout_paths(self):
        m = nn.MultiHeadAttention(16, 2, attn_dropout=0.5, out_dropout=0.5)
        variables = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 16))
        y1, _ = m.apply(variables, x, training=True,
                        rng=jax.random.PRNGKey(2))
        y2, _ = m.apply(variables, x, training=True,
                        rng=jax.random.PRNGKey(3))
        assert not np.allclose(np.asarray(y1), np.asarray(y2))
        ye, _ = m.apply(variables, x, training=False)
        ye2, _ = m.apply(variables, x, training=False)
        np.testing.assert_allclose(np.asarray(ye), np.asarray(ye2))


class TestXlaBlockwiseForward:
    """impl='xla' — the blockwise lax.scan flash forward (default on
    TPU since round 2; see _flash_fwd_xla)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle_with_lse(self, causal):
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(3, 100, 16), jnp.float32)
        k = jnp.asarray(rng.randn(3, 100, 16), jnp.float32)
        v = jnp.asarray(rng.randn(3, 100, 16), jnp.float32)
        ref, ref_lse = attention_reference(q, k, v, causal=causal,
                                           return_lse=True)
        out, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                            impl="xla", block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_oracle(self):
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(2, 64, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 96, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 96, 8), jnp.float32)

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v) * jnp.arange(8, dtype=jnp.float32))

        g_x = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, impl="xla", block_k=32)),
            argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(loss(lambda q, k, v: attention_reference(
            q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_x, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_uneven_kv_padding(self):
        rng = np.random.RandomState(5)
        q = jnp.asarray(rng.randn(2, 33, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 77, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 77, 8), jnp.float32)
        ref = attention_reference(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, impl="xla",
                              block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestFullyMaskedRows:
    """Causal with seq_q > seq_k leaves leading query rows with NO
    visible keys (bottom-right alignment). _NEG_INF is finite, so a bare
    exp(s - m) would emit 1 per masked column and the row would output
    mean(V); all impls must emit zeros (the ring-combine convention)."""

    @pytest.mark.parametrize("impl", ["xla", "interpret", "reference"])
    def test_fully_masked_rows_are_zero(self, impl):
        rng = np.random.RandomState(6)
        q = jnp.asarray(rng.randn(2, 8, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
        out, lse = flash_attention_with_lse(q, k, v, causal=True,
                                            impl=impl, block_q=8,
                                            block_k=4)
        # rows 0..3 see no keys (row i sees keys <= i + 4 - 8)
        np.testing.assert_allclose(np.asarray(out[:, :4]), 0.0, atol=1e-6)
        assert bool(jnp.all(lse[:, :4] < -1e29))
        # visible rows must still match the oracle
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out[:, 4:]),
                                   np.asarray(ref[:, 4:]),
                                   rtol=1e-5, atol=1e-5)


class TestMosaicBackwardEdgeShapes:
    """Gradient checks through the Mosaic backward kernels (interpret
    mode) on the shapes that can silently break them: cross q/kv
    lengths (bottom-right-aligned causal), block-non-divisible
    sequences (padded-row masking in the dkv kernel), and an explicit
    sm_scale."""

    @pytest.mark.parametrize("sq,sk,causal", [
        (20, 36, True),    # sq < sk, padded rows + cross-length causal
        (40, 24, True),    # sq > sk: fully-masked leading rows
        (33, 33, False),   # non-divisible, non-causal
        (64, 64, True),    # block-divisible control
    ])
    def test_grads_match_oracle(self, sq, sk, causal):
        rng = np.random.RandomState(5)
        q = jnp.asarray(rng.randn(3, sq, 8), jnp.float32)
        k = jnp.asarray(rng.randn(3, sk, 8), jnp.float32)
        v = jnp.asarray(rng.randn(3, sk, 8), jnp.float32)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, causal=causal, block_q=16,
                                   block_k=16, impl="interpret").sum()

        def loss_ref(q, k, v):
            return attention_reference(q, k, v, causal=causal).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_grads_with_explicit_scale(self):
        rng = np.random.RandomState(6)
        q = jnp.asarray(rng.randn(2, 24, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 24, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 24, 8), jnp.float32)
        for scale in (0.5, 0.0):   # 0.0: uniform attention, dk must be 0
            g1 = jax.grad(lambda q: flash_attention(
                q, k, v, causal=True, sm_scale=scale, block_q=16,
                block_k=16, impl="interpret").sum())(q)
            g2 = jax.grad(lambda q: attention_reference(
                q, k, v, causal=True, sm_scale=scale).sum())(q)
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       rtol=2e-4, atol=2e-5)


class TestFusedBackward:
    """The one-pass backward (persistent dq accumulator) must equal the
    two-kernel form bit-for-bit-ish at any shape both can run."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_fused_equals_split(self, causal):
        import importlib
        fa = importlib.import_module("bigdl_tpu.ops.flash_attention")

        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 64, 16).astype(np.float32))
        k = jnp.asarray(rng.randn(2, 64, 16).astype(np.float32))
        v = jnp.asarray(rng.randn(2, 64, 16).astype(np.float32))
        o, lse = fa._flash_fwd_pallas(q, k, v, causal, 0.25, 32, 32,
                                      interpret=True)
        do = jnp.asarray(rng.randn(2, 64, 16).astype(np.float32))
        fused = fa._flash_bwd_pallas_fused(q, k, v, o, lse, do, causal,
                                           0.25, 32, 32, interpret=True)
        split = fa._flash_bwd_pallas_split(q, k, v, o, lse, do, causal,
                                           0.25, 32, 32, interpret=True)
        for a, b, name in zip(fused, split, ("dq", "dk", "dv")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5,
                                       err_msg=name)

    def test_long_sequence_falls_back_to_split(self, monkeypatch):
        import importlib
        fa = importlib.import_module("bigdl_tpu.ops.flash_attention")

        calls = []
        monkeypatch.setattr(
            fa, "_flash_bwd_pallas_split",
            lambda *a, **k: calls.append("split") or
            (a[0], a[1], a[2]))
        monkeypatch.setattr(
            fa, "_flash_bwd_pallas_fused",
            lambda *a, **k: calls.append("fused") or
            (a[0], a[1], a[2]))
        small = jnp.zeros((1, 128, 64))
        fa._flash_bwd_pallas(small, small, small, small,
                             jnp.zeros((1, 128)), small, True, 1.0,
                             128, 128, True)
        # 8M / (128 lanes * 4B) = 16384 rows: S beyond that splits
        big = jnp.zeros((1, 32768, 64))
        fa._flash_bwd_pallas(big, big, big, big,
                             jnp.zeros((1, 32768)), big, True, 1.0,
                             1024, 1024, True)
        assert calls == ["fused", "split"]
