"""MoE layer: routing invariants, and expert-parallel execution vs the
single-device oracle on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel import make_mesh, shard_params
from bigdl_tpu.parallel.moe import MoE, moe_specs

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

DIM, HID, EXPERTS = 16, 32, 8


def test_single_device_forward_and_aux():
    m = MoE(DIM, HID, EXPERTS, name="moe")
    variables = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, DIM))
    (y, aux), _ = m.apply(variables, x)
    assert y.shape == x.shape
    assert float(aux) > 0.0
    # top-1 with generous capacity: every token routed exactly once →
    # output is gate-scaled expert output, never all-zero rows for a
    # reasonable capacity factor
    m2 = MoE(DIM, HID, EXPERTS, capacity_factor=8.0, name="moe2")
    (y2, _), _ = m2.apply({"params": variables["params"],
                           "state": {}}, x)
    norms = np.linalg.norm(np.asarray(y2), axis=-1)
    assert (norms > 0).all()


def test_grads_flow():
    m = MoE(DIM, HID, EXPERTS, name="moe")
    variables = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, DIM))

    def loss(p):
        (y, aux), _ = m.apply({"params": p, "state": {}}, x)
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.tree_util.tree_leaves(jax.grad(loss)(variables["params"]))
    assert all(np.isfinite(np.asarray(x)).all() for x in g)
    assert any(float(jnp.linalg.norm(x)) > 0 for x in g)


@pytest.mark.parametrize("cap", [1.25, 8.0])
def test_expert_parallel_matches_single_device(cap):
    n = 4
    mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
    m_ref = MoE(DIM, HID, EXPERTS, capacity_factor=cap, name="moe")
    m_ep = MoE(DIM, HID, EXPERTS, capacity_factor=cap,
               expert_axis="expert", name="moe")
    variables = m_ref.init(jax.random.PRNGKey(0))
    params = variables["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (n * 16, DIM))

    # oracle: each device routes its own chunk independently
    chunks = x.reshape(n, 16, DIM)
    ref = jnp.concatenate([
        m_ref.apply({"params": params, "state": {}}, chunks[i])[0][0]
        for i in range(n)])

    specs = moe_specs("expert")

    def body(p, x):
        (y, aux), _ = m_ep.apply({"params": p, "state": {}}, x)
        return y

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("expert", None)),
        out_specs=P("expert", None), check_vma=False))
    out = fn(shard_params(mesh, specs, params),
             jax.device_put(x, NamedSharding(mesh, P("expert", None))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_expert_parallel_grads_match(cap=8.0):
    n = 4
    mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
    m_ref = MoE(DIM, HID, EXPERTS, capacity_factor=cap, name="moe")
    m_ep = MoE(DIM, HID, EXPERTS, capacity_factor=cap,
               expert_axis="expert", name="moe")
    params = m_ref.init(jax.random.PRNGKey(0))["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (n * 16, DIM))
    chunks = x.reshape(n, 16, DIM)

    def ref_loss(p):
        tot = 0.0
        for i in range(n):
            (y, aux), _ = m_ref.apply({"params": p, "state": {}},
                                      chunks[i])
            tot = tot + jnp.sum(y ** 2) + 0.01 * aux
        return tot

    g_ref = jax.grad(ref_loss)(params)

    specs = moe_specs("expert")

    def body(p, x):
        def lf(p):
            (y, aux), _ = m_ep.apply({"params": p, "state": {}}, x)
            return jnp.sum(y ** 2) + 0.01 * aux
        g = jax.grad(lf)(p)
        # router is replicated but each shard saw only its tokens
        g["router"] = jax.lax.psum(g["router"], "expert")
        return g

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("expert", None)),
        out_specs=specs, check_vma=False))
    g = fn(shard_params(mesh, specs, params),
           jax.device_put(x, NamedSharding(mesh, P("expert", None))))
    for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(g),
                               jax.tree_util.tree_leaves_with_path(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=str(ka))
