"""MoE layer: routing invariants, and expert-parallel execution vs the
single-device oracle on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel import make_mesh, shard_params
from bigdl_tpu.parallel.moe import MoE, moe_specs

from bigdl_tpu.parallel.shard_map_compat import shard_map

DIM, HID, EXPERTS = 16, 32, 8


def test_single_device_forward_and_aux():
    m = MoE(DIM, HID, EXPERTS, name="moe")
    variables = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, DIM))
    (y, aux), _ = m.apply(variables, x)
    assert y.shape == x.shape
    assert float(aux) > 0.0
    # top-1 with generous capacity: every token routed exactly once →
    # output is gate-scaled expert output, never all-zero rows for a
    # reasonable capacity factor
    m2 = MoE(DIM, HID, EXPERTS, capacity_factor=8.0, name="moe2")
    (y2, _), _ = m2.apply({"params": variables["params"],
                           "state": {}}, x)
    norms = np.linalg.norm(np.asarray(y2), axis=-1)
    assert (norms > 0).all()


def test_grads_flow():
    m = MoE(DIM, HID, EXPERTS, name="moe")
    variables = m.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, DIM))

    def loss(p):
        (y, aux), _ = m.apply({"params": p, "state": {}}, x)
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.tree_util.tree_leaves(jax.grad(loss)(variables["params"]))
    assert all(np.isfinite(np.asarray(x)).all() for x in g)
    assert any(float(jnp.linalg.norm(x)) > 0 for x in g)


@pytest.mark.parametrize("cap", [1.25, 8.0])
def test_expert_parallel_matches_single_device(cap):
    n = 4
    mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
    m_ref = MoE(DIM, HID, EXPERTS, capacity_factor=cap, name="moe")
    m_ep = MoE(DIM, HID, EXPERTS, capacity_factor=cap,
               expert_axis="expert", name="moe")
    variables = m_ref.init(jax.random.PRNGKey(0))
    params = variables["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (n * 16, DIM))

    # oracle: each device routes its own chunk independently
    chunks = x.reshape(n, 16, DIM)
    ref = jnp.concatenate([
        m_ref.apply({"params": params, "state": {}}, chunks[i])[0][0]
        for i in range(n)])

    specs = moe_specs("expert")

    def body(p, x):
        (y, aux), _ = m_ep.apply({"params": p, "state": {}}, x)
        return y

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("expert", None)),
        out_specs=P("expert", None), check_vma=False))
    out = fn(shard_params(mesh, specs, params),
             jax.device_put(x, NamedSharding(mesh, P("expert", None))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_expert_parallel_grads_match(cap=8.0):
    n = 4
    mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
    m_ref = MoE(DIM, HID, EXPERTS, capacity_factor=cap, name="moe")
    m_ep = MoE(DIM, HID, EXPERTS, capacity_factor=cap,
               expert_axis="expert", name="moe")
    params = m_ref.init(jax.random.PRNGKey(0))["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (n * 16, DIM))
    chunks = x.reshape(n, 16, DIM)

    def ref_loss(p):
        tot = 0.0
        for i in range(n):
            (y, aux), _ = m_ref.apply({"params": p, "state": {}},
                                      chunks[i])
            tot = tot + jnp.sum(y ** 2) + 0.01 * aux
        return tot

    g_ref = jax.grad(ref_loss)(params)

    specs = moe_specs("expert")

    def body(p, x):
        def lf(p):
            (y, aux), _ = m_ep.apply({"params": p, "state": {}}, x)
            return jnp.sum(y ** 2) + 0.01 * aux
        g = jax.grad(lf)(p)
        # router is replicated but each shard saw only its tokens
        g["router"] = jax.lax.psum(g["router"], "expert")
        return g

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("expert", None)),
        out_specs=specs, check_vma=False))
    g = fn(shard_params(mesh, specs, params),
           jax.device_put(x, NamedSharding(mesh, P("expert", None))))
    for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(g),
                               jax.tree_util.tree_leaves_with_path(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=str(ka))


# ------------------------------------------------------------ top-2 (GShard)

def test_top2_matches_dense_weighted_oracle():
    """With capacity large enough that nothing drops, top-2 output is
    exactly w1*FFN_{e1}(x) + w2*FFN_{e2}(x) with renormalized gates —
    checked against a dense run of ALL experts."""
    m = MoE(DIM, HID, EXPERTS, capacity_factor=8.0, top_k=2, name="moe")
    variables = m.init(jax.random.PRNGKey(0))
    p = variables["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (48, DIM))
    (y, aux), _ = m.apply(variables, x)

    gates = jax.nn.softmax(x @ p["router"], axis=-1)
    e1 = jnp.argmax(gates, axis=-1)
    g2m = gates * (1 - jax.nn.one_hot(e1, EXPERTS))
    e2 = jnp.argmax(g2m, axis=-1)
    g1 = jnp.take_along_axis(gates, e1[:, None], -1)[:, 0]
    g2 = jnp.take_along_axis(gates, e2[:, None], -1)[:, 0]
    w1, w2 = g1 / (g1 + g2 + 1e-9), g2 / (g1 + g2 + 1e-9)
    # dense: every expert applied to every token
    h = jnp.einsum("td,edf->tef", x, p["w1"]) + p["b1"][None]
    out_all = jnp.einsum("tef,efd->ted", jax.nn.gelu(h), p["w2"]) \
        + p["b2"][None]
    rows = jnp.arange(x.shape[0])
    ref = w1[:, None] * out_all[rows, e1] + w2[:, None] * out_all[rows, e2]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert float(aux) > 0.0


def test_top2_second_choice_yields_to_first():
    """Second choices queue BEHIND first choices in an expert's
    capacity buffer: with every token first-choosing expert 0 and
    second-choosing expert 1 at cap=2, expert 0 keeps exactly the first
    two tokens' FIRST choices (seconds could never displace them), and
    dropped-second tokens revert to full weight on their first choice."""
    m = MoE(2, HID, 2, capacity_factor=0.25, top_k=2, name="moe")
    # cap = 0.25 * 2 * 8 / 2 = 2
    t = 8
    x2 = jnp.tile(jnp.asarray([[2.0, 1.0]]), (t, 1))   # e0 first, e1 second
    router = jnp.eye(2)
    dispatch, combine, aux, cap = m._route(x2, router)
    assert cap == 2
    d = np.asarray(dispatch)                            # (T, E, C)
    # expert 0: tokens 0 and 1 occupy its two slots (first choices win)
    np.testing.assert_array_equal(d[:, 0, :].sum(axis=1),
                                  [1, 1, 0, 0, 0, 0, 0, 0])
    # expert 1: the SECOND choices of tokens 0 and 1 fill its slots
    # (its own queue was empty of first choices)
    np.testing.assert_array_equal(d[:, 1, :].sum(axis=1),
                                  [1, 1, 0, 0, 0, 0, 0, 0])
    # tokens 2..7 lost both choices → zero combine weight; tokens 0,1
    # keep both with renormalized weights summing to 1
    c = np.asarray(combine).sum(axis=(1, 2))
    np.testing.assert_allclose(c[:2], [1.0, 1.0], atol=1e-6)
    np.testing.assert_allclose(c[2:], 0.0, atol=1e-6)


def test_top2_dropped_second_reverts_to_full_first_weight():
    """Oversubscribe only the second-choice expert: first choices all
    survive, and a token whose second choice was dropped puts weight
    1.0 on its first choice (renormalization over survivors)."""
    m = MoE(2, HID, 2, capacity_factor=0.75, top_k=2, name="moe")
    # cap = 0.75 * 2 * 8 / 2 = 6: expert 0 keeps 6 of 8 first choices;
    # expert 1 keeps 6 of 8 second choices
    t = 8
    x2 = jnp.tile(jnp.asarray([[2.0, 1.0]]), (t, 1))
    router = jnp.eye(2)
    dispatch, combine, aux, cap = m._route(x2, router)
    assert cap == 6
    d = np.asarray(dispatch)
    np.testing.assert_array_equal(d[:, 0, :].sum(axis=1),
                                  [1] * 6 + [0] * 2)
    np.testing.assert_array_equal(d[:, 1, :].sum(axis=1),
                                  [1] * 6 + [0] * 2)
    c = np.asarray(combine)
    # tokens 0..5: both survive, weights renormalized to sum 1
    np.testing.assert_allclose(c[:6].sum(axis=(1, 2)), 1.0, atol=1e-6)
    # tokens 6,7: both dropped here (same order in both queues)
    np.testing.assert_allclose(c[6:].sum(axis=(1, 2)), 0.0, atol=1e-6)


@pytest.mark.parametrize("cap", [8.0, 1.25])
def test_top2_expert_parallel_matches_single_device(cap):
    n = 4
    mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
    m_ref = MoE(DIM, HID, EXPERTS, capacity_factor=cap, top_k=2,
                name="moe")
    m_ep = MoE(DIM, HID, EXPERTS, capacity_factor=cap, top_k=2,
               expert_axis="expert", name="moe")
    variables = m_ref.init(jax.random.PRNGKey(0))
    params = variables["params"]
    x = jax.random.normal(jax.random.PRNGKey(1), (n * 16, DIM))

    chunks = x.reshape(n, 16, DIM)
    ref = jnp.concatenate([
        m_ref.apply({"params": params, "state": {}}, chunks[i])[0][0]
        for i in range(n)])

    specs = moe_specs("expert")

    def body(p, x):
        (y, aux), _ = m_ep.apply({"params": p, "state": {}}, x)
        return y

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P("expert", None)),
        out_specs=P("expert", None), check_vma=False))
    out = fn(shard_params(mesh, specs, params),
             jax.device_put(x, NamedSharding(mesh, P("expert", None))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_top_k_validation():
    with pytest.raises(ValueError, match="top_k"):
        MoE(DIM, HID, EXPERTS, top_k=3)


def test_pipeline_bubble_fraction_reported():
    from bigdl_tpu.parallel.pipeline import pipeline_bubble_fraction

    assert pipeline_bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert pipeline_bubble_fraction(1, 8) == 0.0
    # the constructed step carries its schedule's bubble fraction
    from bigdl_tpu.models.transformer import TransformerConfig, TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel import make_mesh, make_pipeline_train_step

    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    cfg = TransformerConfig(vocab_size=32, max_len=16, dim=16,
                            num_heads=4, num_layers=4, dropout=0.0)
    step = make_pipeline_train_step(TransformerLM(cfg, name="lm"),
                                    SGD(learningrate=0.1), mesh,
                                    microbatches=8)
    assert step.bubble_fraction == pytest.approx(3 / 11)


# ----------------------------------------------- expert-parallel MoE LM

@pytest.mark.slow
def test_moe_lm_ep_step_matches_single_device():
    """make_moe_lm_train_step (expert axis doubling as batch axis) ==
    single-device full-batch step: loss AND parameters.

    tier-2 (ISSUE 10 budget satellite): the moe-lm/ep dryrun leg in
    __graft_entry__.py asserts the same sharded-loss-vs-oracle on
    every driver run, and test_expert_parallel_matches_single_device /
    test_expert_parallel_grads_match keep the ep step's math tier-1."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (TransformerConfig,
                                              TransformerLM)
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel import (make_mesh, make_moe_lm_train_step,
                                    moe_lm_specs, shard_params)
    from bigdl_tpu.parallel.tensor_parallel import slot_specs_for
    from jax.sharding import NamedSharding

    n = 4
    mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
    cfg = TransformerConfig(vocab_size=32, max_len=16, dim=16,
                            num_heads=4, num_layers=2, dropout=0.0,
                            moe_experts=8, moe_capacity_factor=8.0)
    model_ep = TransformerLM(cfg, ep_axis="expert", name="lm")
    model_ref = TransformerLM(cfg, name="lm")
    params = model_ref.init(jax.random.PRNGKey(0))["params"]
    method = SGD(learningrate=0.1, momentum=0.9)
    slots = method.init_slots(params)

    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32, (n * 2, 16)), jnp.int32)
    tgts = jnp.asarray(rng.randint(0, 32, (n * 2, 16)), jnp.int32)

    # oracle: the EP step folds a per-shard rng; replicate that by
    # averaging the per-shard local losses computed the same way.
    # With dropout=0 the rng is inert, so the plain full-batch loss is
    # exact — but per-SHARD routing differs from full-batch routing, so
    # the oracle routes each shard's chunk independently (capacity 8.0
    # keeps every token, making chunked == full routing-wise).
    def ref_loss_fn(p):
        tot = 0.0
        for i in range(n):
            tot = tot + model_ref.loss(
                {"params": p, "state": {}},
                toks[2 * i:2 * i + 2], tgts[2 * i:2 * i + 2],
                training=True, rng=jax.random.PRNGKey(0)) / n
        return tot

    ref_loss, ref_g = jax.value_and_grad(ref_loss_fn)(params)
    ref_p, _ = method.update(ref_g, params, slots, jnp.asarray(0.1),
                             jnp.asarray(0))

    specs = moe_lm_specs("expert", cfg.tie_embeddings)
    step = make_moe_lm_train_step(model_ep, method, mesh,
                                  ep_axis="expert")
    sp_params = shard_params(mesh, specs, params)
    sp_slots = shard_params(mesh, slot_specs_for(method, specs), slots)
    tok_sharding = NamedSharding(mesh, P("expert", None))
    new_p, _, loss = step(
        sp_params, sp_slots,
        jax.device_put(toks, tok_sharding),
        jax.device_put(tgts, tok_sharding),
        jnp.asarray(0.1), jnp.asarray(0), jax.random.PRNGKey(0))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(new_p),
            jax.tree_util.tree_leaves_with_path(ref_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=str(ka))


def test_moe_lm_ep_requires_matching_axis():
    from bigdl_tpu.models.transformer import (TransformerConfig,
                                              TransformerLM)
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel import make_mesh, make_moe_lm_train_step

    mesh = make_mesh({"expert": 4}, devices=jax.devices()[:4])
    cfg = TransformerConfig(vocab_size=32, max_len=16, dim=16,
                            num_heads=4, num_layers=2, moe_experts=8)
    dense_built = TransformerLM(cfg, name="lm")  # no ep_axis
    with pytest.raises(ValueError, match="ep_axis"):
        make_moe_lm_train_step(dense_built, SGD(learningrate=0.1), mesh)


class TestExpertChoice:
    """routing='expert_choice' (dropless: every expert buffer exactly
    full by construction, aux == 0)."""

    def test_matches_loop_oracle(self):
        m = MoE(DIM, HID, EXPERTS, capacity_factor=2.0,
                routing="expert_choice", name="ec")
        variables = m.init(jax.random.PRNGKey(0))
        p = variables["params"]
        x = jax.random.normal(jax.random.PRNGKey(1), (64, DIM))
        (y, aux), _ = m.apply(variables, x)
        assert float(aux) == 0.0

        # loop oracle: each expert picks its top-C tokens by affinity
        import numpy as np
        scores = np.asarray(jax.nn.softmax(x @ p["router"], axis=-1))
        cap = int(2.0 * 64 / EXPERTS)
        want = np.zeros((64, DIM), np.float32)
        for e in range(EXPERTS):
            top = np.argsort(-scores[:, e])[:cap]
            xe = np.asarray(x)[top]                       # (C, D)
            h = np.asarray(jax.nn.gelu(
                jnp.asarray(xe @ np.asarray(p["w1"])[e]
                            + np.asarray(p["b1"])[e])))
            out_e = h @ np.asarray(p["w2"])[e] + np.asarray(p["b2"])[e]
            for c, t in enumerate(top):
                want[t] += scores[t, e] * out_e[c]
        np.testing.assert_allclose(np.asarray(y), want,
                                   atol=2e-4, rtol=2e-4)

    def test_every_expert_exactly_full(self):
        m = MoE(DIM, HID, EXPERTS, capacity_factor=2.0,
                routing="expert_choice", name="ec")
        variables = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(2), (64, DIM))
        dispatch, combine, cap = m._route_expert_choice(
            x, variables["params"]["router"])
        # every (expert, slot) holds exactly one token — dropless
        slot_fill = np.asarray(dispatch.sum(axis=0))       # (E, C)
        np.testing.assert_array_equal(slot_fill,
                                      np.ones_like(slot_fill))

    def test_grads_flow_and_ep_matches_single_device(self):
        n = 4
        mesh = make_mesh({"expert": n}, devices=jax.devices()[:n])
        m_ref = MoE(DIM, HID, EXPERTS, capacity_factor=2.0,
                    routing="expert_choice", name="ec")
        m_ep = MoE(DIM, HID, EXPERTS, capacity_factor=2.0,
                   routing="expert_choice", expert_axis="expert",
                   name="ec")
        variables = m_ref.init(jax.random.PRNGKey(0))
        params = variables["params"]
        x = jax.random.normal(jax.random.PRNGKey(1), (n * 16, DIM))

        g = jax.grad(lambda p: m_ref.apply(
            {"params": p, "state": {}}, x)[0][0].sum())(params)
        gn = sum(float(jnp.abs(l).sum())
                 for l in jax.tree_util.tree_leaves(g))
        assert np.isfinite(gn) and gn > 0

        chunks = x.reshape(n, 16, DIM)
        ref = jnp.concatenate([
            m_ref.apply({"params": params, "state": {}}, chunks[i])[0][0]
            for i in range(n)])
        specs = moe_specs("expert")

        def body(p, x):
            (y, aux), _ = m_ep.apply({"params": p, "state": {}}, x)
            return y

        fn = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(specs, P("expert", None)),
            out_specs=P("expert", None), check_vma=False))
        out = fn(shard_params(mesh, specs, params),
                 jax.device_put(x, NamedSharding(mesh,
                                                 P("expert", None))))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
