"""Caffe interop tests.

Reference parity: utils/caffe/CaffeLoaderSpec.scala /
CaffePersisterSpec.scala — load small fixture nets, compare forward
output; persist → reload round-trips (SURVEY.md §4 "Interop").
Fixtures are constructed programmatically with the bundled
wire-compatible protobuf subset (no caffe install needed).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.graph import Graph, Input
from bigdl_tpu.utils.caffe import bigdl_caffe_pb2 as pb
from bigdl_tpu.utils.caffe import loader as caffe


def _mk_blob(layer, arr):
    b = layer.blobs.add()
    b.shape.dim.extend(arr.shape)
    b.data.extend(np.asarray(arr, np.float32).ravel().tolist())


def _simple_net(rng):
    """conv(2,3x3,pad1) → relu → maxpool2 → fc(10) → softmax over 1x2x8x8."""
    net = pb.NetParameter()
    net.name = "tiny"
    net.input.append("data")
    net.input_shape.add().dim.extend([1, 2, 8, 8])

    conv = net.layer.add()
    conv.name, conv.type = "conv1", "Convolution"
    conv.bottom.append("data"); conv.top.append("conv1")
    cp = conv.convolution_param
    cp.num_output = 3
    cp.kernel_size.append(3); cp.pad.append(1); cp.stride.append(1)
    w_conv = rng.standard_normal((3, 2, 3, 3)).astype(np.float32)
    b_conv = rng.standard_normal((3,)).astype(np.float32)
    _mk_blob(conv, w_conv); _mk_blob(conv, b_conv)

    relu = net.layer.add()
    relu.name, relu.type = "relu1", "ReLU"
    relu.bottom.append("conv1"); relu.top.append("conv1")  # in-place

    pool = net.layer.add()
    pool.name, pool.type = "pool1", "Pooling"
    pool.bottom.append("conv1"); pool.top.append("pool1")
    pool.pooling_param.pool = pb.PoolingParameter.MAX
    pool.pooling_param.kernel_size = 2
    pool.pooling_param.stride = 2

    fc = net.layer.add()
    fc.name, fc.type = "fc1", "InnerProduct"
    fc.bottom.append("pool1"); fc.top.append("fc1")
    fc.inner_product_param.num_output = 10
    w_fc = rng.standard_normal((10, 3 * 4 * 4)).astype(np.float32)
    b_fc = rng.standard_normal((10,)).astype(np.float32)
    _mk_blob(fc, w_fc); _mk_blob(fc, b_fc)

    sm = net.layer.add()
    sm.name, sm.type = "prob", "Softmax"
    sm.bottom.append("fc1"); sm.top.append("prob")
    return net, (w_conv, b_conv, w_fc, b_fc)


def _expected_simple(x_nchw, w_conv, b_conv, w_fc, b_fc):
    """Reference forward in caffe layout via lax, for cross-checking."""
    from jax import lax

    y = lax.conv_general_dilated(
        jnp.asarray(x_nchw), jnp.asarray(w_conv), (1, 1),
        [(1, 1), (1, 1)],
        dimension_numbers=lax.conv_dimension_numbers(
            x_nchw.shape, w_conv.shape, ("NCHW", "OIHW", "NCHW")))
    y = y + jnp.asarray(b_conv)[None, :, None, None]
    y = jnp.maximum(y, 0)
    y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 1, 2, 2), (1, 1, 2, 2),
                          "VALID")
    flat = y.reshape(y.shape[0], -1)  # (N, C*H*W) — caffe order
    logits = flat @ jnp.asarray(w_fc).T + jnp.asarray(b_fc)
    return jax.nn.softmax(logits, axis=-1)


def test_load_binary_caffemodel(tmp_path):
    rng = np.random.default_rng(0)
    net, weights = _simple_net(rng)
    path = tmp_path / "tiny.caffemodel"
    path.write_bytes(net.SerializeToString())

    model, variables = caffe.load(model_path=str(path))
    x_nchw = rng.standard_normal((1, 2, 8, 8)).astype(np.float32)
    x_nhwc = jnp.asarray(x_nchw.transpose(0, 2, 3, 1))
    out, _ = model.apply(variables, x_nhwc, training=False)
    want = _expected_simple(x_nchw, *weights)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_load_prototxt_plus_model_nchw_layout(tmp_path):
    from google.protobuf import text_format

    rng = np.random.default_rng(1)
    net, weights = _simple_net(rng)
    model_path = tmp_path / "tiny.caffemodel"
    model_path.write_bytes(net.SerializeToString())
    arch = pb.NetParameter(); arch.CopyFrom(net)
    for l in arch.layer:
        del l.blobs[:]
    def_path = tmp_path / "tiny.prototxt"
    def_path.write_text(text_format.MessageToString(arch))

    model, variables = caffe.load(str(def_path), str(model_path),
                                  input_layout="NCHW")
    x_nchw = rng.standard_normal((2, 2, 8, 8)).astype(np.float32)
    out, _ = model.apply(variables, jnp.asarray(x_nchw), training=False)
    want = _expected_simple(x_nchw, *weights)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_v1_legacy_layers(tmp_path):
    rng = np.random.default_rng(2)
    net = pb.NetParameter()
    net.name = "v1net"
    net.input.append("data")
    net.input_dim.extend([1, 3, 4, 4])
    fc = net.layers.add()
    fc.name = "ip"
    fc.type = pb.V1LayerParameter.INNER_PRODUCT
    fc.bottom.append("data"); fc.top.append("ip")
    fc.inner_product_param.num_output = 5
    w = rng.standard_normal((5, 48)).astype(np.float32)
    b = rng.standard_normal((5,)).astype(np.float32)
    _mk_blob(fc, w); _mk_blob(fc, b)
    sm = net.layers.add()
    sm.name = "prob"
    sm.type = pb.V1LayerParameter.SOFTMAX
    sm.bottom.append("ip"); sm.top.append("prob")
    path = tmp_path / "v1.caffemodel"
    path.write_bytes(net.SerializeToString())

    model, variables = caffe.load(model_path=str(path))
    x_nchw = rng.standard_normal((1, 3, 4, 4)).astype(np.float32)
    x_nhwc = jnp.asarray(x_nchw.transpose(0, 2, 3, 1))
    out, _ = model.apply(variables, x_nhwc, training=False)
    want = jax.nn.softmax(
        jnp.asarray(x_nchw.reshape(1, -1)) @ jnp.asarray(w).T
        + jnp.asarray(b), axis=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_batchnorm_scale_eltwise_concat(tmp_path):
    """BN (global stats) + Scale + branch Eltwise/Concat paths load."""
    rng = np.random.default_rng(3)
    net = pb.NetParameter()
    net.input.append("data")
    net.input_shape.add().dim.extend([2, 4, 5, 5])

    bn = net.layer.add()
    bn.name, bn.type = "bn", "BatchNorm"
    bn.bottom.append("data"); bn.top.append("bn")
    mean = rng.standard_normal(4).astype(np.float32)
    var = np.abs(rng.standard_normal(4)).astype(np.float32) + 0.5
    _mk_blob(bn, mean); _mk_blob(bn, var)
    _mk_blob(bn, np.asarray([1.0], np.float32))

    sc = net.layer.add()
    sc.name, sc.type = "scale", "Scale"
    sc.bottom.append("bn"); sc.top.append("scale")
    sc.scale_param.bias_term = True
    gamma = rng.standard_normal(4).astype(np.float32)
    beta = rng.standard_normal(4).astype(np.float32)
    _mk_blob(sc, gamma); _mk_blob(sc, beta)

    add = net.layer.add()
    add.name, add.type = "sum", "Eltwise"
    add.bottom.append("scale"); add.bottom.append("data")
    add.top.append("sum")

    cat = net.layer.add()
    cat.name, cat.type = "cat", "Concat"
    cat.bottom.append("sum"); cat.bottom.append("data")
    cat.top.append("cat")  # default axis=1 → channels

    path = tmp_path / "bn.caffemodel"
    path.write_bytes(net.SerializeToString())
    model, variables = caffe.load(model_path=str(path))

    x_nchw = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    x = jnp.asarray(x_nchw.transpose(0, 2, 3, 1))
    out, _ = model.apply(variables, x, training=False)
    normed = (x - mean) / np.sqrt(var + 1e-5) * gamma + beta
    want = jnp.concatenate([normed + x, x], axis=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    assert out.shape == (2, 5, 5, 8)


def test_persist_reload_roundtrip_sequential(tmp_path):
    """Native model → caffe files → reload: outputs must match exactly."""
    seq = nn.Sequential()
    seq.add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1).set_name("c1"))
    seq.add(nn.ReLU().set_name("r1"))
    seq.add(nn.SpatialMaxPooling(2, 2, 2, 2).set_name("p1"))
    flat = nn.Sequential()
    flat.add(nn.Transpose(((2, 4), (3, 4))))
    flat.add(nn.Reshape((-1,), batch_mode=True))
    seq.add(flat)
    seq.add(nn.Linear(4 * 3 * 3, 7).set_name("fc"))
    seq.add(nn.SoftMax().set_name("prob"))
    variables = seq.init(jax.random.PRNGKey(7))

    dp = tmp_path / "m.prototxt"
    mp = tmp_path / "m.caffemodel"
    caffe.persist(str(dp), str(mp), seq, variables, (1, 3, 6, 6))

    loaded, lvars = caffe.load(str(dp), str(mp))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 6, 3))
    out0, _ = seq.apply(variables, x, training=False)
    out1, _ = loaded.apply(lvars, x, training=False)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1),
                               rtol=1e-5, atol=1e-6)
    # prototxt is valid text format naming every layer
    assert "c1" in dp.read_text() and "InnerProduct" in dp.read_text()


def test_persist_reload_roundtrip_graph_branches(tmp_path):
    """Graph with concat + eltwise branches round-trips."""
    x = Input()
    c1 = nn.SpatialConvolution(2, 3, 1, 1).set_name("b1")(x)
    c2 = nn.SpatialConvolution(2, 3, 1, 1).set_name("b2")(x)
    cat = nn.JoinTable(dimension=4, n_input_dims=4).set_name("cat")(c1, c2)
    s = nn.CAddTable().set_name("add")(cat, cat)
    g = Graph(x, s)
    variables = g.init(jax.random.PRNGKey(3))

    dp = tmp_path / "g.prototxt"
    mp = tmp_path / "g.caffemodel"
    caffe.persist(str(dp), str(mp), g, variables, (1, 2, 4, 4))
    loaded, lvars = caffe.load(str(dp), str(mp))

    xv = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 4, 2))
    out0, _ = g.apply(variables, xv, training=False)
    out1, _ = loaded.apply(lvars, xv, training=False)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1),
                               rtol=1e-5, atol=1e-6)


def test_unsupported_layer_raises(tmp_path):
    net = pb.NetParameter()
    net.input.append("data")
    net.input_shape.add().dim.extend([1, 2, 3, 3])
    l = net.layer.add()
    l.name, l.type = "mystery", "FancyNewLayer"
    l.bottom.append("data"); l.top.append("out")
    path = tmp_path / "bad.caffemodel"
    path.write_bytes(net.SerializeToString())
    with pytest.raises(NotImplementedError, match="FancyNewLayer"):
        caffe.load(model_path=str(path))


def test_inner_product_transpose_blob(tmp_path):
    """transpose=true stores the blob input-major (K, num_output)."""
    rng = np.random.default_rng(7)
    net = pb.NetParameter()
    net.input.append("data")
    net.input_shape.add().dim.extend([1, 6])
    fc = net.layer.add()
    fc.name, fc.type = "fc", "InnerProduct"
    fc.bottom.append("data"); fc.top.append("fc")
    fc.inner_product_param.num_output = 4
    fc.inner_product_param.transpose = True
    w = rng.standard_normal((6, 4)).astype(np.float32)  # (K, N)
    b = rng.standard_normal((4,)).astype(np.float32)
    _mk_blob(fc, w); _mk_blob(fc, b)
    path = tmp_path / "t.caffemodel"
    path.write_bytes(net.SerializeToString())

    model, variables = caffe.CaffeLoader(model_path=str(path)).load()
    x = rng.standard_normal((3, 6)).astype(np.float32)
    out, _ = model.apply(variables, jnp.asarray(x), training=False)
    np.testing.assert_allclose(np.asarray(out), x @ w + b,
                               rtol=1e-5, atol=1e-5)


def test_prototxt_only_fresh_init(tmp_path):
    """Architecture-only import: unmatched layers keep fresh init."""
    from google.protobuf import text_format

    rng = np.random.default_rng(8)
    net, _ = _simple_net(rng)
    arch = pb.NetParameter(); arch.CopyFrom(net)
    for l in arch.layer:
        del l.blobs[:]
    def_path = tmp_path / "arch.prototxt"
    def_path.write_text(text_format.MessageToString(arch))

    ldr = caffe.CaffeLoader(def_path=str(def_path))
    model, variables = ldr.load()
    assert set(ldr.unmatched) == {"conv1", "fc1"}
    x = rng.standard_normal((2, 8, 8, 2)).astype(np.float32)
    out, _ = model.apply(variables, jnp.asarray(x), training=False)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-5)


def test_accuracy_layer_does_not_hide_output(tmp_path):
    """A terminal blob also feeding Accuracy must stay an output."""
    rng = np.random.default_rng(9)
    net, _ = _simple_net(rng)
    acc = net.layer.add()
    acc.name, acc.type = "accuracy", "Accuracy"
    acc.bottom.append("prob"); acc.bottom.append("label")
    acc.top.append("accuracy")
    path = tmp_path / "acc.caffemodel"
    path.write_bytes(net.SerializeToString())

    model, variables = caffe.CaffeLoader(model_path=str(path)).load()
    x = rng.standard_normal((1, 8, 8, 2)).astype(np.float32)
    out, _ = model.apply(variables, jnp.asarray(x), training=False)
    assert out.shape == (1, 10)


def test_concat_negative_axis(tmp_path):
    rng = np.random.default_rng(10)
    net = pb.NetParameter()
    net.input.append("a"); net.input_shape.add().dim.extend([1, 2, 4, 4])
    net.input.append("b"); net.input_shape.add().dim.extend([1, 3, 4, 4])
    cat = net.layer.add()
    cat.name, cat.type = "cat", "Concat"
    cat.bottom.append("a"); cat.bottom.append("b"); cat.top.append("cat")
    cat.concat_param.axis = -3  # == channel axis of a 4-D blob
    path = tmp_path / "cat.caffemodel"
    path.write_bytes(net.SerializeToString())

    model, variables = caffe.CaffeLoader(model_path=str(path)).load()
    a = rng.standard_normal((1, 4, 4, 2)).astype(np.float32)
    b = rng.standard_normal((1, 4, 4, 3)).astype(np.float32)
    out, _ = model.apply(variables, jnp.asarray(a), jnp.asarray(b),
                         training=False)
    assert out.shape == (1, 4, 4, 5)


def test_floor_pooling_roundtrip(tmp_path):
    """ceil_mode=False survives persist → load (round_mode=FLOOR)."""
    m = nn.Sequential(
        nn.SpatialConvolution(2, 3, 3, 3).set_name("c"),
        nn.SpatialMaxPooling(2, 2, 2, 2, ceil_mode=False).set_name("p"),
    )
    variables = m.init(jax.random.PRNGKey(0))
    dp = tmp_path / "f.prototxt"; mp = tmp_path / "f.caffemodel"
    caffe.persist(str(dp), str(mp), m, variables,
                  input_shape=(1, 7, 7, 2))
    model2, vars2 = caffe.load(str(dp), str(mp))
    x = jnp.asarray(np.random.default_rng(3).standard_normal(
        (1, 7, 7, 2)).astype(np.float32))
    want, _ = m.apply(variables, x, training=False)
    got, _ = model2.apply(vars2, x, training=False)
    assert got.shape == want.shape  # floor: (1,2,2,3), ceil would be 3x3
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_persister_keeps_non_flatten_transpose_reshape(tmp_path):
    """A transpose/reshape pair that is NOT the flatten idiom must not be
    collapsed into a Caffe Flatten layer."""
    m = nn.Sequential(
        nn.Transpose([(2, 3)]).set_name("t"),
        nn.Reshape((4, -1)).set_name("r"),
    )
    variables = m.init(jax.random.PRNGKey(0))
    dp = tmp_path / "nf.prototxt"; mp = tmp_path / "nf.caffemodel"
    try:
        caffe.persist(str(dp), str(mp), m, variables,
                      input_shape=(1, 2, 2, 4))
    except NotImplementedError:
        return  # refusing to export is fine; silently flattening is not
    net = pb.NetParameter()
    net.ParseFromString((tmp_path / "nf.caffemodel").read_bytes())
    assert not any(l.type == "Flatten" for l in net.layer)


def test_deconvolution_matches_torch(tmp_path):
    """Deconvolution fixture → SpatialFullConvolution, oracled against
    torch ConvTranspose2d (VERDICT r3 item 9)."""
    import torch

    rng = np.random.default_rng(7)
    net = pb.NetParameter()
    net.name = "deconv_net"
    net.input.append("data")
    net.input_shape.add().dim.extend([1, 3, 5, 5])

    dc = net.layer.add()
    dc.name, dc.type = "up1", "Deconvolution"
    dc.bottom.append("data"); dc.top.append("up1")
    cp = dc.convolution_param
    cp.num_output = 4
    cp.kernel_size.append(4); cp.stride.append(2); cp.pad.append(1)
    w = rng.standard_normal((3, 4, 4, 4)).astype(np.float32)  # (I,O,kH,kW)
    b = rng.standard_normal((4,)).astype(np.float32)
    _mk_blob(dc, w); _mk_blob(dc, b)

    path = tmp_path / "deconv.caffemodel"
    path.write_bytes(net.SerializeToString())
    model, variables = caffe.load(model_path=str(path))

    x_nchw = rng.standard_normal((2, 3, 5, 5)).astype(np.float32)
    out, _ = model.apply(variables,
                         jnp.asarray(x_nchw.transpose(0, 2, 3, 1)),
                         training=False)
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x_nchw), torch.from_numpy(w),
        torch.from_numpy(b), stride=2, padding=1)
    np.testing.assert_allclose(
        np.asarray(out), want.numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-4)

    # round-trip through the persister
    def_p, mod_p = tmp_path / "d.prototxt", tmp_path / "d.caffemodel"
    caffe.persist(str(def_p), str(mod_p), model, variables, (1, 5, 5, 3))
    model2, vars2 = caffe.load(str(def_p), str(mod_p))
    out2, _ = model2.apply(vars2,
                           jnp.asarray(x_nchw.transpose(0, 2, 3, 1)),
                           training=False)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out),
                               rtol=1e-5, atol=1e-5)

def test_persist_asymmetric_padding_clear_error(tmp_path):
    """Tuple (low, high) padding (s2d stem) has no Caffe encoding —
    must raise a clear ValueError, not an opaque protobuf TypeError."""
    seq = nn.Sequential()
    seq.add(nn.SpatialConvolution(3, 4, 2, 2, 2, 2,
                                  pad_w=(0, 1), pad_h=(0, 1)
                                  ).set_name("s2d"))
    variables = seq.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="asymmetric"):
        caffe.persist(str(tmp_path / "m.prototxt"),
                      str(tmp_path / "m.caffemodel"),
                      seq, variables, (1, 3, 8, 8))

def test_grouped_dilated_deconvolution_matches_torch(tmp_path):
    """Grouped + dilated Deconvolution (VERDICT r4 missing #6): loads,
    matches torch ConvTranspose2d(groups, dilation), and round-trips
    through the persister."""
    import torch

    rng = np.random.default_rng(8)
    net = pb.NetParameter()
    net.name = "gdeconv_net"
    net.input.append("data")
    net.input_shape.add().dim.extend([1, 4, 5, 5])

    dc = net.layer.add()
    dc.name, dc.type = "up1", "Deconvolution"
    dc.bottom.append("data"); dc.top.append("up1")
    cp = dc.convolution_param
    cp.num_output = 6
    cp.kernel_size.append(3); cp.stride.append(2); cp.pad.append(1)
    cp.group = 2
    cp.dilation.append(2)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)  # (I,O/g,k,k)
    b = rng.standard_normal((6,)).astype(np.float32)
    _mk_blob(dc, w); _mk_blob(dc, b)

    path = tmp_path / "gdeconv.caffemodel"
    path.write_bytes(net.SerializeToString())
    model, variables = caffe.load(model_path=str(path))

    x_nchw = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    out, _ = model.apply(variables,
                         jnp.asarray(x_nchw.transpose(0, 2, 3, 1)),
                         training=False)
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x_nchw), torch.from_numpy(w),
        torch.from_numpy(b), stride=2, padding=1, groups=2, dilation=2)
    np.testing.assert_allclose(
        np.asarray(out), want.numpy().transpose(0, 2, 3, 1),
        rtol=1e-4, atol=1e-4)

    # round-trip through the persister preserves group/dilation + values
    def_p, mod_p = tmp_path / "gd.prototxt", tmp_path / "gd.caffemodel"
    caffe.persist(str(def_p), str(mod_p), model, variables, (1, 5, 5, 4))
    model2, vars2 = caffe.load(str(def_p), str(mod_p))
    out2, _ = model2.apply(vars2,
                           jnp.asarray(x_nchw.transpose(0, 2, 3, 1)),
                           training=False)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out),
                               rtol=1e-5, atol=1e-6)
