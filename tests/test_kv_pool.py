"""Paged KV cache + radix prefix reuse (ISSUE 8): block-pool
primitives against the contiguous-cache oracle, allocator/ref-count/
COW invariants, LRU eviction determinism, the warm-vs-cold bitwise
pin, and the compile-count guard re-run under the paged cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.ops.kv_cache import (cached_attention, gather_block_cache,
                                    init_block_pool, init_layer_cache,
                                    paged_attention, update_cache,
                                    write_decode_blocks,
                                    write_prompt_blocks)
from bigdl_tpu.serving import BlockPool, InferenceEngine, Request
from bigdl_tpu.serving.prefix_cache import RadixPrefixCache


def _tiny_lm(max_len=64, layers=2):
    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=layers,
                 max_len=max_len)
    m.build(jax.random.PRNGKey(0))
    return m


# one module-shared model: engines over the same model object share
# jitted executables, so every block_size=4 engine below compiles the
# paged prefill/decode exactly once for this file
_SHARED_LM = None


def _shared_lm():
    global _SHARED_LM
    if _SHARED_LM is None:
        _SHARED_LM = _tiny_lm()
    return _SHARED_LM


class TestPagedPrimitives:
    """ops/kv_cache.py paged ops vs the dense (contiguous) oracle."""

    def test_paged_attention_matches_contiguous_bitwise(self):
        """Identical KV content read through a SHUFFLED block table
        must produce bit-identical attention output to the dense
        cached_attention — the gather is a pure relayout."""
        rng = np.random.RandomState(0)
        B, H, S, D, bs = 2, 2, 32, 8, 4
        nb = S // bs
        k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
        q = jnp.asarray(rng.randn(B, H, 1, D), jnp.float32)
        pos = jnp.asarray([13, 27], jnp.int32)

        kd, vd = init_layer_cache(B, H, S, D)
        from bigdl_tpu.ops.kv_cache import write_prefill
        kd, vd = write_prefill(kd, vd, k, v)
        dense = np.asarray(cached_attention(q, kd, vd, pos))

        # scatter the same content into a pool behind shuffled tables
        kp, vp = init_block_pool(1 + B * nb, H, bs, D)
        perm = rng.permutation(np.arange(1, 1 + B * nb))
        table = perm.reshape(B, nb).astype(np.int32)
        for b in range(B):
            kp, vp = write_prompt_blocks(
                kp, vp, k[b:b + 1], v[b:b + 1],
                jnp.asarray(table[b]))
        paged = np.asarray(paged_attention(q, kp, vp,
                                           jnp.asarray(table), pos))
        np.testing.assert_array_equal(dense, paged)

    def test_decode_write_matches_dense_update(self):
        """write_decode_blocks lands one row's k/v at exactly the
        (block, offset) the dense update_cache writes at `pos`."""
        rng = np.random.RandomState(1)
        B, H, S, D, bs = 2, 2, 16, 4, 4
        nb = S // bs
        kn = jnp.asarray(rng.randn(B, H, 1, D), jnp.float32)
        vn = jnp.asarray(rng.randn(B, H, 1, D), jnp.float32)
        pos = np.asarray([5, 14], np.int32)

        kd, vd = init_layer_cache(B, H, S, D)
        kd, vd = update_cache(kd, vd, kn, vn, jnp.asarray(pos))

        kp, vp = init_block_pool(1 + B * nb, H, bs, D)
        table = np.arange(1, 1 + B * nb, dtype=np.int32).reshape(B, nb)
        kp, vp = write_decode_blocks(
            kp, vp, kn, vn,
            jnp.asarray(table[np.arange(B), pos // bs]),
            jnp.asarray(pos % bs, np.int32))
        gk = np.asarray(gather_block_cache(kp, jnp.asarray(table)))
        gv = np.asarray(gather_block_cache(vp, jnp.asarray(table)))
        np.testing.assert_array_equal(np.asarray(kd)[0, :, 5],
                                      gk[0, :, 5])
        np.testing.assert_array_equal(np.asarray(vd)[1, :, 14],
                                      gv[1, :, 14])

    def test_write_prompt_blocks_pads_partial_bucket(self):
        """An 8-token bucket into 16-token blocks: one block, zero
        pad tail."""
        rng = np.random.RandomState(2)
        H, D, bs = 2, 4, 16
        k = jnp.asarray(rng.randn(1, H, 8, D), jnp.float32)
        kp, vp = init_block_pool(3, H, bs, D)
        kp, _ = write_prompt_blocks(kp, vp, k, k, jnp.asarray([2]))
        got = np.asarray(kp)
        np.testing.assert_array_equal(got[2, :, :8], np.asarray(k)[0])
        assert (got[2, :, 8:] == 0).all()
        assert (got[1] == 0).all()           # untouched block


class TestModelPagedParity:
    """TransformerLM paged prefill/decode vs the full forward and the
    dense incremental path."""

    @pytest.mark.slow
    def test_paged_decode_matches_full_forward(self):
        """Cold paged prefill + paged decode reproduces the full
        forward's next-token distribution at every position (fp32).
        Tier-2: the property rides tier-1 through the paged engine's
        greedy-vs-full-forward oracle (tests/test_serving.py) and the
        bitwise warm/cold pin below."""
        m = _tiny_lm()
        v = m.variables
        toks = np.random.RandomState(3).randint(0, 50, (1, 20)).astype(
            np.int32)
        full, _ = m.apply(v, jnp.asarray(toks))
        bs, nb = 4, 16 // 4
        pools = m.init_block_pool(1 + nb + 8, bs)
        table = np.zeros((1, 64 // bs), np.int32)
        blocks = np.arange(1, 1 + nb, dtype=np.int32)
        table[0, :nb] = blocks
        pools = m.prefill_paged(v, jnp.asarray(toks[:, :12]).reshape(
            1, 12)[:, :12], pools, jnp.asarray(table),
            jnp.asarray(blocks), 0)
        # grow the table for decode past position 16
        extra = np.arange(1 + nb, 1 + nb + 2, dtype=np.int32)
        table[0, nb:nb + 2] = extra
        for t in range(12, 20):
            logits, pools = m.decode_step_paged(
                v, jnp.asarray(toks[:, t]),
                jnp.full((1,), t, jnp.int32), pools,
                jnp.asarray(table))
            np.testing.assert_allclose(
                np.asarray(jax.nn.log_softmax(logits)),
                np.asarray(full[:, t]), atol=1e-5)

    def test_warm_cold_prefill_bitwise_identical(self):
        """THE extent-invariance pin (ops/kv_cache.py bit-identity
        contract): a position's KV computed by a cold bucket-16
        prefill equals — BITWISE — the same position computed by a
        warm bucket-8 suffix prefill over a reused prefix."""
        m = _tiny_lm()
        v = m.variables
        rng = np.random.RandomState(4)
        toks = rng.randint(1, 50, (1, 16)).astype(np.int32)
        bs = 4
        nb_slot = 64 // bs

        def fresh(n):
            return m.init_block_pool(1 + 2 * nb_slot, bs)

        # cold: all 16 tokens in one bucket-16 prefill
        cold_blocks = np.arange(1, 5, dtype=np.int32)
        cold_tab = np.zeros((1, nb_slot), np.int32)
        cold_tab[0, :4] = cold_blocks
        cold = m.prefill_paged(v, jnp.asarray(toks), fresh(0),
                               jnp.asarray(cold_tab),
                               jnp.asarray(cold_blocks), 0)

        # warm: prefix = first 8 tokens (2 blocks) prefilled first,
        # then the suffix [8:16] as a bucket-8 prefill at start=8
        pools = fresh(1)
        pre_blocks = np.arange(1, 3, dtype=np.int32)
        pre_tab = np.zeros((1, nb_slot), np.int32)
        pre_tab[0, :2] = pre_blocks
        pools = m.prefill_paged(v, jnp.asarray(toks[:, :8]), pools,
                                jnp.asarray(pre_tab),
                                jnp.asarray(pre_blocks), 0)
        suf_blocks = np.arange(3, 5, dtype=np.int32)
        warm_tab = np.zeros((1, nb_slot), np.int32)
        warm_tab[0, :2] = pre_blocks
        warm_tab[0, 2:4] = suf_blocks
        warm = m.prefill_paged(v, jnp.asarray(toks[:, 8:]), pools,
                               jnp.asarray(warm_tab),
                               jnp.asarray(suf_blocks),
                               jnp.asarray(8, jnp.int32))
        for lc, lw in zip(cold, warm):
            for leaf in ("k", "v"):
                np.testing.assert_array_equal(
                    np.asarray(lc[leaf])[1:5],
                    np.asarray(lw[leaf])[1:5])


class TestBlockPool:
    def test_alloc_order_deterministic(self):
        p = BlockPool(8, 4)
        assert p.alloc(3) == [1, 2, 3]
        assert p.capacity == 7 and p.free_count == 4
        assert p.alloc(5) is None            # short → no partial take
        assert p.free_count == 4
        p.unref([2])
        assert p.alloc(1) == [2]             # LIFO: freed block reused
        p2 = BlockPool(8, 4)                 # first, deterministically
        assert p2.alloc(3) == [1, 2, 3]      # fresh pool, same order

    def test_ref_unref_cow_invariants(self):
        p = BlockPool(8, 4)
        (a, b) = p.alloc(2)
        p.mark_cached(a)                     # tree inserts while ref'd
        p.ref([a])                           # a second user (shared)
        assert p.refcount(a) == 2 and p.in_tree(a)
        assert p.unref([a]) == []            # still shared
        assert p.unref([a]) == []            # → cached, NOT freed
        assert p.cached_count == 1 and p.free_count == 5
        assert p.unref([b]) == [b]           # plain block → freed
        p.ref([a])                           # cache revival
        assert p.cached_count == 0 and p.refcount(a) == 1
        with pytest.raises(ValueError, match="unreferenced"):
            p.unref([b])

    def test_guards(self):
        with pytest.raises(ValueError, match="scratch"):
            BlockPool(1, 4)
        with pytest.raises(ValueError, match="block_size"):
            BlockPool(8, 1)
        p = BlockPool(4, 4)
        with pytest.raises(ValueError, match="unreferenced"):
            p.mark_cached(1)


class TestRadixPrefixCache:
    def _cached_chain(self, pool, tree, tokens):
        n = (len(tokens)) // pool.block_size
        blocks = pool.alloc(n)
        owned = tree.insert(tokens, blocks)
        for b in owned:
            pool.mark_cached(b)
        pool.unref(blocks)                   # park as cached
        return blocks

    def test_lookup_insert_roundtrip_and_cap(self):
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool)
        toks = list(range(1, 13))            # 12 tokens = 3 blocks
        blocks = self._cached_chain(pool, tree, toks)
        assert tree.lookup(toks, 3) == blocks
        assert tree.lookup(toks, 2) == blocks[:2]     # caller's cap
        assert tree.lookup(toks[:7], 1) == blocks[:1]
        assert tree.lookup([9] + toks, 3) == []       # shifted: miss
        # a diverging suffix shares only the common block-aligned part
        other = toks[:8] + [40, 41, 42, 43]
        assert tree.lookup(other, 3) == blocks[:2]

    def test_lru_eviction_order_deterministic(self):
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool)
        a = self._cached_chain(pool, tree, list(range(1, 9)))
        b = self._cached_chain(pool, tree, [20, 21, 22, 23])
        tree.lookup(list(range(1, 9)), 2)    # touch chain a
        # LRU leaf is b's block; then a's chain leaf-first (deepest
        # node first — interior nodes wait for their subtree)
        assert tree.evict_one() == b[0]
        assert tree.evict_one() == a[1]
        assert tree.evict_one() == a[0]
        assert tree.evict_one() is None
        assert pool.free_count == pool.capacity

    def test_refd_blocks_never_evict(self):
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool)
        a = self._cached_chain(pool, tree, list(range(1, 9)))
        pool.ref([a[0]])                     # an active user
        assert tree.evict_one() == a[1]      # leaf with ref 0
        assert tree.evict_one() is None      # a[0] pinned
        pool.unref([a[0]])
        assert tree.evict_one() == a[0]

    def test_forget_block_leaf_only(self):
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool)
        a = self._cached_chain(pool, tree, list(range(1, 9)))
        assert not tree.forget_block(a[0])   # interior: refused
        assert tree.forget_block(a[1])
        assert tree.forget_block(a[0])       # now a leaf


class TestEnginePaged:
    def test_warm_vs_cold_bit_identity_in_cobatch(self):
        """The tentpole acceptance: a cached-prefix admission decodes
        tokens bit-identical to the cold run of the same request —
        co-batched with a stranger."""
        m = _shared_lm()
        A = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=5, temperature=0.8, seed=11)
        S = dict(prompt=[30, 31, 32], max_new_tokens=5,
                 temperature=0.9, seed=4)
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              block_size=4)
        cold = eng.run([Request(**A)])[0]
        assert eng.stats["prefix_hits"] == 0
        warm, stranger = eng.run([Request(**A), Request(**S)])
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["prefix_tokens_saved"] == 12
        assert warm.tokens == cold.tokens
        alone_s = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                                  block_size=4).run([Request(**S)])[0]
        assert stranger.tokens == alone_s.tokens

    def test_compile_count_guard_paged(self):
        """The #buckets+1 contract under the PAGED cache: ragged
        traffic WITH prefix hits and LRU evictions still compiles
        exactly (#buckets used) suffix prefills + 1 decode, and a
        second wave (all shapes + reuse paths warm) compiles
        NOTHING."""
        m = _tiny_lm()                       # fresh: attribute traces
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              block_size=4, max_len=32,
                              pool_blocks=12)
        rng = np.random.RandomState(0)
        shared = list(rng.randint(1, 50, 9))
        wave = [Request(prompt=shared + [int(x)], max_new_tokens=3,
                        seed=i)
                for i, x in enumerate(rng.randint(1, 50, 3))]
        wave += [Request(prompt=list(rng.randint(1, 50, 4)),
                         max_new_tokens=3, seed=9)]
        eng.run(wave)
        assert eng.stats["prefix_hits"] >= 2          # shared head hit
        assert eng.stats["prefill_traces"] == 2       # buckets 8 + 16
        assert eng.stats["decode_traces"] == 1
        # churn until the pool must evict, then a reuse wave: still 0
        for i in range(4):
            eng.run([Request(prompt=list(rng.randint(1, 50, 9)),
                             max_new_tokens=2, seed=20 + i)])
        eng.run([Request(prompt=shared + [7], max_new_tokens=3,
                         seed=40),
                 Request(prompt=list(rng.randint(1, 50, 12)),
                         max_new_tokens=2, seed=41)])
        assert eng.stats["pool_evictions"] > 0
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1

    @pytest.mark.slow
    def test_pool_exhausted_finishes_gracefully(self):
        """A generation that outgrows an exhausted pool finishes
        'pool_exhausted' (partial tokens kept, status done); the
        co-resident request is unaffected. Tier-2: the allocator's
        failure mode is unit-tested (TestBlockPool) and the admission
        requeue path rides tier-1 via the hit-chain-pin test."""
        m = _shared_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              block_size=4, max_len=32, pool_blocks=9,
                              prefix_cache=False)
        a, b = eng.run([
            Request(prompt=[1, 2, 3, 4, 5, 6, 7, 8, 9], max_new_tokens=20,
                    seed=1),
            Request(prompt=[9, 8, 7, 6, 5, 4, 3, 2, 1], max_new_tokens=20,
                    seed=2)])
        # 8 usable blocks, both 9-token prompts hold a 16-bucket
        # (4 blocks) each: growth past position 16 finds an empty free
        # list — slot 0 finishes 'pool_exhausted' with its 8 partial
        # tokens (status done), and its freed blocks deterministically
        # let slot 1 run to completion
        assert a.status == "done"
        assert a.finish_reason == "pool_exhausted"
        assert len(a.tokens) == 8
        assert b.status == "done" and b.finish_reason == "max_tokens"
        assert len(b.tokens) == 20
        # the freed blocks serve the next request normally
        c = eng.run([Request(prompt=[2, 4, 6], max_new_tokens=3,
                             seed=3)])[0]
        assert c.finish_reason == "max_tokens"

    def test_hit_chain_pinned_against_admission_eviction(self):
        """Regression: the allocator's LRU eviction during an
        admission must never reclaim the hit chain that same admission
        just matched (it is refcount-0 'cached' until the admission
        refs it — the engine pins it BEFORE allocating). Starved of
        blocks, the admission requeues instead; once the co-resident
        request frees blocks it admits with the prefix intact and
        decodes bit-identical to cold."""
        m = _shared_lm()

        def eng(prefix):
            return InferenceEngine(m, slots=2, prefill_buckets=(16,),
                                   block_size=4, max_len=32,
                                   pool_blocks=9, prefix_cache=prefix)

        P = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=3, temperature=0.8, seed=11)
        cold = eng(False).run([Request(**P)])[0]
        e = eng(True)
        e.run([Request(**P)])                # caches P's 3-block chain
        # a long-running stranger pins 4 of the 5 free blocks...
        lid = e.submit(Request(prompt=[20, 21, 22, 23, 24, 25, 26, 27,
                                       28],
                               max_new_tokens=6, seed=1))
        e.step()
        # ...so Q (= P resubmitted) matches the cached chain but finds
        # only 1 free block for its 4-block suffix bucket: it must
        # WAIT (requeue), not let eviction eat its own hit chain
        qid = e.submit(Request(**P))
        while e._queue or any(r is not None for r in e._req):
            for res in e.step():
                e.completed[res.id] = res
        q = e.completed[qid]
        assert e.completed[lid].status == "done"
        assert e.stats["prefix_hits"] == 1
        assert e.stats["prefix_tokens_saved"] == 12
        assert q.tokens == cold.tokens

    def test_poisoned_exclusive_chain_fully_forgotten(self):
        """Regression: a poisoned request's EXCLUSIVE inserted chain
        must be forgotten whole (deep-to-shallow — forget_block
        removes leaves only), not just its deepest block: nothing a
        poisoned request wrote may stay addressable in the radix
        tree."""
        from bigdl_tpu.utils import faults

        m = _shared_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              block_size=4)
        P = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=5, temperature=0.8, seed=11)
        faults.set_plan(faults.FaultPlan("serve_nan@1"))
        try:
            got = eng.run([Request(**P)])[0]
        finally:
            faults.set_plan(None)
        assert got.status == "poisoned"
        assert eng.health()["prefix"]["tree_blocks"] == 0
        # a resubmission must prefill COLD — zero reuse of anything
        # the poisoned request wrote
        eng.run([Request(**P)])
        assert eng.stats["prefix_hits"] == 0

    def test_knob_validation(self):
        m = _shared_lm()
        with pytest.raises(ValueError, match="multiple of block_size"):
            InferenceEngine(m, slots=1, max_len=30, block_size=4)
        with pytest.raises(ValueError, match="block_size"):
            InferenceEngine(m, slots=1, block_size=1)
        with pytest.raises(ValueError, match="pool_blocks"):
            InferenceEngine(m, slots=1, block_size=16, pool_blocks=3)

    def test_admit_requeue_budget_bounds_spin(self):
        """Regression (ISSUE 16 satellite): an admission that can
        NEVER succeed (pool pinned by an external holder, nothing in
        flight to free blocks) must not spin the request through the
        queue forever — after `admit_requeue_budget` requeues it
        finishes 'pool_exhausted' (status done, zero tokens) and bumps
        the exhaustion counter; the pool stays serviceable once blocks
        return."""
        m = _shared_lm()
        eng = InferenceEngine(m, slots=1, prefill_buckets=(8,),
                              block_size=4, max_len=16, pool_blocks=5,
                              prefix_cache=False,
                              admit_requeue_budget=3)
        pinned = eng._pool_mgr.alloc(4)      # every usable block held
        r = eng.run([Request(prompt=[1, 2, 3], max_new_tokens=2,
                             seed=0)])[0]
        assert r.status == "done"
        assert r.finish_reason == "pool_exhausted"
        assert r.tokens == []
        assert eng.stats["admit_requeue_exhausted"] == 1
        eng._pool_mgr.unref(pinned)
        ok = eng.run([Request(prompt=[1, 2, 3], max_new_tokens=2,
                              seed=0)])[0]
        assert ok.finish_reason == "max_tokens"

    def test_multi_turn_resubmission_reuses_history(self):
        """The loadgen multi-turn shape: turn 2 resubmits turn 1's
        prompt + output and must hit the cached history prefix, with
        tokens bit-identical to a cold engine's run of the same
        turn-2 prompt."""
        m = _shared_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              block_size=4)
        t1 = eng.run([Request(prompt=[3, 1, 4, 1, 5, 9, 2, 6],
                              max_new_tokens=4, temperature=0.7,
                              seed=13)])[0]
        follow = list(t1.prompt) + list(t1.tokens) + [42]
        t2 = eng.run([Request(prompt=follow, max_new_tokens=4,
                              temperature=0.7, seed=14)])[0]
        assert eng.stats["prefix_hits"] == 1
        assert eng.stats["prefix_tokens_saved"] >= 4
        cold = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                               block_size=4).run(
            [Request(prompt=follow, max_new_tokens=4, temperature=0.7,
                     seed=14)])[0]
        assert t2.tokens == cold.tokens


class TestSpillTier:
    """Host-RAM block spill tier (ISSUE 16): tree-level spill/park/
    re-admit/graft units, the engine round-trip bitwise pin, and the
    compile-count guard re-pinned with the tier armed."""

    def _cached_chain(self, pool, tree, tokens):
        blocks = pool.alloc(len(tokens) // pool.block_size)
        for b in tree.insert(tokens, blocks):
            pool.mark_cached(b)
        pool.unref(blocks)
        return blocks

    def test_spill_victim_selection_lru_refd_protect(self):
        """spill_victims returns LRU refcount-0 device nodes (stamp,
        then insertion-order tie-break), skips ref'd blocks and the
        protected chain — and unlike eviction has NO leaf-only rule."""
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool, host_blocks=8)
        a = self._cached_chain(pool, tree, list(range(1, 9)))
        b = self._cached_chain(pool, tree, [20, 21, 22, 23])
        tree.lookup(list(range(1, 9)), 2)    # touch chain a
        got = [n.block for n in tree.spill_victims(3)]
        assert got == [b[0], a[0], a[1]]     # b LRU; a root-first
        pool.ref([a[0]])                     # an active user pins it
        assert [n.block for n in tree.spill_victims(3)] == [b[0], a[1]]
        pool.unref([a[0]])
        prot = frozenset(tree.lookup_nodes(list(range(1, 9)), 2))
        assert [n.block for n in tree.spill_victims(3, prot)] == [b[0]]

    def test_park_readmit_roundtrip_and_tier_surfaces(self):
        """park moves a victim's block to the free list and its bytes
        to the host tier; the device-block surface (lookup) stops at
        the parked node while the tier-aware walk still matches;
        readmit hands the bytes back and re-joins the device tier."""
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool, host_blocks=8)
        toks = list(range(1, 9))
        a = self._cached_chain(pool, tree, toks)
        free0 = pool.free_count
        node = tree.spill_victims(1)[0]      # root-most of chain a
        assert node.block == a[0]
        assert tree.park(node, "BYTES") == a[0]
        assert pool.free_count == free0 + 1
        assert (tree.num_blocks, tree.host_in_use) == (1, 1)
        assert tree.lookup(toks, 2) == []    # chain starts on host
        assert len(tree.lookup_nodes(toks, 2)) == 2
        assert tree.peek_blocks(toks, 2) == 2
        nb = pool.alloc(1)[0]
        assert tree.readmit(node, nb) == "BYTES"
        pool.mark_cached(nb)
        pool.unref([nb])
        assert tree.lookup(toks, 2) == [nb, a[1]]
        assert tree.host_in_use == 0

    def test_host_eviction_childless_only_and_graft(self):
        """evict_host_one drops only CHILDLESS host nodes (deepest
        first — interior nodes wait for their subtree); graft_host
        seeds parents-first, lets incumbents win, refuses orphans,
        makes room by host-LRU, and is disabled at host_blocks=0."""
        pool = BlockPool(32, 4)
        tree = RadixPrefixCache(pool, host_blocks=8)
        toks = list(range(1, 9))
        self._cached_chain(pool, tree, toks)
        for node in tree.spill_victims(2):
            tree.park(node, bytes(node.tokens))
        assert tree.host_in_use == 2
        assert tree.evict_host_one()         # deepest (childless)
        assert tree.evict_host_one()         # then its parent
        assert not tree.evict_host_one()
        assert tree.peek_blocks(toks, 2) == 0

        t2 = RadixPrefixCache(BlockPool(8, 4), host_blocks=2)
        assert t2.graft_host(toks[:4], "D0")
        assert t2.graft_host(toks, "D1")
        assert t2.host_in_use == 2
        # orphan: depth-2 entry whose parent chunk was never imported
        assert not t2.graft_host([70, 71, 72, 73, 80, 81, 82, 83],
                                 "ORPHAN")
        assert not t2.graft_host(toks[:4], "X")     # incumbent wins
        assert t2.graft_host([90, 91, 92, 93], "D2")  # evicts LRU
        assert t2.host_in_use == 2
        assert t2.peek_blocks(toks, 2) == 1  # D1 made way for D2
        t3 = RadixPrefixCache(BlockPool(8, 4))      # tier disabled
        assert not t3.graft_host(toks[:4], "D0")

    def test_spill_readmit_round_trip_bit_identity(self):
        """THE tentpole acceptance pin: a chain pushed to the host
        tier by pool pressure and re-admitted on the next hit decodes
        tokens BITWISE identical to the cold run AND to the original
        warm run — spilled blocks are bytes, never recomputation."""
        m = _shared_lm()
        P = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=3, temperature=0.8, seed=11)
        F = dict(prompt=[30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40,
                         41, 42],
                 max_new_tokens=3, temperature=0.8, seed=2)
        cold = InferenceEngine(m, slots=1, prefill_buckets=(8, 16),
                               block_size=4, max_len=20, pool_blocks=6,
                               prefix_cache=False).run(
            [Request(**P)])[0]
        # 5 usable blocks: P's 13-token prompt holds 4, so its cached
        # 3-block chain MUST spill to admit F — and F's must spill to
        # re-admit P
        eng = InferenceEngine(m, slots=1, prefill_buckets=(8, 16),
                              block_size=4, max_len=20, pool_blocks=6,
                              spill=True, host_blocks=8)
        first = eng.run([Request(**P)])[0]
        assert first.tokens == cold.tokens
        eng.run([Request(**F)])              # pressure: P's chain spills
        assert eng.stats["kv_spill_blocks"] >= 1
        assert eng.health()["prefix"]["host_in_use"] >= 1
        warm = eng.run([Request(**P)])[0]
        assert eng.stats["kv_readmit_blocks"] >= 1
        assert eng.stats["prefix_hits"] >= 1
        assert warm.tokens == cold.tokens == first.tokens

    def test_compile_guard_with_spill_armed(self):
        """The #buckets+1 contract holds with the tier armed: spill
        waves and host re-admissions compile ZERO new executables — a
        re-admit is a device_put + block-table patch, never a prefill
        of the parked positions."""
        m = _tiny_lm()                       # fresh: attribute traces
        eng = InferenceEngine(m, slots=1, prefill_buckets=(8, 16),
                              block_size=4, max_len=20, pool_blocks=6,
                              spill=True, host_blocks=8)
        P = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=3, temperature=0.8, seed=11)
        F = dict(prompt=[30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40,
                         41, 42],
                 max_new_tokens=3, temperature=0.8, seed=2)
        eng.run([Request(**P)])              # bucket 16 + decode
        eng.run([Request(**F)])              # spill wave
        eng.run([Request(**P)])              # re-admit + bucket-8 suffix
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1
        eng.run([Request(**F)])              # spill AND re-admit again:
        eng.run([Request(**P)])              # every path now warm
        assert eng.stats["kv_spill_blocks"] > 0
        assert eng.stats["kv_readmit_blocks"] > 0
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1

    def test_spill_knob_validation(self):
        m = _shared_lm()
        with pytest.raises(ValueError, match="prefix_cache"):
            InferenceEngine(m, slots=1, block_size=4, max_len=16,
                            spill=True, prefix_cache=False)
        with pytest.raises(ValueError, match="host_blocks"):
            InferenceEngine(m, slots=1, block_size=4, max_len=16,
                            host_blocks=4)
        with pytest.raises(ValueError, match="host_blocks"):
            InferenceEngine(m, slots=1, block_size=4, max_len=16,
                            spill=True, host_blocks=0)
        with pytest.raises(ValueError, match="admit_requeue_budget"):
            InferenceEngine(m, slots=1, block_size=4, max_len=16,
                            admit_requeue_budget=0)
