"""Serving fleet plane (ISSUE 7): EngineRouter dispatch/spillover/
failover/drain/rebalance, the Autoscaler's deterministic closed loop,
the fleet-wide compile contract, and the loadgen traffic harness.

The headline guarantees — failover bit-identity and autoscaler
determinism — are ALSO drilled end-to-end in scripts/fault_drill.py
(fleet_* legs, tier-1 via test_fault_drill); this file covers the
router/autoscaler machinery those drills ride on, at unit granularity.
"""

import importlib.util
import os
import sys

import jax
import pytest

from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.serving import (Autoscaler, EngineDraining, EngineRouter,
                               InferenceEngine, NoHealthyEngine,
                               OverloadError, Request)
from bigdl_tpu.utils import faults

# one module-shared model: engines over the same model object share
# jitted executables, so this file pays the compile once (the
# compile-count test builds its OWN fresh model to attribute traces)
_LM = None


def _lm():
    global _LM
    if _LM is None:
        _LM = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=2,
                       max_len=64)
        _LM.build(jax.random.PRNGKey(0))
    return _LM


def _engine(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_buckets", (8,))
    return InferenceEngine(_lm(), **kw)


def _loadgen():
    mod = sys.modules.get("bigdl_loadgen")  # one shared module object
    if mod is not None:
        return mod
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "loadgen.py")
    spec = importlib.util.spec_from_file_location("bigdl_loadgen", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bigdl_loadgen"] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


_SPECS = [dict(prompt=[i + 1, i + 2, i + 3], max_new_tokens=4,
               temperature=0.8, seed=60 + i) for i in range(6)]


def _ref_tokens():
    """Undisturbed single-engine oracle for _SPECS (tokens are slot/
    co-batch/arrival independent, so one engine is THE reference)."""
    return [r.tokens for r in _engine().run([Request(**s)
                                             for s in _SPECS])]


class TestDispatch:
    def test_least_loaded_dispatch_and_run_semantics(self):
        ref = _ref_tokens()
        e0, e1 = _engine(), _engine()
        router = EngineRouter([e0, e1])
        out = router.run([Request(**s) for s in _SPECS])
        assert [r.tokens for r in out] == ref
        assert all(r.status == "done" for r in out)
        # load-balanced: both engines actually served traffic
        assert e0.stats["requests_done"] == 3
        assert e1.stats["requests_done"] == 3
        assert router.stats["dispatched"] == 6

    def test_spillover_past_full_queue(self):
        """A bounded reject-policy queue spills to the next engine
        instead of bouncing the caller; only a pool-wide full raises.
        (Spillover needs a LOW-load-score engine whose queue is
        nevertheless full: e0 has many slots but a 1-deep queue.)"""
        e0 = _engine(slots=4, max_queue=1, overload_policy="reject")
        e1 = _engine(slots=1, max_queue=4, overload_policy="reject")
        router = EngineRouter([e0, e1])
        for i in range(5):      # capacity pre-step: 1 (e0) + 4 (e1)
            router.submit(Request(prompt=[i + 1, i + 2],
                                  max_new_tokens=2, seed=i))
        assert router.stats["spillover"] >= 1
        with pytest.raises(OverloadError):
            router.submit(Request(prompt=[8, 8], max_new_tokens=2))
        assert router.stats["rejected"] == 1
        out = router.run()      # drain cleanly
        assert all(r.status == "done" for r in out)
        assert router.completed == {}  # run() handed everything back

    def test_submit_time_shed_surfaces_through_step(self):
        """A shed-policy victim settled AT SUBMIT TIME rides the next
        step() return — a driver loop (loadgen) accounts for every
        request it submitted, never hanging on a silent shed."""
        e0 = _engine(slots=1, max_queue=1,
                     overload_policy="shed-oldest")
        router = EngineRouter([e0])
        a = router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                  seed=1))
        b = router.submit(Request(prompt=[3, 4], max_new_tokens=2,
                                  seed=2))     # queue full: sheds a
        out = router.step()
        assert any(r.id == a and r.status == "shed" for r in out)
        while any(not e.idle for e in router.engines):
            router.step()
        assert router.completed[b].status == "done"

    def test_no_healthy_engine_raises(self):
        e0 = _engine()
        router = EngineRouter([e0])
        router.drain(e0)
        with pytest.raises(NoHealthyEngine):
            router.submit(Request(prompt=[1, 2]))

    def test_duplicate_router_id_rejected(self):
        router = EngineRouter([_engine()])
        router.submit(Request(prompt=[1, 2], max_new_tokens=2, id=5))
        with pytest.raises(ValueError, match="already in flight"):
            router.submit(Request(prompt=[3, 4], id=5))
        router.run()

    def test_rebalance_moves_backlog_to_idle_engine(self):
        """Queued work migrates to an engine with free capacity — the
        mechanism that makes scale-up absorb an existing backlog."""
        e0 = _engine()
        router = EngineRouter([e0])
        for s in _SPECS:
            router.submit(Request(**s))     # 2 in-flight + 4 queued
        router.step()
        e1 = router.add_engine(_engine())
        router.step()                       # rebalance, then decode
        assert router.stats["rebalanced"] >= 2
        assert e1.slots_active == 2
        out = router.run()
        assert [r.tokens for r in sorted(out, key=lambda r: r.id)] \
            == _ref_tokens()


class TestFailover:
    def test_failover_bit_identity_mid_decode(self):
        """Kill engine 0 (watchdog trip via serve_slow) mid-decode:
        every request it held completes on engine 1 with tokens
        bit-identical to the undisturbed run — the satellite
        acceptance, also drilled as fleet_failover."""
        ref = _ref_tokens()
        e0 = _engine(step_timeout_s=0.05)
        e1 = _engine()
        router = EngineRouter([e0, e1])
        faults.set_plan(faults.FaultPlan("serve_slow@1"))
        try:
            out = router.run([Request(**s) for s in _SPECS])
        finally:
            faults.set_plan(None)
        assert e0.degraded is not None and "watchdog" in e0.degraded
        assert all(r.status == "done" for r in out)
        assert [r.tokens for r in out] == ref
        assert router.stats["failover"] == 3
        assert router.stats["failover_lost"] == 0
        # the dead engine can now leave the pool
        router.remove_engine(e0)
        assert len(router.engines) == 1

    def test_failover_with_no_survivor_fails_requests(self):
        e0 = _engine(step_timeout_s=0.05)
        router = EngineRouter([e0])
        faults.set_plan(faults.FaultPlan("serve_slow@1"))
        try:
            out = router.run([Request(prompt=[1, 2, 3],
                                      max_new_tokens=4, seed=1)])
        finally:
            faults.set_plan(None)
        assert [r.status for r in out] == ["failed"]
        assert router.stats["failover_lost"] == 1


class TestDrain:
    def test_drain_states_and_gating(self):
        e0, e1 = _engine(), _engine()
        router = EngineRouter([e0, e1])
        ids = [router.submit(Request(**s)) for s in _SPECS[:4]]
        router.step()
        router.drain(e0)
        assert e0.health()["state"] == "draining"
        with pytest.raises(EngineDraining):
            e0.submit(Request(prompt=[1, 2]))
        # a premature removal is refused
        with pytest.raises(ValueError, match="drain"):
            router.remove_engine(e0)
        late = router.submit(Request(**_SPECS[4]))
        while any(not e.idle for e in router.engines):
            router.step()
        assert e0.health()["state"] == "drained"
        assert e0.stats["rejected"] == 0
        router.remove_engine(e0)
        assert len(router.engines) == 1
        results = {i: router.completed[i] for i in ids + [late]}
        assert all(r.status == "done" for r in results.values())
        # the late request never touched the draining engine
        assert e1.stats["requests_done"] == 3

    @pytest.mark.slow
    def test_draining_engine_donates_queue_when_room_exists(self):
        """A draining engine hands its queue to the pool as capacity
        frees up elsewhere — drain completes without serializing the
        backlog behind the drained slots. (Tier-2: the core drain
        contract is tier-1 above and in the fleet_drain drill; this
        pins the donation optimization.)"""
        # even ids (dispatched to e0) decode long, odd ids (e1) short:
        # e1 frees capacity while e0 still holds a queued request
        specs = [dict(prompt=[i + 1, i + 2, i + 3],
                      max_new_tokens=6 if i % 2 == 0 else 2,
                      temperature=0.8, seed=80 + i) for i in range(6)]
        ref = [r.tokens for r in _engine().run([Request(**s)
                                                for s in specs])]
        e0, e1 = _engine(), _engine()
        router = EngineRouter([e0, e1])
        for s in specs:         # e0: {0,2,4}, e1: {1,3,5}
            router.submit(Request(**s))
        router.step()
        router.drain(e0)        # 2 in-flight + 1 queued on e0
        out = router.run()
        assert router.stats["rebalanced"] >= 1
        assert e0.stats["requests_done"] == 2   # queued one migrated
        assert [r.tokens for r in sorted(out, key=lambda r: r.id)] \
            == ref


class TestCompileContract:
    def test_pool_compiles_buckets_plus_one_total(self):
        """Fleet-wide zero-recompile contract: a 2-engine pool over
        one (fresh) model compiles #buckets prefills + 1 decode IN
        TOTAL — the second engine (and a mid-run add_engine) report
        zero new traces, because executables are shared."""
        fresh = build_lm(vocab_size=50, dim=16, num_heads=2,
                         num_layers=1, max_len=32)
        fresh.build(jax.random.PRNGKey(1))

        def eng():
            return InferenceEngine(fresh, slots=2,
                                   prefill_buckets=(8, 16))
        e0, e1 = eng(), eng()
        router = EngineRouter([e0, e1], engine_factory=eng)
        import numpy as np

        from bigdl_tpu.serving.engine import _TRACES

        traces0 = dict(_TRACES)         # pool-wide, not per-engine:
        # each engine's stats delta counts the SHARED executables'
        # traces since ITS construction, so summing them double-counts
        rng = np.random.RandomState(0)
        reqs = [Request(prompt=list(rng.randint(1, 50, n)),
                        max_new_tokens=3, seed=i)
                for i, n in enumerate((3, 10, 6, 12, 5, 9))]
        out = router.run(reqs)
        assert all(r.status == "done" for r in out)
        assert _TRACES["prefill"] - traces0["prefill"] == 2
        assert _TRACES["decode"] - traces0["decode"] == 1
        # scale-up compiles nothing
        e2 = router.add_engine()
        out2 = router.run([Request(prompt=[1, 2, 3], max_new_tokens=2,
                                   seed=99)])
        assert out2[0].status == "done"
        assert e2.stats["prefill_traces"] == 0
        assert e2.stats["decode_traces"] == 0


class TestLifecycleStamps:
    def test_ttft_and_latency_deterministic_under_injected_clock(self):
        clk = {"t": 0.0}

        def eng():
            return _engine(clock=lambda: clk["t"])
        router = EngineRouter([eng()], clock=lambda: clk["t"])
        rid = router.submit(Request(prompt=[1, 2, 3], max_new_tokens=3,
                                    seed=1))
        while any(not e.idle for e in router.engines):
            clk["t"] += 0.5
            router.step()
        res = router.completed[rid]
        assert res.ttft_s == 0.5            # first decode round
        assert res.latency_s == 1.5         # 3 tokens, 0.5 s/round
        h = router.health()
        assert h["request_p50_ms"] is not None
        assert h["pool_size"] == 1 and h["healthy"] == 1


class TestAutoscaler:
    def _run_burst(self, autoscale, lg):
        from bigdl_tpu import obs

        obs.reset_all()         # fresh registry per run (labels etc.)
        clk = {"t": 0.0}

        def factory():
            return _engine(clock=lambda: clk["t"])
        router = EngineRouter([factory()], engine_factory=factory,
                              clock=lambda: clk["t"])
        asc = Autoscaler(router, target_p99_s=10.0, max_engines=3,
                         evaluate_every_s=0.5, backlog_high=8.0) \
            if autoscale else None
        trace = lg.make_trace(12, seed=3, arrival="bursty",
                              burst_size=12,
                              prompt_len_choices=(3, 5, 8),
                              max_new_choices=(4,), priorities=(0,))
        report = lg.replay(router, trace, clock=clk, step_dt=0.5,
                           autoscaler=asc)
        decisions = [] if asc is None else list(asc.decisions)
        return report, decisions

    def test_decisions_and_report_deterministic(self):
        lg = _loadgen()
        rep1, dec1 = self._run_burst(True, lg)
        rep2, dec2 = self._run_burst(True, lg)
        assert dec1 == dec2                 # the satellite acceptance
        assert rep1 == rep2
        assert [d["action"] for d in dec1
                if d["action"] != "hold"][:1] == ["scale_up"]
        assert rep1["by_status"] == {"done": 12}

    @pytest.mark.slow
    def test_autoscaled_pool_beats_fixed_pool(self):
        """Tier-2: the held-vs-violated p99 acceptance runs tier-1 as
        the fleet_autoscale drill; this is the unit-level replica."""
        lg = _loadgen()
        fixed, _ = self._run_burst(False, lg)
        auto, dec = self._run_burst(True, lg)
        assert auto["latency_p99_s"] < fixed["latency_p99_s"]
        assert auto["pool"]["engines_final"] >= 2

    def test_knob_validation(self):
        router = EngineRouter([_engine()])
        with pytest.raises(ValueError, match="target_p99_s"):
            Autoscaler(router, target_p99_s=0.0)
        with pytest.raises(ValueError, match="min_engines"):
            Autoscaler(router, target_p99_s=1.0, min_engines=3,
                       max_engines=2)


class TestLoadgen:
    def test_trace_is_pure_function_of_args(self):
        lg = _loadgen()
        t1 = lg.make_trace(8, seed=5, sessions=2)
        t2 = lg.make_trace(8, seed=5, sessions=2)
        assert [(a.t, a.spec, a.session) for a in t1["arrivals"]] \
            == [(a.t, a.spec, a.session) for a in t2["arrivals"]]
        assert t1["sessions"]["continuations"] \
            == t2["sessions"]["continuations"]
        t3 = lg.make_trace(8, seed=6, sessions=2)
        assert [a.spec for a in t1["arrivals"]] \
            != [a.spec for a in t3["arrivals"]]

    @pytest.mark.slow
    def test_multi_turn_sessions_resubmit_history(self):
        """Tier-2 (tier-1 budget): session mechanics are deterministic
        plumbing over the tier-1-covered replay loop."""
        lg = _loadgen()
        clk = {"t": 0.0}

        def eng():
            return InferenceEngine(_lm(), slots=2,
                                   prefill_buckets=(8, 16, 32),
                                   clock=lambda: clk["t"])
        router = EngineRouter([eng()], clock=lambda: clk["t"])
        trace = lg.make_trace(2, seed=1, sessions=1, session_turns=3,
                              prompt_len_choices=(3,),
                              max_new_choices=(2,))
        report = lg.replay(router, trace, clock=clk, step_dt=0.5)
        # 2 single-shot + 3 session turns
        assert report["requests"] == 5
        assert report["by_status"] == {"done": 5}
        assert report["goodput_tokens"] == 10
