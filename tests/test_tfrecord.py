"""TFRecord interop — framing + tf.train.Example codec, oracled against
tensorflow (test-only oracle; core never imports TF)."""

import numpy as np
import pytest

from bigdl_tpu.dataset.tfrecord import (
    TFRecordDataSet, decode_example, encode_example, read_tfrecords,
    write_image_examples, write_tfrecords,
)


def test_frame_roundtrip_and_crc(tmp_path):
    p = tmp_path / "x.tfrecord"
    payloads = [b"hello", b"", b"\x00\xff" * 100]
    write_tfrecords(str(p), payloads)
    assert list(read_tfrecords(str(p))) == payloads
    # corrupt one data byte → CRC failure
    raw = bytearray(p.read_bytes())
    raw[12 + 2] ^= 0xFF  # inside "hello"
    p.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="CRC"):
        list(read_tfrecords(str(p)))


def test_example_codec_roundtrip():
    ex = {
        "image": b"\x01\x02\x03",
        "shape": np.asarray([1, 3, 1], np.int64),
        "label": np.asarray([7], np.int64),
        "weights": np.asarray([0.5, -2.0], np.float32),
        "neg": np.asarray([-5], np.int64),
    }
    out = decode_example(encode_example(ex))
    assert out["image"] == b"\x01\x02\x03"
    np.testing.assert_array_equal(out["shape"], [1, 3, 1])
    np.testing.assert_array_equal(out["label"], [7])
    np.testing.assert_allclose(out["weights"], [0.5, -2.0])
    np.testing.assert_array_equal(out["neg"], [-5])


def test_example_matches_tensorflow_oracle(tmp_path):
    tf = pytest.importorskip("tensorflow")

    # ours → TF parses it
    ours = encode_example({"image": b"abc",
                           "label": np.asarray([3], np.int64),
                           "w": np.asarray([1.5], np.float32)})
    ex = tf.train.Example.FromString(ours)
    assert ex.features.feature["image"].bytes_list.value[0] == b"abc"
    assert ex.features.feature["label"].int64_list.value[0] == 3
    assert abs(ex.features.feature["w"].float_list.value[0] - 1.5) < 1e-6

    # TF → we parse it
    theirs = tf.train.Example(features=tf.train.Features(feature={
        "image": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[b"xyz"])),
        "label": tf.train.Feature(
            int64_list=tf.train.Int64List(value=[9, -1])),
        "w": tf.train.Feature(
            float_list=tf.train.FloatList(value=[0.25])),
    })).SerializeToString()
    out = decode_example(theirs)
    assert out["image"] == b"xyz"
    np.testing.assert_array_equal(out["label"], [9, -1])
    np.testing.assert_allclose(out["w"], [0.25])

    # and the FRAMING matches TF's TFRecord reader
    p = tmp_path / "t.tfrecord"
    write_tfrecords(str(p), [ours, theirs])
    got = [r.numpy() for r in tf.data.TFRecordDataset(str(p))]
    assert got == [ours, theirs]


def test_tfrecord_dataset_trains(tmp_path):
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.RandomState(0)
    n = 128
    images = np.zeros((n, 8, 8, 1), np.uint8)
    labels = (np.arange(n) % 2).astype(np.int64)
    for i in range(n):
        if labels[i]:
            images[i, 2:6, 2:6, 0] = 200
        images[i] += rng.randint(0, 20, (8, 8, 1)).astype(np.uint8)
    for s in range(2):
        write_image_examples(str(tmp_path / f"s{s}.tfrecord"),
                             images[s::2], labels[s::2])

    ds = TFRecordDataSet(str(tmp_path))
    assert ds.size() == n
    model = nn.Sequential(nn.Reshape([64]), nn.Linear(64, 2),
                          nn.LogSoftMax())
    trained = (Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
               .set_optim_method(SGD(learningrate=0.01))
               .set_end_when(Trigger.max_iteration(30))
               .optimize())
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    res = Evaluator(trained).test(ds, [Top1Accuracy()], batch_size=32)
    assert res["Top1Accuracy"].result()[0] > 0.9


def test_train_replay_stateless(tmp_path):
    rng = np.random.RandomState(1)
    write_image_examples(str(tmp_path / "a.tfrecord"),
                         rng.randint(0, 255, (12, 4, 4, 1), np.uint8),
                         np.arange(12))
    ds = TFRecordDataSet(str(tmp_path), seed=5)
    it1 = ds.data(train=True)
    run1 = [int(next(it1).label) for _ in range(20)]
    it2 = ds.data(train=True)
    run2 = [int(next(it2).label) for _ in range(20)]
    assert run1 == run2

def test_count_tfrecords_seek_and_sidecar(tmp_path):
    from bigdl_tpu.dataset.tfrecord import count_tfrecords

    images = np.zeros((10, 4, 4, 1), np.uint8)
    p = str(tmp_path / "c.tfrecord")
    write_image_examples(p, images, list(range(10)))
    assert count_tfrecords(p) == 10          # framing-seek path
    (tmp_path / "c.tfrecord.count").write_text("10\n")
    assert count_tfrecords(p) == 10          # sidecar path
    ds = TFRecordDataSet(str(tmp_path))
    assert ds.size() == 10
