"""Data plane tests (reference: dataset/DataSetSpec, transformer specs)."""

import pytest
import numpy as np

from bigdl_tpu.dataset import (
    DataSet, MiniBatch, Sample, SampleToMiniBatch, chain,
)
from bigdl_tpu.dataset.image import (
    BGRImgNormalizer, CenterCrop, GreyImgNormalizer, HFlip, RandomCrop,
    RandomResizedCrop, ColorJitter, Lighting,
)
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.dataset.text import (
    Dictionary, LabeledSentenceToSample, SentenceBiPadding, SentenceTokenizer,
    TextToLabeledSentence,
)


class TestSampleMiniBatch:
    def test_stack(self):
        samples = [Sample(np.ones((4, 4, 1)) * i, np.int32(i)) for i in range(3)]
        mb = MiniBatch.from_samples(samples)
        assert mb.input.shape == (3, 4, 4, 1)
        assert mb.target.shape == (3,)
        assert mb.target[2] == 2

    def test_pad_to(self):
        samples = [Sample(np.zeros(2), np.int32(0))] * 3
        mb = MiniBatch.from_samples(samples, pad_to=8)
        assert mb.input.shape == (8, 2)
        assert mb.real_size == 3

    def test_slice(self):
        mb = MiniBatch(np.arange(10)[:, None], np.arange(10))
        s = mb.slice(4, 3)
        np.testing.assert_array_equal(s.input[:, 0], [4, 5, 6])


class TestDataSet:
    def test_eval_iterates_once(self):
        ds = DataSet.array(list(range(5)))
        assert list(ds.data(train=False)) == [0, 1, 2, 3, 4]

    def test_train_loops_and_shuffles(self):
        ds = DataSet.array(list(range(10)), seed=3)
        it = ds.data(train=True)
        first_epoch = [next(it) for _ in range(10)]
        second_epoch = [next(it) for _ in range(10)]
        assert sorted(first_epoch) == list(range(10))
        assert sorted(second_epoch) == list(range(10))
        assert first_epoch != list(range(10)) or second_epoch != first_epoch

    def test_train_replay_is_stateless(self):
        # checkpoint-resume fast-forward depends on data(train=True)
        # replaying the identical schedule on every call, even after a
        # previous iterator consumed epochs (ADVICE r3: in-process retry
        # desynchronized the skip=neval realignment)
        ds = DataSet.array(list(range(10)), seed=3)
        it = ds.data(train=True)
        run1 = [next(it) for _ in range(25)]  # advances 2.5 epochs
        it2 = ds.data(train=True)
        run2 = [next(it2) for _ in range(25)]
        assert run1 == run2

    def test_sharded_train_replay_is_stateless(self):
        ds = DataSet.sharded(list(range(8)), process_id=0, process_count=2,
                             seed=7)
        it = ds.data(train=True)
        run1 = [next(it) for _ in range(10)]  # crosses an epoch boundary
        it2 = ds.data(train=True)
        run2 = [next(it2) for _ in range(10)]
        assert run1 == run2

    def test_sharded_partition(self):
        ds0 = DataSet.sharded(list(range(10)), process_id=0, process_count=2)
        ds1 = DataSet.sharded(list(range(10)), process_id=1, process_count=2)
        e0 = list(ds0.data(train=False))
        e1 = list(ds1.data(train=False))
        assert sorted(e0 + e1) == list(range(10))
        assert not set(e0) & set(e1)

    def test_sharded_train_covers_all_in_lockstep(self):
        shards = [DataSet.sharded(list(range(8)), process_id=p, process_count=2,
                                  seed=7) for p in range(2)]
        its = [s.data(train=True) for s in shards]
        epoch = [next(it) for it in its for _ in range(4)]
        assert sorted(epoch) == list(range(8))

    def test_transform_chain(self):
        samples = [Sample(np.full((4, 4, 1), 10.0), np.int32(1))] * 4
        ds = DataSet.array(samples) >> GreyImgNormalizer(10.0, 2.0) \
            >> SampleToMiniBatch(2)
        batches = list(ds.data(train=False))
        assert len(batches) == 2
        np.testing.assert_allclose(batches[0].input, 0.0)


class TestBatcher:
    def test_drop_partial(self):
        samples = [Sample(np.zeros(1), np.int32(0))] * 5
        t = SampleToMiniBatch(2, partial="drop")
        assert len(list(t(iter(samples)))) == 2

    def test_pad_partial(self):
        samples = [Sample(np.zeros(1), np.int32(0))] * 5
        batches = list(SampleToMiniBatch(2)(iter(samples)))
        assert len(batches) == 3
        assert batches[-1].real_size == 1
        assert batches[-1].size == 2


class TestImageTransforms:
    def _img_samples(self, n=4, h=8, w=8, c=3):
        rng = np.random.RandomState(0)
        return [Sample(rng.rand(h, w, c).astype(np.float32), np.int32(0))
                for _ in range(n)]

    def test_bgr_normalizer(self):
        out = list(BGRImgNormalizer([0.5] * 3, [0.25] * 3)(self._img_samples(1)))
        assert out[0].feature.shape == (8, 8, 3)

    def test_center_crop(self):
        out = list(CenterCrop(4, 4)(self._img_samples(1)))
        assert out[0].feature.shape == (4, 4, 3)

    def test_random_crop_with_padding(self):
        out = list(RandomCrop(8, 8, padding=2)(self._img_samples(1)))
        assert out[0].feature.shape == (8, 8, 3)

    def test_random_resized_crop(self):
        out = list(RandomResizedCrop(5)(self._img_samples(2)))
        assert all(s.feature.shape == (5, 5, 3) for s in out)

    def test_hflip_deterministic_seed(self):
        a = list(HFlip(0.5, seed=1)(self._img_samples(4)))
        b = list(HFlip(0.5, seed=1)(self._img_samples(4)))
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x.feature, y.feature)

    def test_colorjitter_lighting_run(self):
        out = list(chain(ColorJitter(), Lighting())(self._img_samples(2)))
        assert out[0].feature.shape == (8, 8, 3)


class TestTextPipeline:
    def test_tokenize_and_dictionary(self):
        sents = list(SentenceTokenizer()(["Hello world", "hello there"]))
        d = Dictionary(sents)
        assert d.index("hello") != d.index("world")
        assert d.index("zzz") == d.unk_index

    def test_lm_pipeline(self):
        texts = ["the cat sat", "the dog ran"]
        tok = SentenceTokenizer()
        sents = list(chain(tok, SentenceBiPadding())(texts))
        d = Dictionary(sents)
        pipeline = chain(tok, SentenceBiPadding(), TextToLabeledSentence(d),
                         LabeledSentenceToSample(fixed_length=6))
        samples = list(pipeline(texts))
        assert len(samples) == 2
        assert samples[0].feature.shape == (6,)
        assert samples[0].label.shape == (6,)
        # next-word property: label[t] == feature[t+1] inside the sentence
        assert samples[0].label[0] == samples[0].feature[1]

    def test_synthetic_mnist_shapes(self):
        s = synthetic_mnist(8)
        assert s[0].feature.shape == (28, 28, 1)
        assert 0 <= int(s[0].label) < 10


class TestPaddedBatching:
    """Variable-length stacking (reference: dataset/PaddingParam.scala)."""

    def test_pad_to_batch_max(self):
        samples = [Sample(np.arange(3, dtype=np.int32), 0),
                   Sample(np.arange(5, dtype=np.int32), 1)]
        mb = MiniBatch.from_samples(samples, feature_padding=0)
        assert mb.input.shape == (2, 5)
        np.testing.assert_array_equal(mb.input[0], [0, 1, 2, 0, 0])

    def test_fixed_padding_length(self):
        samples = [Sample(np.ones(2, np.float32), np.ones(2, np.int32)),
                   Sample(np.ones(4, np.float32), np.ones(4, np.int32))]
        mb = MiniBatch.from_samples(samples, feature_padding=-1.0,
                                    label_padding=0,
                                    padding_length=6)
        assert mb.input.shape == (2, 6)
        assert mb.target.shape == (2, 6)
        assert mb.input[0, 5] == -1.0
        assert mb.target[1, 5] == 0

    def test_too_long_raises(self):
        samples = [Sample(np.ones(9, np.float32), 0)]
        with pytest.raises(ValueError, match="padding_length"):
            MiniBatch.from_samples(samples, feature_padding=0.0,
                                   padding_length=4)

    def test_through_transformer_chain(self):
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        samples = [Sample(np.arange(n, dtype=np.int32), n % 2)
                   for n in (2, 4, 3, 5)]
        batcher = SampleToMiniBatch(2, feature_padding=0,
                                    padding_length=5)
        batches = list(batcher.apply(iter(samples)))
        assert [b.input.shape for b in batches] == [(2, 5), (2, 5)]

    def test_padding_length_without_value_raises(self):
        samples = [Sample(np.ones(2, np.float32), 0)]
        with pytest.raises(ValueError, match="pad value"):
            MiniBatch.from_samples(samples, padding_length=4)
