"""Layer unit tests — numpy/torch-oracle style.

Mirrors the reference's per-layer `XxxSpec.scala` strategy (SURVEY.md §4):
fixed-seed forward checks against hand-computed or torch (CPU) oracle
values, plus shape/edge cases. torch plays the role the reference gave
Torch7 (`torch/TH.scala` golden tests).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

KEY = jax.random.PRNGKey(0)


def eager(mod, x, training=False, rng=None):
    mod.build(KEY)
    if training:
        mod.training()
    else:
        mod.evaluate()
    return np.asarray(mod.forward(x, rng=rng))


class TestLinear:
    def test_forward_matches_manual(self):
        m = nn.Linear(3, 2).build(KEY)
        w = m.variables["params"]["weight"]
        b = m.variables["params"]["bias"]
        x = jnp.asarray([[1.0, 2.0, 3.0]])
        out = m.forward(x)
        np.testing.assert_allclose(out, x @ w + b, rtol=1e-6)

    def test_no_bias(self):
        m = nn.Linear(3, 2, with_bias=False).build(KEY)
        assert "bias" not in m.variables["params"]

    def test_xavier_bounds(self):
        m = nn.Linear(100, 100).build(KEY)
        w = m.variables["params"]["weight"]
        bound = np.sqrt(6.0 / 200)
        assert np.abs(w).max() <= bound + 1e-6

    def test_grad_flows(self):
        m = nn.Linear(4, 2)
        variables = m.init(KEY)

        def loss(params):
            out, _ = m.apply({"params": params, "state": {}}, jnp.ones((5, 4)))
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(variables["params"])
        assert g["weight"].shape == (4, 2)
        assert np.abs(np.asarray(g["weight"])).sum() > 0


class TestConv:
    def test_shape_basic(self):
        m = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)
        x = jnp.ones((2, 16, 16, 3))
        assert eager(m, x).shape == (2, 16, 16, 8)

    def test_stride_pad(self):
        m = nn.SpatialConvolution(1, 4, 5, 5, 2, 2, 0, 0)
        x = jnp.ones((1, 28, 28, 1))
        assert eager(m, x).shape == (1, 12, 12, 4)

    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        m = nn.SpatialConvolution(2, 3, 3, 3, 1, 1, 1, 1).build(KEY)
        w = np.asarray(m.variables["params"]["weight"])  # HWIO
        b = np.asarray(m.variables["params"]["bias"])
        x = np.random.RandomState(0).randn(2, 5, 5, 2).astype(np.float32)
        ours = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        tw = torch.tensor(w.transpose(3, 2, 0, 1))  # HWIO->OIHW
        tx = torch.tensor(x.transpose(0, 3, 1, 2))  # NHWC->NCHW
        ref = torch.nn.functional.conv2d(tx, tw, torch.tensor(b), padding=1)
        np.testing.assert_allclose(
            ours, ref.numpy().transpose(0, 2, 3, 1), rtol=1e-4, atol=1e-5)

    def test_grouped(self):
        m = nn.SpatialConvolution(4, 8, 3, 3, 1, 1, 1, 1, n_group=2)
        x = jnp.ones((1, 8, 8, 4))
        assert eager(m, x).shape == (1, 8, 8, 8)

    def test_dilated(self):
        m = nn.SpatialDilatedConvolution(1, 1, 3, 3, 1, 1, 2, 2, dilation_w=2)
        x = jnp.ones((1, 9, 9, 1))
        assert eager(m, x).shape == (1, 9, 9, 1)

    def test_transposed_upsamples(self):
        m = nn.SpatialFullConvolution(2, 3, 4, 4, 2, 2, 1, 1)
        x = jnp.ones((1, 8, 8, 2))
        # out = (in-1)*stride - 2*pad + kernel = 7*2 - 2 + 4 = 16
        assert eager(m, x).shape == (1, 16, 16, 3)

    def test_transposed_matches_torch(self):
        import torch

        rng = np.random.RandomState(3)
        m = nn.SpatialFullConvolution(2, 3, 4, 4, 2, 2, 1, 1)
        v = m.init(jax.random.PRNGKey(0))
        x = rng.randn(2, 5, 5, 2).astype(np.float32)
        out, _ = m.apply(v, jnp.asarray(x))
        # our (kH,kW,O,I) ↔ torch (I,O,kH,kW)
        w_t = np.asarray(v["params"]["weight"]).transpose(3, 2, 0, 1)
        want = torch.nn.functional.conv_transpose2d(
            torch.from_numpy(x.transpose(0, 3, 1, 2)),
            torch.from_numpy(w_t),
            torch.from_numpy(np.asarray(v["params"]["bias"])),
            stride=2, padding=1)
        np.testing.assert_allclose(
            np.asarray(out), want.numpy().transpose(0, 2, 3, 1),
            rtol=1e-4, atol=1e-4)

    def test_transposed_grouped_dilated_matches_torch(self):
        import torch

        rng = np.random.RandomState(4)
        m = nn.SpatialFullConvolution(4, 6, 3, 3, 2, 2, 1, 1,
                                      n_group=2, dilation_w=2)
        v = m.init(jax.random.PRNGKey(0))
        x = rng.randn(2, 5, 5, 4).astype(np.float32)
        out, _ = m.apply(v, jnp.asarray(x))
        # ours (kH,kW,O_total,I/g); torch wants (I_total, O/g, kH, kW):
        # stack the per-group O-blocks along the input axis
        w = np.asarray(v["params"]["weight"])       # (3,3,6,2)
        w_t = np.concatenate([w[:, :, g * 3:(g + 1) * 3, :]
                              .transpose(3, 2, 0, 1)
                              for g in range(2)], axis=0)  # (4,3,3,3)
        want = torch.nn.functional.conv_transpose2d(
            torch.from_numpy(x.transpose(0, 3, 1, 2)),
            torch.from_numpy(w_t),
            torch.from_numpy(np.asarray(v["params"]["bias"])),
            stride=2, padding=1, groups=2, dilation=2)
        assert out.shape == want.numpy().transpose(0, 2, 3, 1).shape
        np.testing.assert_allclose(
            np.asarray(out), want.numpy().transpose(0, 2, 3, 1),
            rtol=1e-4, atol=1e-4)


class TestPooling:
    def test_max_pool(self):
        m = nn.SpatialMaxPooling(2, 2, 2, 2)
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        out = eager(m, x)
        np.testing.assert_allclose(out[0, :, :, 0], [[5, 7], [13, 15]])

    def test_avg_pool(self):
        m = nn.SpatialAveragePooling(2, 2, 2, 2)
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        out = eager(m, x)
        np.testing.assert_allclose(out[0, :, :, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_ceil_mode(self):
        # 6x6, k=3, s=2: floor -> (6-3)//2+1 = 2; ceil -> ceil(1.5)+1 = 3
        x = jnp.ones((1, 6, 6, 1))
        m = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        assert eager(m, x).shape == (1, 3, 3, 1)
        m2 = nn.SpatialMaxPooling(3, 3, 2, 2)
        assert eager(m2, x).shape == (1, 2, 2, 1)


class TestBatchNorm:
    def test_train_normalizes(self):
        m = nn.BatchNormalization(4).build(KEY).training()
        x = jax.random.normal(KEY, (100, 4)) * 5 + 3
        out = m.forward(x)
        np.testing.assert_allclose(np.asarray(out).mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(np.asarray(out).std(0), 1.0, atol=1e-2)

    def test_running_stats_update(self):
        m = nn.BatchNormalization(2, momentum=0.5).build(KEY).training()
        x = jnp.ones((10, 2)) * 4
        m.forward(x)
        np.testing.assert_allclose(
            m.variables["state"]["running_mean"], [2.0, 2.0], atol=1e-6)

    def test_eval_uses_running_stats(self):
        m = nn.BatchNormalization(2, affine=False).build(KEY).evaluate()
        x = jnp.asarray([[1.0, 2.0]])
        out = m.forward(x)  # running mean 0, var 1
        np.testing.assert_allclose(out, x, atol=1e-4)

    def test_spatial_bn_shape(self):
        m = nn.SpatialBatchNormalization(3)
        x = jnp.ones((2, 4, 4, 3))
        assert eager(m, x, training=True).shape == (2, 4, 4, 3)


class TestActivations:
    def test_relu(self):
        out = eager(nn.ReLU(), jnp.asarray([-1.0, 2.0]))
        np.testing.assert_allclose(out, [0.0, 2.0])

    def test_logsoftmax_sums_to_one(self):
        out = eager(nn.LogSoftMax(), jnp.asarray([[1.0, 2.0, 3.0]]))
        np.testing.assert_allclose(np.exp(out).sum(), 1.0, rtol=1e-6)

    def test_prelu_learnable(self):
        m = nn.PReLU().build(KEY)
        out = m.forward(jnp.asarray([-4.0, 4.0]))
        np.testing.assert_allclose(out, [-1.0, 4.0])

    def test_hardtanh(self):
        out = eager(nn.HardTanh(-2, 2), jnp.asarray([-5.0, 0.5, 5.0]))
        np.testing.assert_allclose(out, [-2.0, 0.5, 2.0])

    def test_relu6(self):
        out = eager(nn.ReLU6(), jnp.asarray([-1.0, 3.0, 9.0]))
        np.testing.assert_allclose(out, [0.0, 3.0, 6.0])


class TestDropout:
    def test_eval_is_identity(self):
        m = nn.Dropout(0.5)
        x = jnp.ones((10, 10))
        np.testing.assert_allclose(eager(m, x), x)

    def test_train_masks_and_scales(self):
        m = nn.Dropout(0.5).build(KEY).training()
        x = jnp.ones((100, 100))
        out = np.asarray(m.forward(x, rng=jax.random.PRNGKey(1)))
        vals = np.unique(out)
        assert set(np.round(vals, 4)) <= {0.0, 2.0}
        assert abs((out == 0).mean() - 0.5) < 0.05

    def test_train_without_rng_raises(self):
        m = nn.Dropout(0.5).build(KEY).training()
        with pytest.raises(ValueError):
            m.forward(jnp.ones((2, 2)))


class TestShapeOps:
    def test_reshape(self):
        out = eager(nn.Reshape([4]), jnp.ones((2, 2, 2)))
        assert out.shape == (2, 4)

    def test_view_wildcard(self):
        out = eager(nn.View(-1), jnp.ones((3, 2, 5)))
        assert out.shape == (3, 10)

    def test_select(self):
        x = jnp.arange(12.0).reshape(3, 4)
        out = eager(nn.Select(1, 2), x)  # second row (1-based)
        np.testing.assert_allclose(out, [4, 5, 6, 7])

    def test_transpose(self):
        out = eager(nn.Transpose([(1, 2)]), jnp.ones((3, 4)))
        assert out.shape == (4, 3)

    def test_narrow(self):
        x = jnp.arange(10.0)[None, :].repeat(2, 0)
        out = eager(nn.Narrow(2, 3, 4), x)
        assert out.shape == (2, 4)
        np.testing.assert_allclose(out[0], [2, 3, 4, 5])

    def test_zero_padding(self):
        out = eager(nn.SpatialZeroPadding(1), jnp.ones((1, 4, 4, 1)))
        assert out.shape == (1, 6, 6, 1)
        assert out[0, 0, 0, 0] == 0


class TestTableOps:
    def test_cadd_table(self):
        out = eager(nn.CAddTable(), (jnp.ones(3), jnp.ones(3) * 2))
        np.testing.assert_allclose(out, [3.0, 3.0, 3.0])

    def test_join_table(self):
        a, b = jnp.ones((2, 3)), jnp.zeros((2, 3))
        out = eager(nn.JoinTable(1, n_input_dims=1), (a, b))
        assert out.shape == (2, 6)

    def test_split_select(self):
        x = jnp.arange(6.0).reshape(2, 3)
        m = nn.SplitTable(2).build(KEY).evaluate()  # 1-based dim over full tensor
        table = m.forward(x)
        assert len(table) == 3
        np.testing.assert_allclose(table[1], [0.0, 3.0])
        out = eager(nn.SelectTable(2), table)
        np.testing.assert_allclose(out, [1.0, 4.0])

    def test_mm(self):
        a = jnp.ones((2, 3, 4))
        b = jnp.ones((2, 4, 5))
        out = eager(nn.MM(), (a, b))
        assert out.shape == (2, 3, 5)
        np.testing.assert_allclose(out[0, 0, 0], 4.0)


class TestLookupTable:
    def test_gather(self):
        m = nn.LookupTable(10, 4).build(KEY)
        out = m.forward(jnp.asarray([[0, 3], [9, 1]]))
        assert out.shape == (2, 2, 4)
        w = np.asarray(m.variables["params"]["weight"])
        np.testing.assert_allclose(out[0, 1], w[3], rtol=1e-6)

    def test_padding_value_zeros(self):
        m = nn.LookupTable(10, 4, padding_value=0).build(KEY)
        out = m.forward(jnp.asarray([0, 1]))
        np.testing.assert_allclose(np.asarray(out)[0], 0.0)


class TestLRN:
    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        m = nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0)
        x = np.random.RandomState(1).rand(2, 4, 4, 8).astype(np.float32)
        ours = eager(m, jnp.asarray(x))
        ref = torch.nn.functional.local_response_norm(
            torch.tensor(x.transpose(0, 3, 1, 2)), 5, alpha=1.0, beta=0.75, k=1.0)
        np.testing.assert_allclose(
            ours, ref.numpy().transpose(0, 2, 3, 1), rtol=1e-4, atol=1e-5)


class TestSpaceToDepth:
    def test_blocks_to_channels(self):
        m = nn.SpaceToDepth(2)
        x = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
        out = eager(m, x)
        assert out.shape == (2, 2, 2, 12)
        # first output pixel = the 2x2 block's channels, row-major
        np.testing.assert_allclose(
            np.asarray(out[0, 0, 0]),
            np.concatenate([np.asarray(x[0, 0, 0]), np.asarray(x[0, 0, 1]),
                            np.asarray(x[0, 1, 0]), np.asarray(x[0, 1, 1])]))

    def test_indivisible_raises(self):
        m = nn.SpaceToDepth(2)
        with pytest.raises(ValueError, match="not divisible"):
            eager(m, jnp.zeros((1, 5, 4, 3)))

    def test_asymmetric_conv_padding(self):
        # (low, high) padding tuples: 4x4/s1 with pad (2,1) preserves
        # the spatial size (the s2d stem geometry)
        m = nn.SpatialConvolution(3, 4, 4, 4, 1, 1, (2, 1), (2, 1))
        x = jnp.zeros((1, 8, 8, 3))
        assert eager(m, x).shape == (1, 8, 8, 4)

    def test_s2d_resnet_stem_shapes(self):
        import jax

        from bigdl_tpu.models import resnet

        model = resnet.build_imagenet(50, 10, stem="s2d")
        v = model.init(jax.random.PRNGKey(0))
        out, _ = model.apply(v, jnp.zeros((1, 224, 224, 3)),
                             training=False)
        assert out.shape == (1, 10)
