"""NCF + TextClassifier model tests, and the temporal conv/pool layers
they ride on (reference: NeuralCF / example/textclassification;
nn/TemporalConvolution.scala, nn/TemporalMaxPooling.scala)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from bigdl_tpu import nn
from bigdl_tpu.models import ncf, textclassifier
from bigdl_tpu.optim import SGD

KEY = jax.random.PRNGKey(0)


class TestTemporalConvolution:
    def test_vs_torch_oracle(self):
        tc = nn.TemporalConvolution(6, 4, 3, 2)
        v = tc.init(KEY)
        x = np.random.RandomState(0).randn(2, 11, 6).astype(np.float32)
        y, _ = tc.apply(v, jnp.asarray(x))
        assert y.shape == (2, 5, 4)
        conv = torch.nn.Conv1d(6, 4, 3, stride=2, bias=True)
        w = np.asarray(v["params"]["weight"])  # (KW, I, O)
        conv.weight.data = torch.tensor(w.transpose(2, 1, 0))
        conv.bias.data = torch.tensor(np.asarray(v["params"]["bias"]))
        ref = conv(torch.tensor(x.transpose(0, 2, 1)))
        ref = ref.detach().numpy().transpose(0, 2, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4)

    def test_grads_flow(self):
        tc = nn.TemporalConvolution(3, 2, 2)
        v = tc.init(KEY)
        x = jnp.ones((1, 5, 3))

        def loss(p):
            y, _ = tc.apply({"params": p, "state": {}}, x)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(v["params"])
        assert float(jnp.abs(g["weight"]).sum()) > 0


class TestTemporalMaxPooling:
    def test_windows(self):
        pool = nn.TemporalMaxPooling(2, 2)
        x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 6, 2))
        y, _ = pool.apply({"params": {}, "state": {}}, x)
        assert y.shape == (1, 3, 2)
        np.testing.assert_allclose(
            np.asarray(y[0, :, 0]), [2.0, 6.0, 10.0])

    def test_global(self):
        pool = nn.TemporalMaxPooling(-1)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 7, 3),
                        jnp.float32)
        y, _ = pool.apply({"params": {}, "state": {}}, x)
        assert y.shape == (2, 1, 3)
        np.testing.assert_allclose(
            np.asarray(y[:, 0]), np.asarray(x).max(1), rtol=1e-6)


class TestNCF:
    def test_shapes_and_logprobs(self):
        m = ncf.build(30, 40, class_num=5).build(KEY).evaluate()
        pairs = jnp.asarray(
            np.random.RandomState(0).randint(0, 30, (8, 2)), jnp.int32)
        out = m.forward(pairs)
        assert out.shape == (8, 5)
        np.testing.assert_allclose(
            np.exp(np.asarray(out)).sum(-1), 1.0, rtol=1e-5)

    def test_no_mf_tower(self):
        m = ncf.build(10, 10, class_num=3, include_mf=False).build(KEY)
        out = m.evaluate().forward(jnp.zeros((4, 2), jnp.int32))
        assert out.shape == (4, 3)

    def test_learns_synthetic_ratings(self):
        # tiny synthetic problem: rating = (u + i) % 3
        rng = np.random.RandomState(0)
        users = rng.randint(0, 8, 256)
        items = rng.randint(0, 8, 256)
        labels = (users + items) % 3
        pairs = jnp.asarray(np.stack([users, items], 1), jnp.int32)
        y = jnp.asarray(labels, jnp.int32)

        m = ncf.build(8, 8, class_num=3, user_embed=8, item_embed=8,
                      hidden_layers=(16, 8), mf_embed=8)
        variables = m.init(KEY)
        crit = nn.ClassNLLCriterion()
        method = SGD(learningrate=0.5)
        slots = method.init_slots(variables["params"])
        state = variables["state"]

        @jax.jit
        def step(params, slots, lr, t):
            def lf(p):
                out, _ = m.apply({"params": p, "state": state}, pairs)
                return crit(out, y)
            loss, g = jax.value_and_grad(lf)(params)
            params, slots = method.update(g, params, slots, lr, t)
            return params, slots, loss

        params = variables["params"]
        first = None
        for t in range(60):
            params, slots, loss = step(
                params, slots, jnp.asarray(0.5), jnp.asarray(t))
            if first is None:
                first = float(loss)
        assert float(loss) < 0.5 * first  # clearly learning


class TestTextClassifier:
    def test_forward_shape(self):
        m = textclassifier.build(class_num=4, vocab_size=50,
                                 sequence_len=160, embedding_dim=16,
                                 filters=8).build(KEY).evaluate()
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 50, (2, 160)), jnp.int32)
        out = m.forward(toks)
        assert out.shape == (2, 4)
        np.testing.assert_allclose(
            np.exp(np.asarray(out)).sum(-1), 1.0, rtol=1e-5)

    def test_set_embedding(self):
        m = textclassifier.build(class_num=2, vocab_size=20,
                                 sequence_len=160, embedding_dim=8,
                                 filters=4)
        v = m.init(KEY)
        vec = np.random.RandomState(1).rand(20, 8).astype(np.float32)
        v2 = textclassifier.set_embedding(v, vec)
        emb = next(p for k, p in v2["params"].items()
                   if k.endswith("_embedding"))
        np.testing.assert_allclose(np.asarray(emb["weight"]), vec)
