"""Module serialization round-trips.

Reference parity: utils/serializer tests (SerializerSpec /
ModuleSerializerSpec — a reflection-driven spec that round-trips every
layer type; SURVEY.md §4 'Serialization round-trip'). Each case builds a
module, saves architecture+weights, loads into a fresh object, and
requires bit-identical forward outputs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.serialization import load_module, save_module
from bigdl_tpu.nn.initialization import ConstInitMethod, RandomNormal, Xavier


def _roundtrip(tmp_path, module, *inputs, training=False):
    variables = module.init(jax.random.PRNGKey(3))
    out0, _ = module.apply(variables, *inputs, training=training)
    save_module(str(tmp_path), module, variables=variables)
    loaded, lvars = load_module(str(tmp_path))
    assert type(loaded) is type(module)
    out1, _ = loaded.apply(lvars, *inputs, training=training)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        out0, out1)
    return loaded


# ------------------------------------------------------------ layer catalog

x2 = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32)
img = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8, 3)),
                  jnp.float32)
seq = jnp.asarray(np.random.default_rng(2).normal(size=(2, 5, 6)),
                  jnp.float32)

CASES = [
    ("linear", lambda: nn.Linear(8, 3), (x2,)),
    ("linear-init", lambda: nn.Linear(8, 3, w_init=RandomNormal(0.0, 0.2),
                                      b_init=ConstInitMethod(0.5)), (x2,)),
    ("relu", lambda: nn.ReLU(), (x2,)),
    ("hardtanh", lambda: nn.HardTanh(-2.0, 2.0), (x2,)),
    ("prelu", lambda: nn.PReLU(8), (x2,)),
    ("dropout-eval", lambda: nn.Dropout(0.5), (x2,)),
    ("reshape", lambda: nn.Reshape([2, 4]), (x2,)),
    ("logsoftmax", lambda: nn.LogSoftMax(), (x2,)),
    ("conv", lambda: nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1,
                                           w_init=Xavier()), (img,)),
    ("maxpool-ceil", lambda: nn.SpatialMaxPooling(3, 3, 2, 2).ceil(), (img,)),
    ("avgpool", lambda: nn.SpatialAveragePooling(2, 2, 2, 2), (img,)),
    ("bn", lambda: nn.SpatialBatchNormalization(3), (img,)),
    ("lrn", lambda: nn.SpatialCrossMapLRN(5, 0.0001, 0.75), (img,)),
    ("embedding", lambda: nn.LookupTable(10, 6),
     (jnp.asarray([[1, 2], [3, 4]], jnp.int32),)),
    ("sequential", lambda: nn.Sequential(
        nn.Linear(8, 16).set_name("fc1"), nn.ReLU(), nn.Linear(16, 3)), (x2,)),
    ("concat", lambda: nn.Concat(2, nn.Linear(8, 3), nn.Linear(8, 5)), (x2,)),
    ("concattable", lambda: nn.ConcatTable(nn.Linear(8, 3), nn.ReLU()), (x2,)),
    ("bottle", lambda: nn.Bottle(nn.Linear(6, 4)), (seq,)),
    ("lstm", lambda: nn.Recurrent(nn.LSTM(6, 7)), (seq,)),
    ("gru", lambda: nn.Recurrent(nn.GRU(6, 7)), (seq,)),
    ("birecurrent", lambda: nn.BiRecurrent(nn.LSTM(6, 7)), (seq,)),
    ("timedistributed", lambda: nn.TimeDistributed(nn.Linear(6, 2)), (seq,)),
]


@pytest.mark.parametrize("name,build,inputs", CASES,
                         ids=[c[0] for c in CASES])
def test_layer_roundtrip(tmp_path, name, build, inputs):
    _roundtrip(tmp_path, build(), *inputs)


def test_sequential_post_hoc_add(tmp_path):
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU())
    m.add(nn.Linear(16, 3))  # mutator after construction must replay
    loaded = _roundtrip(tmp_path, m, x2)
    assert len(loaded) == 3


def test_graph_roundtrip(tmp_path):
    from bigdl_tpu.models import lenet

    g = lenet.graph(10)
    x = jnp.asarray(np.random.default_rng(5).normal(size=(2, 28, 28, 1)),
                    jnp.float32)
    _roundtrip(tmp_path, g, x)


def test_model_zoo_roundtrip(tmp_path):
    from bigdl_tpu.models import resnet

    m = resnet.build_cifar(20, 10)
    x = jnp.asarray(np.random.default_rng(6).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    _roundtrip(tmp_path, m, x)


def test_explicit_names_survive(tmp_path):
    m = nn.Sequential(nn.Linear(8, 4).set_name("enc"), nn.ReLU())
    variables = m.init(jax.random.PRNGKey(0))
    save_module(str(tmp_path), m, variables=variables)
    loaded, lvars = load_module(str(tmp_path))
    assert loaded[0].name == "enc"
    assert "0_enc" in lvars["params"]


def test_spec_rejects_foreign_classes(tmp_path):
    import json
    from bigdl_tpu.serialization import spec_to_module

    with pytest.raises(ValueError):
        spec_to_module({"class": "os:system", "args": ["true"], "kwargs": {}})
    with pytest.raises(ValueError):
        spec_to_module(json.loads(
            '{"class": "subprocess.run:x", "args": [], "kwargs": {}}'))


def test_criterion_in_spec(tmp_path):
    # criterions captured too (used by estimator configs)
    from bigdl_tpu.serialization import module_to_spec, spec_to_module
    from bigdl_tpu.utils.table import T

    crit = nn.ParallelCriterion()
    crit.add(nn.MSECriterion(), 0.5)
    spec = module_to_spec(crit)
    rebuilt = spec_to_module(spec)
    a = jnp.asarray([[1.0, 2.0]]), jnp.asarray([[1.0, 2.0]])
    inp, tgt = T(a[0]), T(a[1])
    np.testing.assert_allclose(float(rebuilt(inp, tgt)), float(crit(inp, tgt)))


def test_rename_after_add_keeps_saved_keys(tmp_path):
    # set_name AFTER the module was added: the container's pytree key was
    # computed pre-rename, and the saved key list must win on reload.
    inner = nn.Linear(8, 4)
    m = nn.Sequential(inner, nn.ReLU())
    inner.set_name("renamed")
    _roundtrip(tmp_path, m, x2)


def test_rename_after_wire_graph(tmp_path):
    from bigdl_tpu.nn.graph import Graph, Input

    inp = Input()
    fc = nn.Linear(8, 4)
    out = nn.ReLU()(fc(inp))
    g = Graph(inp, out)
    fc.set_name("late-rename")
    _roundtrip(tmp_path, g, x2)


def test_shared_module_graph_roundtrip(tmp_path):
    """nn.Graph dedupes shared module objects into one param entry
    (round-4 weight sharing); the ctor-capture serializer must
    preserve the sharing across save/load."""
    import numpy as np

    from bigdl_tpu.nn.graph import Graph, Input, Node

    a, b = Input(), Input()
    shared = nn.Linear(4, 3)
    out = Node(nn.CAddTable(), [shared(a), shared(b)])
    g = Graph([a, b], out).build(jax.random.PRNGKey(0))
    assert sum("Linear" in k for k in g.variables["params"]) == 1

    save_module(str(tmp_path / "m"), g, g.variables)
    m2, v2 = load_module(str(tmp_path / "m"))
    assert sorted(v2["params"]) == sorted(g.variables["params"])
    x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
    o1, _ = g.apply(g.variables, x, x)
    o2, _ = m2.apply(v2, x, x)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-6)
