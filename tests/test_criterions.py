"""Criterion tests vs torch oracle (reference: nn/*CriterionSpec.scala)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.table import T


class TestClassNLL:
    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        logits = np.random.RandomState(0).randn(5, 4).astype(np.float32)
        target = np.array([0, 1, 2, 3, 1])
        logp = jax.nn.log_softmax(jnp.asarray(logits), -1)
        ours = nn.ClassNLLCriterion()(logp, jnp.asarray(target))
        ref = torch.nn.functional.nll_loss(
            torch.log_softmax(torch.tensor(logits), -1), torch.tensor(target))
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)

    def test_weighted(self):
        w = jnp.asarray([1.0, 2.0])
        logp = jnp.log(jnp.asarray([[0.9, 0.1], [0.2, 0.8]]))
        tgt = jnp.asarray([0, 1])
        ours = float(nn.ClassNLLCriterion(weights=w)(logp, tgt))
        expect = -(1.0 * np.log(0.9) + 2.0 * np.log(0.8)) / 3.0
        np.testing.assert_allclose(ours, expect, rtol=1e-5)


class TestCrossEntropy:
    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        logits = np.random.RandomState(1).randn(6, 3).astype(np.float32)
        target = np.array([0, 1, 2, 0, 1, 2])
        ours = nn.CrossEntropyCriterion()(jnp.asarray(logits), jnp.asarray(target))
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(target))
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)

    def test_grad(self):
        g = jax.grad(lambda x: nn.CrossEntropyCriterion()(x, jnp.asarray([1])))(
            jnp.asarray([[1.0, 2.0, 3.0]]))
        p = jax.nn.softmax(jnp.asarray([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(g[0], p - jnp.asarray([0, 1.0, 0]), rtol=1e-5)


class TestRegression:
    def test_mse(self):
        ours = nn.MSECriterion()(jnp.asarray([1.0, 2.0]), jnp.asarray([0.0, 0.0]))
        np.testing.assert_allclose(float(ours), 2.5)

    def test_mse_sum(self):
        c = nn.MSECriterion(size_average=False)
        np.testing.assert_allclose(
            float(c(jnp.asarray([1.0, 2.0]), jnp.zeros(2))), 5.0)

    def test_abs(self):
        np.testing.assert_allclose(
            float(nn.AbsCriterion()(jnp.asarray([1.0, -3.0]), jnp.zeros(2))), 2.0)

    def test_smooth_l1_matches_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(2).randn(10).astype(np.float32) * 2
        ours = nn.SmoothL1Criterion()(jnp.asarray(x), jnp.zeros(10))
        ref = torch.nn.functional.smooth_l1_loss(
            torch.tensor(x), torch.zeros(10))
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)


class TestBCE:
    def test_matches_torch(self):
        torch = pytest.importorskip("torch")
        p = np.random.RandomState(3).rand(8).astype(np.float32)
        t = (np.random.RandomState(4).rand(8) > 0.5).astype(np.float32)
        ours = nn.BCECriterion()(jnp.asarray(p), jnp.asarray(t))
        ref = torch.nn.functional.binary_cross_entropy(
            torch.tensor(p), torch.tensor(t))
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-4)


class TestComposite:
    def test_parallel_criterion(self):
        pc = (nn.ParallelCriterion()
              .add(nn.MSECriterion(), 0.5)
              .add(nn.AbsCriterion(), 2.0))
        loss = pc(T(jnp.asarray([2.0]), jnp.asarray([1.0])),
                  T(jnp.asarray([0.0]), jnp.asarray([0.0])))
        np.testing.assert_allclose(float(loss), 0.5 * 4.0 + 2.0 * 1.0)

    def test_multi_criterion(self):
        mc = nn.MultiCriterion().add(nn.MSECriterion()).add(nn.AbsCriterion())
        loss = mc(jnp.asarray([2.0]), jnp.asarray([0.0]))
        np.testing.assert_allclose(float(loss), 4.0 + 2.0)

    def test_time_distributed(self):
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True)
        logp = jnp.log(jnp.full((2, 3, 4), 0.25))
        tgt = jnp.zeros((2, 3), jnp.int32)
        np.testing.assert_allclose(float(crit(logp, tgt)), -np.log(0.25), rtol=1e-6)

    def test_kld(self):
        loss = nn.KLDCriterion()(T(jnp.zeros((2, 3)), jnp.zeros((2, 3))), None)
        np.testing.assert_allclose(float(loss), 0.0, atol=1e-6)
