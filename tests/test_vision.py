"""Vision ImageFrame pipeline tests (reference: transform/vision/image/
specs — see SURVEY.md §2.4 Vision ImageFrame row)."""

import numpy as np
import pytest

from bigdl_tpu.dataset import vision
from bigdl_tpu.dataset.vision import (
    AspectScale, Brightness, CenterCrop, ChannelNormalize, Contrast, HFlip,
    ImageFeature, ImageFrame, ImageFrameToSample, MatToTensor, PixelNormalizer,
    RandomCrop, RandomTransformer, Resize, Saturation,
)


def _img(h=8, w=6, c=3, seed=0):
    return np.random.default_rng(seed).uniform(0, 255, (h, w, c)).astype(
        np.float32)


def test_resize_shapes_and_identity():
    img = _img(8, 6)
    out = Resize(4, 3).transform_image(img)
    assert out.shape == (4, 3, 3)
    same = Resize(8, 6).transform_image(img)
    np.testing.assert_allclose(same, img)


def test_resize_bilinear_constant_preserved():
    img = np.full((5, 7, 3), 42.0, np.float32)
    out = Resize(9, 4).transform_image(img)
    np.testing.assert_allclose(out, 42.0, rtol=1e-6)


def test_aspect_scale_short_side():
    img = _img(10, 20)
    out = AspectScale(5).transform_image(img)
    assert out.shape == (5, 10, 3)


def test_center_and_random_crop():
    img = _img(10, 10)
    assert CenterCrop(4, 6).transform_image(img).shape == (4, 6, 3)
    out = RandomCrop(4, 6, seed=0).transform_image(img)
    assert out.shape == (4, 6, 3)


def test_hflip():
    img = _img()
    np.testing.assert_allclose(HFlip().transform_image(img), img[:, ::-1])


def test_photometric_ranges():
    img = _img()
    out = Brightness(5.0, 5.0, seed=0).transform_image(img)
    np.testing.assert_allclose(out, img + 5.0, rtol=1e-6)
    out = Contrast(2.0, 2.0, seed=0).transform_image(img)
    np.testing.assert_allclose(out, img * 2.0, rtol=1e-6)
    # saturation with alpha=1 is identity
    out = Saturation(1.0, 1.0, seed=0).transform_image(img)
    np.testing.assert_allclose(out, img, rtol=1e-5)


def test_channel_normalize_and_pixel_normalizer():
    img = _img()
    mean, std = [1.0, 2.0, 3.0], [2.0, 2.0, 2.0]
    out = ChannelNormalize(mean, std).transform_image(img)
    np.testing.assert_allclose(out, (img - np.array(mean)) / 2.0, rtol=1e-6)
    out = PixelNormalizer(img).transform_image(img)
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_mat_to_tensor_chw():
    img = _img(4, 5, 3)
    assert MatToTensor(to_chw=True).transform_image(img).shape == (3, 4, 5)


def test_random_transformer_prob_extremes():
    img = _img()
    f = ImageFeature(img.copy())
    never = RandomTransformer(HFlip(), 0.0, seed=0).transform_feature(f)
    np.testing.assert_allclose(never.image, img)
    always = RandomTransformer(HFlip(), 1.0, seed=0).transform_feature(
        ImageFeature(img.copy()))
    np.testing.assert_allclose(always.image, img[:, ::-1])


def test_frame_transform_chain_and_to_sample():
    imgs = np.stack([_img(10, 10, 3, seed=i) for i in range(4)])
    labels = np.arange(4)
    frame = ImageFrame.from_arrays(imgs, labels)
    chain = Resize(8, 8) >> CenterCrop(6, 6) >> \
        ChannelNormalize([0.0] * 3, [255.0] * 3) >> MatToTensor()
    out = frame.transform(chain)
    assert len(out) == 4
    samples = out.to_samples()
    assert samples[0].feature.shape == (6, 6, 3)
    assert int(samples[2].label) == 2


def test_error_isolation_marks_invalid():
    class Boom(vision.FeatureTransformer):
        def transform_image(self, img):
            raise RuntimeError("boom")

    frame = ImageFrame.from_arrays(np.zeros((2, 4, 4, 3), np.float32),
                                   np.arange(2))
    out = frame.transform(Boom())
    assert all(not f.is_valid for f in out)
    assert out.to_samples() == []
    # terminal stage drops invalid
    assert list(ImageFrameToSample()(iter(out.features))) == []


def test_image_frame_read_roundtrip(tmp_path):
    img = _img(5, 5)
    np.save(tmp_path / "a.npy", img)
    (tmp_path / "a.label").write_text("7")
    frame = ImageFrame.read(str(tmp_path), with_label=True)
    assert len(frame) == 1
    np.testing.assert_allclose(frame.features[0].image, img)
    assert frame.features[0][ImageFeature.LABEL] == 7
