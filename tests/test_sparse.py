"""Sparse layers vs dense oracles."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.sparse import LookupTableSparse, SparseLinear, encode_sparse


def _dense_from_coo(indices, values, size):
    n, k = indices.shape
    dense = np.zeros((n, size), np.float32)
    for i in range(n):
        for j in range(k):
            dense[i, indices[i, j]] += values[i, j]
    return dense


def test_encode_sparse_pads():
    idx, val = encode_sparse([([1, 3], [2.0, 4.0]), ([0], [1.0])])
    assert idx.shape == (2, 2)
    np.testing.assert_array_equal(idx, [[1, 3], [0, 0]])
    np.testing.assert_array_equal(val, [[2.0, 4.0], [1.0, 0.0]])


def test_sparse_linear_matches_dense():
    m = SparseLinear(50, 8, name="sl")
    variables = m.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(7):
        ids = rng.choice(50, size=rng.randint(1, 6), replace=False)
        rows.append((ids, rng.randn(len(ids))))
    idx, val = encode_sparse(rows)
    out, _ = m.apply(variables, (jnp.asarray(idx), jnp.asarray(val)))

    dense = _dense_from_coo(idx, val, 50)
    ref = dense @ np.asarray(variables["params"]["weight"]) + \
        np.asarray(variables["params"]["bias"])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_lookup_sparse_combiners():
    rng = np.random.RandomState(1)
    idx, val = encode_sparse([([2, 5, 9], [1.0, 1.0, 1.0]),
                              ([4], [1.0])])
    for combiner in ("sum", "mean", "sqrtn"):
        m = LookupTableSparse(16, 4, combiner=combiner, name=f"lt_{combiner}")
        variables = m.init(jax.random.PRNGKey(0))
        out, _ = m.apply(variables, (jnp.asarray(idx), jnp.asarray(val)))
        w = np.asarray(variables["params"]["weight"])
        row0 = w[2] + w[5] + w[9]
        if combiner == "mean":
            row0 = row0 / 3.0
        elif combiner == "sqrtn":
            row0 = row0 / np.sqrt(3.0)
        np.testing.assert_allclose(np.asarray(out)[0], row0, atol=1e-5)


def test_sparse_embedding_grad_is_scatter_add():
    m = LookupTableSparse(10, 4, name="lt")
    variables = m.init(jax.random.PRNGKey(0))
    idx, val = encode_sparse([([1, 1], [1.0, 1.0])])  # duplicate id

    def loss(p):
        out, _ = m.apply({"params": p, "state": {}},
                         (jnp.asarray(idx), jnp.asarray(val)))
        return jnp.sum(out)

    g = jax.grad(loss)(variables["params"])["weight"]
    # duplicate contributions accumulate
    np.testing.assert_allclose(np.asarray(g)[1], 2.0 * np.ones(4), atol=1e-6)
    assert float(np.abs(np.asarray(g)[0]).sum()) == 0.0


class TestSparseTensorMath:
    """General sparse math (reference: tensor/SparseTensorMath.scala,
    SparseTensorBLAS.scala) — oracled against dense jnp."""

    def _rand_sparse(self, m, n, density=0.3, seed=0, capacity=None):
        rng = np.random.RandomState(seed)
        dense = rng.randn(m, n) * (rng.rand(m, n) < density)
        return nn.SparseTensor.from_dense(
            dense.astype(np.float32), capacity), dense.astype(np.float32)

    def test_from_to_dense_roundtrip(self):
        sp, dense = self._rand_sparse(5, 7)
        np.testing.assert_array_equal(np.asarray(sp.to_dense()), dense)
        # padded capacity: extra zero entries contribute nothing
        sp2 = nn.SparseTensor.from_dense(dense, capacity=64)
        np.testing.assert_array_equal(np.asarray(sp2.to_dense()), dense)

    def test_mm_mv_dot_against_dense(self):
        sp, dense = self._rand_sparse(6, 8, seed=1, capacity=32)
        rng = np.random.RandomState(2)
        b = rng.randn(8, 4).astype(np.float32)
        v = rng.randn(8).astype(np.float32)
        other = rng.randn(6, 8).astype(np.float32)
        np.testing.assert_allclose(np.asarray(sp.mm(b)), dense @ b,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(sp @ b), dense @ b,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(sp.mv(v)), dense @ v,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(sp.dot(jnp.asarray(other))),
                                   float((dense * other).sum()),
                                   rtol=1e-5)

    def test_addmm_addmv(self):
        sp, dense = self._rand_sparse(4, 6, seed=3)
        rng = np.random.RandomState(4)
        b = rng.randn(6, 3).astype(np.float32)
        c = rng.randn(4, 3).astype(np.float32)
        y = rng.randn(4).astype(np.float32)
        v = rng.randn(6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(nn.addmm(0.5, c, 2.0, sp, b)),
            0.5 * c + 2.0 * (dense @ b), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.addmv(0.25, y, 3.0, sp, v)),
            0.25 * y + 3.0 * (dense @ v), rtol=1e-5, atol=1e-6)

    def test_transpose_add_scale_mul(self):
        sp, dense = self._rand_sparse(5, 4, seed=5)
        sp2, dense2 = self._rand_sparse(5, 4, seed=6)
        np.testing.assert_array_equal(
            np.asarray(sp.transpose().to_dense()), dense.T)
        np.testing.assert_allclose(
            np.asarray(sp.add(sp2).to_dense()), dense + dense2,
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sp.scale(2.5).to_dense()), dense * 2.5, rtol=1e-6)
        other = np.random.RandomState(7).randn(5, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(sp.mul_dense(jnp.asarray(other)).to_dense()),
            dense * other, rtol=1e-5, atol=1e-6)

    def test_jit_and_grad_through_sparse(self):
        """SparseTensor is a pytree: passes through jit, and grad wrt
        the dense operand of mm matches the dense formulation."""
        sp, dense = self._rand_sparse(6, 8, seed=8)
        b0 = np.random.RandomState(9).randn(8, 4).astype(np.float32)

        @jax.jit
        def f(s, b):
            return jnp.sum(s.mm(b) ** 2)

        g = jax.grad(lambda b: f(sp, b))(jnp.asarray(b0))
        want = jax.grad(lambda b: jnp.sum((jnp.asarray(dense) @ b) ** 2))(
            jnp.asarray(b0))
        np.testing.assert_allclose(np.asarray(g), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_sparse_join_table(self):
        """SparseJoinTable concatenates batch-COO features with column
        offsets; feeding the join into SparseLinear equals summing two
        SparseLinears over the concatenated weight."""
        idx1, val1 = nn.encode_sparse([([0, 2], [1.0, 2.0]),
                                       ([1], [3.0])])
        idx2, val2 = nn.encode_sparse([([1], [4.0]),
                                       ([0, 3], [5.0, 6.0])])
        join = nn.SparseJoinTable([4, 5]).build(jax.random.PRNGKey(0))
        (jidx, jval), _ = join.apply(join.variables,
                                     (jnp.asarray(idx1), jnp.asarray(val1)),
                                     (jnp.asarray(idx2), jnp.asarray(val2)))
        assert jidx.shape == (2, 4) and jval.shape == (2, 4)
        lin = nn.SparseLinear(9, 3).build(jax.random.PRNGKey(1))
        out, _ = lin.apply(lin.variables, (jidx, jval))
        # dense oracle
        d1 = np.zeros((2, 4), np.float32)
        d1[0, 0], d1[0, 2], d1[1, 1] = 1.0, 2.0, 3.0
        d2 = np.zeros((2, 5), np.float32)
        d2[0, 1], d2[1, 0], d2[1, 3] = 4.0, 5.0, 6.0
        full = np.concatenate([d1, d2], axis=1)
        w = np.asarray(lin.variables["params"]["weight"])
        b = np.asarray(lin.variables["params"]["bias"])
        np.testing.assert_allclose(np.asarray(out), full @ w + b,
                                   rtol=1e-5, atol=1e-6)

    def test_grad_wrt_values_via_with_values(self):
        """The documented differentiation pattern: grad wrt the float
        values leaf through with_values + mm."""
        sp, dense = self._rand_sparse(4, 6, seed=10)
        b = jnp.asarray(np.random.RandomState(11).randn(6, 2), jnp.float32)

        def f(vals):
            return jnp.sum(sp.with_values(vals).mm(b) ** 2)

        g = jax.grad(f)(sp.values)
        # oracle: d/dvals sum((sum_nnz vals_i e_i @ b)^2)
        rows, cols = np.asarray(sp.indices).T
        out = np.asarray(sp.mm(b))
        want = 2.0 * np.einsum("nk->n", out[rows] * np.asarray(b)[cols])
        np.testing.assert_allclose(np.asarray(g), want, rtol=1e-4,
                                   atol=1e-5)
