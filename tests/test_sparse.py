"""Sparse layers vs dense oracles."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.sparse import LookupTableSparse, SparseLinear, encode_sparse


def _dense_from_coo(indices, values, size):
    n, k = indices.shape
    dense = np.zeros((n, size), np.float32)
    for i in range(n):
        for j in range(k):
            dense[i, indices[i, j]] += values[i, j]
    return dense


def test_encode_sparse_pads():
    idx, val = encode_sparse([([1, 3], [2.0, 4.0]), ([0], [1.0])])
    assert idx.shape == (2, 2)
    np.testing.assert_array_equal(idx, [[1, 3], [0, 0]])
    np.testing.assert_array_equal(val, [[2.0, 4.0], [1.0, 0.0]])


def test_sparse_linear_matches_dense():
    m = SparseLinear(50, 8, name="sl")
    variables = m.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    rows = []
    for _ in range(7):
        ids = rng.choice(50, size=rng.randint(1, 6), replace=False)
        rows.append((ids, rng.randn(len(ids))))
    idx, val = encode_sparse(rows)
    out, _ = m.apply(variables, (jnp.asarray(idx), jnp.asarray(val)))

    dense = _dense_from_coo(idx, val, 50)
    ref = dense @ np.asarray(variables["params"]["weight"]) + \
        np.asarray(variables["params"]["bias"])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_lookup_sparse_combiners():
    rng = np.random.RandomState(1)
    idx, val = encode_sparse([([2, 5, 9], [1.0, 1.0, 1.0]),
                              ([4], [1.0])])
    for combiner in ("sum", "mean", "sqrtn"):
        m = LookupTableSparse(16, 4, combiner=combiner, name=f"lt_{combiner}")
        variables = m.init(jax.random.PRNGKey(0))
        out, _ = m.apply(variables, (jnp.asarray(idx), jnp.asarray(val)))
        w = np.asarray(variables["params"]["weight"])
        row0 = w[2] + w[5] + w[9]
        if combiner == "mean":
            row0 = row0 / 3.0
        elif combiner == "sqrtn":
            row0 = row0 / np.sqrt(3.0)
        np.testing.assert_allclose(np.asarray(out)[0], row0, atol=1e-5)


def test_sparse_embedding_grad_is_scatter_add():
    m = LookupTableSparse(10, 4, name="lt")
    variables = m.init(jax.random.PRNGKey(0))
    idx, val = encode_sparse([([1, 1], [1.0, 1.0])])  # duplicate id

    def loss(p):
        out, _ = m.apply({"params": p, "state": {}},
                         (jnp.asarray(idx), jnp.asarray(val)))
        return jnp.sum(out)

    g = jax.grad(loss)(variables["params"])["weight"]
    # duplicate contributions accumulate
    np.testing.assert_allclose(np.asarray(g)[1], 2.0 * np.ones(4), atol=1e-6)
    assert float(np.abs(np.asarray(g)[0]).sum()) == 0.0
