"""Torch import: converted models must match torch outputs numerically
(the reference's Torch-as-oracle strategy, SURVEY.md §4)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from bigdl_tpu.utils.torch_interop import from_torch  # noqa: E402


def _check(tm, x_torch, x_ours, atol=1e-5, **kw):
    tm.eval()
    with torch.no_grad():
        ref = tm(x_torch).numpy()
    m, variables = from_torch(tm, **kw)
    m.evaluate()
    out, _ = m.apply(variables, jnp.asarray(x_ours), training=False)
    np.testing.assert_allclose(np.asarray(out), ref, atol=atol, rtol=1e-4)


def test_linear():
    torch.manual_seed(0)
    tm = tnn.Linear(12, 5)
    x = torch.randn(3, 12)
    _check(tm, x, x.numpy())


def test_mlp_sequential():
    torch.manual_seed(0)
    tm = tnn.Sequential(tnn.Linear(8, 16), tnn.ReLU(), tnn.Dropout(0.5),
                        tnn.Linear(16, 4), tnn.LogSoftmax(dim=-1))
    x = torch.randn(6, 8)
    _check(tm, x, x.numpy())


def test_conv_bn_pool_nchw():
    torch.manual_seed(0)
    tm = tnn.Sequential(
        tnn.Conv2d(3, 8, 3, stride=1, padding=1),
        tnn.BatchNorm2d(8),
        tnn.ReLU(),
        tnn.MaxPool2d(2),
        tnn.Conv2d(8, 4, 3),
        tnn.AvgPool2d(2),
    )
    # push some stats through BN so running stats are non-trivial
    tm.train()
    with torch.no_grad():
        tm(torch.randn(8, 3, 16, 16))
    x = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        tm.eval()
        ref = tm(x).numpy()              # NCHW output
    m, variables = from_torch(tm, input_layout="NCHW")
    m.evaluate()
    out, _ = m.apply(variables, jnp.asarray(x.numpy()), training=False)
    # ours emits NHWC; compare against torch's NCHW transposed
    np.testing.assert_allclose(np.asarray(out),
                               ref.transpose(0, 2, 3, 1), atol=1e-4,
                               rtol=1e-4)


def test_lenet_like_with_flatten():
    torch.manual_seed(1)
    tm = tnn.Sequential(
        tnn.Conv2d(1, 6, 5, padding=2), tnn.ReLU(), tnn.MaxPool2d(2),
        tnn.Flatten(), tnn.Linear(6 * 14 * 14, 10),
    )
    x = torch.randn(2, 1, 28, 28)
    with torch.no_grad():
        tm.eval()
        ref = tm(x).numpy()
    m, variables = from_torch(tm)  # feed NHWC directly
    # NOTE: flatten order differs between NCHW and NHWC layouts, so for
    # models with Flatten→Linear the import must keep torch's layout:
    m, variables = from_torch(tm, input_layout="NCHW")
    m.evaluate()
    out, _ = m.apply(variables, jnp.asarray(x.numpy()), training=False)
    # flatten of NHWC permutes features vs torch's NCHW flatten; the
    # Linear consumes a permuted-but-consistent basis only if we also
    # permute its weight — so this case documents the limitation:
    assert out.shape == ref.shape


def test_embedding():
    torch.manual_seed(0)
    tm = tnn.Embedding(20, 6)
    idx = torch.randint(0, 20, (4, 7))
    tm.eval()
    with torch.no_grad():
        ref = tm(idx).numpy()
    m, variables = from_torch(tm)
    out, _ = m.apply(variables, jnp.asarray(idx.numpy()))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_unsupported_layer_raises():
    with pytest.raises(NotImplementedError, match="no bigdl_tpu mapping"):
        from_torch(tnn.TransformerEncoderLayer(16, 2))
