"""Live SLO plane (ISSUE 14): windowed quantiles vs a numpy oracle,
sampler ring/capacity semantics, the alert-rule state machine
(pending→firing→resolved + flap suppression), autoscaler-on-shared-
windowing bit-identity, the scrape endpoint, and the serving
compile-count guard re-pinned with the sampler + alert engine armed."""

import json
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import obs
from bigdl_tpu.obs.registry import quantile_from_buckets
from bigdl_tpu.obs.slo import (BAD_STATUSES, AlertEngine, AlertRule,
                               SLOObjective)
from bigdl_tpu.obs.timeseries import (HistogramWindow, MetricsSampler,
                                      delta_quantile)


@pytest.fixture(autouse=True)
def _fresh_obs():
    prev = obs.set_enabled(True)
    obs.reset_all()
    yield
    obs.reset_all()
    obs.set_enabled(prev)


def _clock():
    clk = {"t": 0.0}
    return clk, (lambda: clk["t"])


# --------------------------------------------------------- time series

def test_window_quantile_vs_numpy_oracle():
    """The windowed (bucket-delta) quantile must track np.quantile of
    ONLY the in-window observations within one bucket width, across
    distributions — pre-window observations must not bleed in."""
    edges = tuple(np.linspace(0.01, 1.0, 100))      # width 0.01
    rng = np.random.RandomState(7)
    for dist in (rng.uniform(0, 1, (2, 1500)),
                 rng.beta(2, 5, (2, 1500)),         # skewed low
                 rng.beta(5, 1, (2, 1500))):        # skewed high
        warmup, windowed = dist
        clk, c = _clock()
        reg = obs.set_registry(obs.MetricsRegistry(clock=c))
        h = reg.histogram("h_seconds", buckets=edges)
        sampler = MetricsSampler(reg, interval_s=0.0, clock=c)
        for v in warmup:                            # pre-window noise
            h.observe(float(v))
        sampler.sample()                            # window opens
        clk["t"] = 10.0
        for v in windowed:
            h.observe(float(v))
        sampler.sample()                            # window closes
        for q in (0.1, 0.5, 0.9, 0.99):
            est = sampler.window_quantile("h_seconds", q)
            oracle = float(np.quantile(windowed, q))
            assert abs(est - oracle) <= 0.011, (q, est, oracle)
        # the primitive agrees with the registry estimator on a
        # from-zero delta
        child = h.labels()
        assert delta_quantile(child.buckets, child.counts, None, 0.5) \
            == quantile_from_buckets(child.buckets, child.counts, 0.5)


def test_sampler_ring_capacity_and_tick_rate_limit():
    clk, c = _clock()
    reg = obs.set_registry(obs.MetricsRegistry(clock=c))
    ctr = reg.counter("x_total")
    sampler = MetricsSampler(reg, interval_s=1.0, capacity=4, clock=c)
    assert sampler.tick() is not None           # first tick samples
    assert sampler.tick() is None               # rate-limited
    for i in range(6):
        clk["t"] += 1.0
        ctr.inc()
        assert sampler.tick() is not None
    assert len(sampler) == 4                    # ring bound
    # oldest samples rolled off the RING: the sample list starts at
    # t=3, but whole-run queries keep the first-sample baseline
    assert sampler.samples()[0]["t"] == 3.0
    assert sampler.latest()["t"] == 6.0
    # window selection is by sample time relative to the newest
    assert [s["t"] for s in sampler.samples(window_s=2.0)] \
        == [4.0, 5.0, 6.0]
    # whole-run delta/rate anchor at the never-evicted baseline
    # (t=0, count 0) — eviction must not silently turn "whole run"
    # into "last capacity samples" (sim-found truncation, ISSUE 20)
    assert sampler.span()[0]["t"] == 0.0
    assert sampler.delta("x_total") == 6.0      # counts 0 → 6
    assert sampler.rate("x_total") == pytest.approx(1.0)
    assert sampler.delta("x_total", window_s=1.0) == 1.0
    # a family absent from the newest sample → None; absent series
    # born inside the window counts from zero
    assert sampler.delta("nope_total") is None
    with pytest.raises(ValueError):
        MetricsSampler(reg, capacity=1)
    with pytest.raises(ValueError):
        MetricsSampler(reg, interval_s=-1.0)


def test_whole_run_queries_survive_ring_roll():
    """Regression for the ISSUE 20 sim-found control-plane bug: a
    10^5-request scenario ticks the sampler thousands of times past
    `capacity`, and every `window_s=None` ("whole run" by contract)
    query used to diff against the oldest SURVIVING ring sample —
    loadgen's end-of-run SLO compliance silently summarized only the
    tail of the run. With the never-evicted first-sample baseline,
    whole-run deltas/quantiles/error budgets count from the actual
    start after the ring rolls, while bounded windows still read only
    the ring. Real components throughout (registry, sampler,
    SLOObjective) — the fix must hold outside the simulator."""
    clk, c = _clock()
    reg = obs.set_registry(obs.MetricsRegistry(clock=c))
    ctr = reg.counter("serving_requests_total", "", ("status",))
    h = reg.histogram("req_latency_seconds",
                      buckets=(0.1, 1.0, 10.0, 100.0))
    sampler = MetricsSampler(reg, interval_s=0.0, capacity=4, clock=c)
    sampler.sample()                      # the t=0 baseline
    for _ in range(20):                   # bad, slow head ...
        clk["t"] += 1.0
        ctr.labels(status="shed").inc()
        h.observe(50.0)
        sampler.sample()
    for _ in range(20):                   # ... clean fast tail fills
        clk["t"] += 1.0                   # the whole ring
        ctr.labels(status="done").inc()
        h.observe(0.05)
        sampler.sample()
    assert len(sampler) == 4              # ring rolled long ago
    # whole-run endpoints: the baseline survives eviction
    old, new = sampler.span()
    assert old["t"] == 0.0 and new["t"] == 40.0
    assert sampler.delta("serving_requests_total",
                         labels={"status": "shed"}) == 20.0
    deltas = dict((k["status"], v) for k, v in
                  sampler.series_deltas("serving_requests_total"))
    assert deltas == {"done": 20.0, "shed": 20.0}
    # whole-run error budget sees the bad head (50% shed), and the
    # whole-run p75 lands in the slow head's bucket — a truncated
    # window would report the clean tail's <= 0.1
    obj = SLOObjective(name="goodput", kind="error_budget",
                       metric="serving_requests_total", target=0.05)
    assert obj.measure(sampler) == pytest.approx(0.5)
    assert obj.violated(obj.measure(sampler))
    p75 = sampler.window_quantile("req_latency_seconds", 0.75)
    assert p75 is not None and p75 > 1.0        # head not forgotten
    # bounded windows are untouched: the last 3 samples are all clean
    assert sampler.delta("serving_requests_total",
                         labels={"status": "shed"}, window_s=3.0) == 0.0
    assert sampler.window_quantile("req_latency_seconds", 0.99,
                                   window_s=3.0) <= 0.1


def test_sampler_series_deltas_and_error_budget():
    clk, c = _clock()
    reg = obs.set_registry(obs.MetricsRegistry(clock=c))
    ctr = reg.counter("serving_requests_total", "",
                      ("engine", "status", "tp"))
    sampler = MetricsSampler(reg, interval_s=0.0, clock=c)
    sampler.sample()
    ctr.labels(engine="e0", status="done", tp="1").inc(18)
    ctr.labels(engine="e0", status="shed", tp="1").inc(2)
    clk["t"] = 5.0
    sampler.sample()
    deltas = dict((tuple(sorted(k.items())), v) for k, v in
                  sampler.series_deltas("serving_requests_total"))
    assert sum(deltas.values()) == 20
    obj = SLOObjective(name="goodput", kind="error_budget",
                       metric="serving_requests_total", target=0.05)
    assert obj.measure(sampler) == pytest.approx(0.1)
    assert obj.violated(obj.measure(sampler))
    ev = obj.evaluate(sampler)
    assert ev["ok"] is False and ev["value"] == pytest.approx(0.1)
    # label-subset filtering
    obj_e1 = SLOObjective(name="g1", kind="error_budget",
                          metric="serving_requests_total", target=0.05,
                          labels={"engine": "e1"})
    assert obj_e1.measure(sampler) is None      # no e1 traffic


def test_objective_validation():
    with pytest.raises(ValueError, match="objective kind"):
        SLOObjective(name="x", kind="frobnicate", metric="m",
                     target=1.0)
    with pytest.raises(ValueError, match="q must be"):
        SLOObjective(name="x", kind="latency_quantile", metric="m",
                     target=1.0, q=1.5)
    with pytest.raises(ValueError, match="alert kind"):
        AlertRule(name="a", objective=SLOObjective(
            name="x", kind="latency_quantile", metric="m",
            target=1.0), kind="frobnicate")
    with pytest.raises(ValueError, match="short_window_s"):
        AlertRule(name="a", objective=SLOObjective(
            name="x", kind="latency_quantile", metric="m",
            target=1.0), kind="burn_rate", long_window_s=1.0,
            short_window_s=2.0)


# --------------------------------------------------- alert state machine

def _latency_plane(clk, c, buckets=(0.5, 1.0, 2.5, 5.0, 10.0)):
    reg = obs.set_registry(obs.MetricsRegistry(clock=c))
    child = reg.histogram("lat_seconds", buckets=buckets).labels()
    sampler = MetricsSampler(reg, interval_s=0.0, clock=c)
    obj = SLOObjective(name="p99", kind="latency_quantile",
                       metric="lat_seconds", target=1.0, q=0.99)
    return reg, child, sampler, obj


def test_alert_threshold_pending_firing_resolved():
    """inactive → pending (for_s not yet held) → firing → resolved
    after a clear_s healthy streak — each transition emitting exactly
    one registered event with the injected-clock stamps."""
    clk, c = _clock()
    reg, child, sampler, obj = _latency_plane(clk, c)
    rule = AlertRule(name="p99_thr", objective=obj, kind="threshold",
                     window_s=4.0, for_s=2.0, clear_s=2.0)
    aeng = AlertEngine(sampler, [rule], clock=c)
    log = obs.get_event_log()

    def step(lat):
        clk["t"] += 1.0
        child.observe(lat)
        sampler.sample()
        return aeng.evaluate()[0]

    sampler.sample()
    assert step(0.2)["state"] == "inactive"     # healthy
    assert step(3.0)["state"] == "pending"      # breach, for_s opens
    assert step(3.0)["state"] == "pending"      # 1.0s < for_s... held
    r = step(3.0)                               # 2.0s held → firing
    assert r["state"] == "firing"
    assert aeng.firing() == ["p99_thr"]
    firing_ev = log.events("alert_firing")
    assert len(firing_ev) == 1
    assert firing_ev[0]["alert"] == "p99_thr"
    assert firing_ev[0]["objective"] == "p99"
    assert firing_ev[0]["value"] > 1.0
    assert firing_ev[0]["window_s"] == 4.0
    assert firing_ev[0]["pending_s"] == 2.0
    # recovery: the breach must first AGE OUT of the 4 s window (the
    # stale 3.0s keep the measured p99 hot until then), and only then
    # does the healthy streak have to hold for clear_s
    assert step(0.2)["state"] == "firing"       # 3.0@t=2..4 in window
    assert step(0.2)["state"] == "firing"
    assert step(0.2)["state"] == "firing"
    assert step(0.2)["state"] == "firing"       # window clean: streak
    assert step(0.2)["state"] == "firing"       # 1.0s < clear_s
    assert step(0.2)["state"] == "inactive"     # 2.0s held → resolved
    resolved_ev = log.events("alert_resolved")
    assert len(resolved_ev) == 1
    assert resolved_ev[0]["firing_s"] == 6.0
    assert aeng.fired == 1 and aeng.resolved == 1


def test_alert_pending_that_heals_never_fires():
    """A breach that leaves the window before for_s is held walks
    pending → inactive with no events (a 1 s window ages the spike
    out before the 2 s pending duration elapses)."""
    clk, c = _clock()
    reg, child, sampler, obj = _latency_plane(clk, c)
    rule = AlertRule(name="p99_thr", objective=obj, kind="threshold",
                     window_s=1.0, for_s=2.0, clear_s=0.0)
    aeng = AlertEngine(sampler, [rule], clock=c)

    def step(lat):
        clk["t"] += 1.0
        child.observe(lat)
        sampler.sample()
        return aeng.evaluate()[0]

    sampler.sample()
    assert step(3.0)["state"] == "pending"
    assert step(0.2)["state"] == "inactive"     # spike aged out
    assert aeng.fired == 0
    assert obs.get_event_log().events("alert_firing") == []


def test_alert_flap_suppression_resets_healthy_streak():
    """A re-breach inside the clear_s streak resets it — the alert
    keeps firing instead of flapping resolve/refire."""
    clk, c = _clock()
    reg, child, sampler, obj = _latency_plane(clk, c)
    rule = AlertRule(name="p99_burn", objective=obj, kind="burn_rate",
                     long_window_s=3.0, short_window_s=1.0,
                     clear_s=3.0)
    aeng = AlertEngine(sampler, [rule], clock=c)

    def step(lat):
        clk["t"] += 1.0
        child.observe(lat)
        sampler.sample()
        return aeng.evaluate()[0]

    sampler.sample()
    r = step(3.0)                     # both windows hot → fires NOW
    assert r["state"] == "firing"     # (burn rate has no for_s)
    assert r["long_value"] is not None and r["burn"] > 1.0
    step(3.0)
    assert step(0.2)["state"] == "firing"       # healthy streak opens
    assert step(3.0)["state"] == "firing"       # FLAP: streak resets
    # the short window clears immediately (breach needs BOTH windows
    # hot), so the streak re-opens on the next healthy second and must
    # then hold the full clear_s
    assert step(0.2)["state"] == "firing"       # streak re-opens
    assert step(0.2)["state"] == "firing"       # 1.0s
    assert step(0.2)["state"] == "firing"       # 2.0s
    assert step(0.2)["state"] == "inactive"     # 3.0s → resolves
    assert aeng.fired == 1 and aeng.resolved == 1
    ev = obs.get_event_log().events("alert_firing")
    assert len(ev) == 1 and ev[0]["rule_kind"] == "burn_rate"
    assert ev[0]["window_s"] == 3.0             # the LONG window named


def test_alert_absence_rule():
    """Silence is an incident: zero family increments over the window
    (while the sampler has data) fires; traffic resuming resolves."""
    clk, c = _clock()
    reg = obs.set_registry(obs.MetricsRegistry(clock=c))
    ctr = reg.counter("beats_total")
    sampler = MetricsSampler(reg, interval_s=0.0, clock=c)
    obj = SLOObjective(name="beats", kind="error_budget",
                       metric="beats_total", target=1.0)
    rule = AlertRule(name="dead_emitter", objective=obj,
                     kind="absence", window_s=2.0, for_s=0.0,
                     clear_s=0.0)
    aeng = AlertEngine(sampler, [rule], clock=c)

    def step(beat):
        clk["t"] += 1.0
        if beat:
            ctr.inc()
        sampler.sample()
        return aeng.evaluate()[0]

    sampler.sample()
    assert step(True)["state"] == "inactive"
    assert step(True)["state"] == "inactive"
    step(False)
    r = step(False)                   # 2 s window all silent → fires
    assert r["state"] == "firing"
    assert step(True)["state"] == "inactive"    # heartbeat resumes
    assert aeng.fired == 1 and aeng.resolved == 1


def test_alert_transitions_emit_outside_the_engine_lock():
    """emit_event runs listeners synchronously (the flight recorder
    dumps bundles and calls health sources) — a listener reading
    alerts() during a firing emission must NOT deadlock on the
    engine's non-reentrant lock (review fix: transitions are collected
    under the lock, emitted after it releases)."""
    clk, c = _clock()
    reg, child, sampler, obj = _latency_plane(clk, c)
    rule = AlertRule(name="p99", objective=obj)
    aeng = AlertEngine(sampler, [rule], clock=c)
    seen = []

    def listener(rec):
        if rec["kind"] == "alert_firing":
            seen.append(aeng.alerts()[0]["state"])  # would deadlock

    obs.get_event_log().add_listener(listener)
    sampler.sample()
    clk["t"] += 1.0
    child.observe(3.0)
    sampler.sample()
    aeng.evaluate()
    assert seen == ["firing"]       # the listener saw settled state


def test_alert_engine_rejects_duplicate_names():
    clk, c = _clock()
    reg, child, sampler, obj = _latency_plane(clk, c)
    rule = AlertRule(name="a", objective=obj)
    with pytest.raises(ValueError, match="duplicate"):
        AlertEngine(sampler, [rule, rule], clock=c)


# -------------------------------- autoscaler on the shared windowing

def test_histogram_window_matches_legacy_window_p99():
    """HistogramWindow must reproduce the autoscaler's old private
    `_window_p99` EXACTLY over interleaved windows — the refactor's
    bit-identity claim at the primitive level (the fleet_autoscale
    drill pins it end to end)."""
    reg = obs.set_registry(obs.MetricsRegistry())
    child = reg.histogram("lat_seconds").labels()
    win = HistogramWindow(child)
    legacy_last = [None]

    def legacy():                      # the pre-ISSUE-14 math, verbatim
        counts = list(child.counts)
        prev = legacy_last[0] or [0] * len(counts)
        legacy_last[0] = counts
        delta = [cc - p for cc, p in zip(counts, prev)]
        return quantile_from_buckets(child.buckets, delta, 0.99)

    rng = np.random.RandomState(3)
    for _ in range(50):
        for v in rng.exponential(0.05, int(rng.randint(0, 20))):
            child.observe(float(v))
        a, b = win.quantile(0.99), legacy()
        assert a == b                  # exact, not approx


class _StubEngine:
    slots = 2
    max_queue = 8
    model_tag = None                   # ISSUE 19: the "default" group
    degraded = None

    def __init__(self):
        self.slots_active = 0
        self.queue_depth = 0
        self.overload_policy = "reject"
        self._state = "running"
        self.obs_name = "stub"

    @property
    def draining(self):
        return self._state != "running"

    def health(self):
        return {"state": self._state}


class _StubRouter:
    """The minimal surface Autoscaler consumes — real registry child,
    injected clock, deterministic pool ops."""

    def __init__(self, clock):
        self._clock = clock
        self._obs_name = "rstub"
        self.engines = [_StubEngine()]
        reg = obs.get_registry()
        from bigdl_tpu.serving.router import ROUTER_LATENCY_BUCKETS
        self.request_latency = reg.histogram(
            "router_request_latency_seconds",
            labelnames=("router",),
            buckets=ROUTER_LATENCY_BUCKETS).labels(router="rstub")

    def healthy_engines(self):
        return [e for e in self.engines if e._state == "running"]

    def add_engine(self, group=None):
        self.engines.append(_StubEngine())

    def drain(self, e):
        e._state = "drained"

    def remove_engine(self, e):
        self.engines.remove(e)


def test_autoscaler_consumes_shared_objective():
    """With `objective=` the scaler derives its target from — and
    defers threshold judgement to — the same SLOObjective the alert
    engine watches; the decision sequence matches a threshold-mode
    scaler with the identical target, decision for decision."""
    from bigdl_tpu.serving.autoscaler import Autoscaler

    def run(objective):
        clk, c = _clock()
        obs.set_registry(obs.MetricsRegistry(clock=c))
        router = _StubRouter(c)
        kw = {"objective": objective} if objective is not None \
            else {"target_p99_s": 1.0}
        asc = Autoscaler(router, max_engines=2, evaluate_every_s=1.0,
                         **kw)
        decisions = []
        for lat in (3.0, 3.0, 0.1, 0.1, 0.1, 0.1):
            clk["t"] += 1.0
            router.request_latency.observe(lat)
            d = asc.observe()
            decisions.append((d["action"], d["p99_s"], d["engines"]))
        return asc, decisions

    obj = SLOObjective(name="p99", kind="latency_quantile",
                       metric="router_request_latency_seconds",
                       target=1.0, labels={"router": "rstub"})
    asc_obj, dec_obj = run(obj)
    asc_thr, dec_thr = run(None)
    assert dec_obj == dec_thr                   # same decisions
    assert asc_obj.target_p99_s == 1.0          # derived from the SLO
    assert dec_obj[0][0] == "scale_up"          # 3.0 > 1.0 target
    assert asc_obj.decisions[0]["objective"] == "p99"
    assert "objective" not in asc_thr.decisions[0]
    with pytest.raises(ValueError, match="latency_quantile"):
        clk, c = _clock()
        Autoscaler(_StubRouter(c), objective=SLOObjective(
            name="g", kind="error_budget", metric="m", target=0.1))
    with pytest.raises(ValueError, match="target_p99_s"):
        clk, c = _clock()
        Autoscaler(_StubRouter(c))
    # a silently diverging target pair would make the recorded target
    # lie about the threshold applied (review fix)
    with pytest.raises(ValueError, match="disagrees"):
        clk, c = _clock()
        Autoscaler(_StubRouter(c), target_p99_s=8.0, objective=obj)
    # equal pair is fine; the objective's quantile is the one measured
    clk, c = _clock()
    obs.set_registry(obs.MetricsRegistry(clock=c))
    asc = Autoscaler(_StubRouter(c), target_p99_s=1.0, objective=obj)
    assert asc.target_p99_s == 1.0


def test_alerts_section_unions_overlapping_firing_intervals():
    """Two rules over one objective firing together must not
    double-count budget: compliance is computed on the UNION of firing
    intervals and clamps at 0 (review fix)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "obs_report.py")
    spec = importlib.util.spec_from_file_location("obs_report_slo",
                                                  path)
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)

    def ev(seq, ts, kind, alert, **kw):
        return {"schema": 1, "seq": seq, "ts": ts, "kind": kind,
                "plane": "serving", "alert": alert,
                "objective": "p99", "value": 3.0, "target": 1.0,
                "window_s": 4.0, "rule_kind": "threshold", **kw}

    events = [
        {"schema": 1, "seq": 0, "ts": 0.0, "kind": "train_step"},
        ev(1, 1.0, "alert_firing", "burn"),
        ev(2, 2.0, "alert_firing", "thr"),
        ev(3, 8.0, "alert_resolved", "burn", firing_s=7.0),
        ev(4, 9.0, "alert_resolved", "thr", firing_s=7.0),
        {"schema": 1, "seq": 5, "ts": 10.0, "kind": "train_step"},
    ]
    s = rep._alerts_section(events)
    o = s["objectives"]["p99"]
    # overlap [1,8] ∪ [2,9] = [1,9] → 8.0s, NOT 14.0s
    assert o["time_firing_s"] == 8.0
    assert o["compliant_frac"] == pytest.approx(0.2)
    assert o["compliant_frac"] >= 0.0


# ------------------------------------------------------ scrape endpoint

def test_scrape_server_routes():
    """/metrics serves the registry's Prometheus text, /health the
    JSON ops view (sampler freshness + compliance + alerts), /alerts
    the alert states; unknown routes 404 — all from the daemon thread
    against lock-guarded shared state."""
    clk, c = _clock()
    reg = obs.set_registry(obs.MetricsRegistry(clock=c))
    reg.counter("req_total", "reqs", ("status",)).labels(
        status="done").inc(4)
    child = reg.histogram("lat_seconds", buckets=(0.5, 1.0)).labels()
    sampler = MetricsSampler(reg, interval_s=0.0, clock=c)
    obj = SLOObjective(name="p99", kind="latency_quantile",
                       metric="lat_seconds", target=1.0)
    aeng = AlertEngine(sampler, [AlertRule(name="p99", objective=obj)],
                       clock=c)
    sampler.sample()
    clk["t"] = 1.0
    child.observe(0.2)
    sampler.sample()
    aeng.evaluate()

    srv = obs.ScrapeServer(registry=reg, sampler=sampler,
                           alert_engine=aeng)
    try:
        port = srv.start()
        base = f"http://127.0.0.1:{port}"

        def get(path):
            try:
                with urllib.request.urlopen(base + path,
                                            timeout=5.0) as r:
                    return r.status, r.read()
            except urllib.error.HTTPError as e:  # 404 etc.
                return e.code, e.read()

        code, body = get("/metrics")
        text = body.decode()
        assert code == 200
        assert 'req_total{status="done"} 4' in text
        assert text == reg.render_prometheus()  # THE exposition bytes
        code, body = get("/health")
        h = json.loads(body)
        assert code == 200 and h["scrapes"] >= 2
        assert h["sampler"]["samples"] == 2
        assert h["sampler"]["last_sample_t"] == 1.0
        assert h["objectives"][0]["ok"] is True
        assert h["alerts"][0]["state"] == "inactive"
        code, body = get("/alerts")
        assert code == 200
        assert json.loads(body)["firing"] == []
        code, body = get("/nope")
        assert code == 404
    finally:
        srv.close()


# -------------------------- compile guard with the SLO plane armed

def _tiny_lm():
    import jax

    from bigdl_tpu.models.transformer import build_lm

    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=1,
                 max_len=64)
    m.build(jax.random.PRNGKey(0))
    return m


def test_compile_guard_with_slo_plane_armed():
    """The zero-recompile contract with the FULL ops loop armed —
    registry + events + sampler ticking + alert evaluation between
    waves: still exactly (#buckets) prefill traces + 1 decode trace,
    because sampling/alerting are pure host-side reads of
    already-fetched values (the <1% telemetry-overhead budget is
    re-measured with this plane armed by bench.py's lmdecode_batched
    row — `slo_plane: armed`)."""
    from bigdl_tpu.serving import InferenceEngine, Request

    m = _tiny_lm()
    eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16))
    sampler = MetricsSampler(interval_s=0.0)
    obj = SLOObjective(name="decode_p99", kind="latency_quantile",
                       metric="serving_decode_step_seconds",
                       target=60.0,
                       labels={"engine": eng.obs_name, "tp": "1"})
    aeng = AlertEngine(sampler, [AlertRule(name="decode_p99",
                                           objective=obj)])
    sampler.sample()
    rng = np.random.RandomState(0)
    res = eng.run([Request(prompt=list(rng.randint(1, 50, n)),
                           max_new_tokens=3) for n in (3, 10, 6)])
    assert all(r.status == "done" for r in res)
    sampler.tick()
    assert aeng.evaluate()[0]["state"] == "inactive"
    p0, d0 = eng.stats["prefill_traces"], eng.stats["decode_traces"]
    assert (p0, d0) == (2, 1)
    # second wave with the plane still ticking: nothing new compiles
    eng.run([Request(prompt=[1, 2, 3], max_new_tokens=2)])
    sampler.tick()
    out = aeng.evaluate()
    assert eng.stats["prefill_traces"] == p0
    assert eng.stats["decode_traces"] == d0
    assert out[0]["value"] is not None          # it measured real data
    assert obj.violated(out[0]["value"]) is False
    assert BAD_STATUSES == ("shed", "expired", "poisoned", "failed")
