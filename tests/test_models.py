"""Model zoo shape/grad tests (reference: models/*/...Spec.scala)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models import alexnet, autoencoder, inception, lenet, resnet, rnn

KEY = jax.random.PRNGKey(0)


def n_params(model):
    return sum(int(np.prod(np.shape(p))) for _, p in model.parameters())


class TestLeNet:
    def test_output_shape(self):
        m = lenet.build(10).build(KEY).evaluate()
        out = m.forward(jnp.ones((2, 28, 28, 1)))
        assert out.shape == (2, 10)
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0, rtol=1e-5)


class TestResNet:
    def test_cifar_resnet20_shape(self):
        m = resnet.build_cifar(20, 10).build(KEY).evaluate()
        out = m.forward(jnp.ones((2, 32, 32, 3)))
        assert out.shape == (2, 10)

    def test_cifar_param_count(self):
        # canonical resnet-20 cifar: ~0.27M params
        m = resnet.build_cifar(20, 10).build(KEY)
        assert 0.25e6 < n_params(m) < 0.30e6

    def test_resnet50_param_count(self):
        m = resnet.build_imagenet(50, 1000).build(KEY)
        # canonical resnet-50: 25.56M
        assert 25.0e6 < n_params(m) < 26.1e6

    def test_resnet50_forward(self):
        m = resnet.build_imagenet(50, 1000).build(KEY).evaluate()
        out = m.forward(jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 1000)

    def test_resnet18_forward(self):
        m = resnet.build_imagenet(18, 1000).build(KEY).evaluate()
        out = m.forward(jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 1000)

    def test_shortcut_type_a_pads_channels(self):
        m = resnet.build_cifar(20, 10, shortcut_type="A").build(KEY)
        # type A adds no conv params in shortcuts: fewer params than B
        mb = resnet.build_cifar(20, 10, shortcut_type="B").build(KEY)
        assert n_params(m) < n_params(mb)

    def test_cifar_grad_flows(self):
        m = resnet.build_cifar(8, 10)
        variables = m.init(KEY)

        def loss(p):
            out, _ = m.apply({"params": p, "state": variables["state"]},
                             jnp.ones((2, 32, 32, 3)), training=True)
            return jnp.sum(out)

        g = jax.grad(loss)(variables["params"])
        total = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(g))
        assert np.isfinite(total) and total > 0


class TestInception:
    def test_inception_v1_shapes(self):
        m = inception.build(1000).build(KEY).evaluate()
        out = m.forward(jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 1000)

    def test_param_count(self):
        # canonical googlenet (no aux): ~6.6M-7M params
        m = inception.build(1000, has_dropout=False).build(KEY)
        assert 5.5e6 < n_params(m) < 7.5e6

    def test_fused_branches_numerically_identical(self):
        """The reduce-merged layer must be EXACTLY the 4-branch layer
        with the three reduce-conv weights concatenated (ReLU commutes
        with the channel slice)."""
        cfg = ((64,), (96, 128), (16, 32), (32,))
        lu = inception.inception_layer_v1(192, cfg, "3a/")
        lf = inception.inception_layer_v1_fused(192, cfg, "3a/")
        vu = lu.init(KEY)
        vf = lf.init(KEY)
        pu, pf = vu["params"], vf["params"]
        # merged reduce conv = concat of 1x1 / 3x3r / 5x5r over out-chans
        mg = pf["1_Sequential"]["0_3a/reduce_merged/conv1x1"]
        mg["weight"] = jnp.concatenate([
            pu["0_Sequential"]["0_3a/1x1/conv1x1"]["weight"],
            pu["1_Sequential"]["0_Sequential"]["0_3a/3x3r/conv1x1"]["weight"],
            pu["2_Sequential"]["0_Sequential"]["0_3a/5x5r/conv1x1"]["weight"],
        ], axis=3)
        mg["bias"] = jnp.concatenate([
            pu["0_Sequential"]["0_3a/1x1/conv1x1"]["bias"],
            pu["1_Sequential"]["0_Sequential"]["0_3a/3x3r/conv1x1"]["bias"],
            pu["2_Sequential"]["0_Sequential"]["0_3a/5x5r/conv1x1"]["bias"],
        ])
        pf["4_Sequential"]["0_3a/3x3/conv3x3"] = \
            pu["1_Sequential"]["1_Sequential"]["0_3a/3x3/conv3x3"]
        pf["6_Sequential"]["0_3a/5x5/conv5x5"] = \
            pu["2_Sequential"]["1_Sequential"]["0_3a/5x5/conv5x5"]
        pf["7_Sequential"]["1_Sequential"]["0_3a/pool/conv1x1"] = \
            pu["3_Sequential"]["1_Sequential"]["0_3a/pool/conv1x1"]

        x = jax.random.normal(jax.random.PRNGKey(3), (2, 28, 28, 192))
        yu, _ = lu.apply(vu, x, training=False)
        yf, _ = lf.apply(vf, x, training=False)
        # one merged gemm vs three: accumulation order differs at ulp
        np.testing.assert_allclose(np.asarray(yf), np.asarray(yu),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_build_shapes_and_params(self):
        m = inception.build(1000, has_dropout=False,
                            fused_branches=True).build(KEY)
        out = m.evaluate().forward(jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 1000)
        assert 5.5e6 < n_params(m) < 7.5e6  # same params, merged layout


class TestAlexNetVgg:
    def test_alexnet(self):
        m = alexnet.build(1000).build(KEY).evaluate()
        out = m.forward(jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 1000)

    def test_vgg_cifar(self):
        from bigdl_tpu.models import vgg

        m = vgg.build_cifar(10).build(KEY).evaluate()
        out = m.forward(jnp.ones((1, 32, 32, 3)))
        assert out.shape == (1, 10)


class TestAutoencoder:
    def test_reconstruction_shape(self):
        m = autoencoder.build(32).build(KEY).evaluate()
        out = m.forward(jnp.ones((4, 28, 28, 1)))
        assert out.shape == (4, 784)

    def test_trains(self):
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Adam, Optimizer, Trigger

        rng = np.random.RandomState(0)
        imgs = rng.rand(64, 28, 28, 1).astype(np.float32)
        data = [Sample(imgs[i], imgs[i].reshape(-1)) for i in range(64)]
        m = autoencoder.build(32).build(KEY)
        opt = (Optimizer(m, DataSet.array(data), nn.MSECriterion(), batch_size=32)
               .set_optim_method(Adam(1e-3))
               .set_end_when(Trigger.max_iteration(5)))
        opt.log_every = 100
        opt.optimize()


class TestRNNModels:
    def test_simple_rnn_lm(self):
        m = rnn.simple_rnn(vocab_size=50, hidden_size=16).build(KEY).evaluate()
        out = m.forward(jnp.zeros((2, 7), jnp.int32))
        assert out.shape == (2, 7, 50)

    def test_lstm_lm_trains(self):
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Adam, Optimizer, Trigger

        rng = np.random.RandomState(0)
        data = [Sample(rng.randint(0, 20, 9).astype(np.int32),
                       rng.randint(0, 20, 9).astype(np.int32))
                for _ in range(32)]
        m = rnn.lstm_lm(vocab_size=20, embed_dim=16, hidden_size=16).build(KEY)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
        opt = (Optimizer(m, DataSet.array(data), crit, batch_size=16)
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_iteration(4)))
        opt.log_every = 100
        opt.optimize()

    def test_bilstm_sentiment(self):
        m = rnn.bilstm_sentiment(vocab_size=100, embed_dim=8, hidden_size=8,
                                 class_num=2).build(KEY).evaluate()
        out = m.forward(jnp.zeros((3, 12), jnp.int32))
        assert out.shape == (3, 2)
