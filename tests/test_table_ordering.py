"""Regression: Tables with >= 10 entries must keep numeric order through
pytree boundaries and table ops (sort-by-repr would give 1,10,11,2,...)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.utils.table import T, Table

KEY = jax.random.PRNGKey(0)


def test_table_pytree_roundtrip_order():
    t = T(*[jnp.asarray([float(i)]) for i in range(15)])
    leaves, treedef = jax.tree_util.tree_flatten(t)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    for i in range(15):
        assert float(rebuilt[i + 1][0]) == float(i)


def test_table_through_jit():
    t = T(*[jnp.asarray([float(i)]) for i in range(12)])

    @jax.jit
    def f(table):
        return table

    out = f(t)
    for i in range(12):
        assert float(out[i + 1][0]) == float(i)


def test_split_join_roundtrip_long_sequence():
    # SplitTable -> JoinTable over T=12 must not permute timesteps
    x = jnp.arange(24.0).reshape(2, 12)
    m = nn.Sequential(nn.SplitTable(2), nn.JoinTable(1, n_input_dims=0))
    m.build(KEY).evaluate()
    # JoinTable on rank-1 elements along dim 1 -> (2*12,) per element concat;
    # use per-element check via SelectTable instead
    split = nn.SplitTable(2).build(KEY).evaluate()
    table = split.forward(x)
    for i in range(12):
        np.testing.assert_allclose(table[i + 1], x[:, i])
    joined = nn.JoinTable(1, n_input_dims=1).build(KEY).evaluate().forward(
        T(*[table[i + 1][:, None] for i in range(12)]))
    np.testing.assert_allclose(joined, x)
