"""Verified checkpoint integrity (ISSUE 1): per-array crc32 checksums
in the manifest, corruption detection at load, and newest-VALID
fallback — the substitute for the lineage-recovery guarantees the
reference inherited from Spark (arXiv 1804.05839 §4; TensorFlow's
user-level checkpointing contract, arXiv 1605.08695 §4.3)."""

import json
import os

import numpy as np
import pytest

from bigdl_tpu.serialization.checkpoint import (
    Checkpoint, CheckpointCorruptError, load_pytree, save_pytree,
    verify_pytree,
)
from bigdl_tpu.utils.faults import corrupt_file


def _vars(seed):
    rng = np.random.RandomState(seed)
    return {"params": {"w": rng.rand(4, 3).astype(np.float32),
                       "b": rng.rand(3).astype(np.float32)},
            "state": {}}


def _save_steps(path, steps):
    ck = Checkpoint(str(path))
    for s in steps:
        ck.save(s, _vars(s), {"m": np.full((7,), float(s), np.float32)},
                train_state={"neval": s})
    return ck


def _loaded_step(ck, **kw):
    _, optim, ts = ck.load(**kw)
    return ts["neval"]


# ------------------------------------------------- corruption → fallback

def test_truncated_npz_falls_back(tmp_path):
    ck = _save_steps(tmp_path, [3, 6])
    corrupt_file(str(tmp_path / "checkpoint-6" / "model.npz"), "truncate")
    assert _loaded_step(ck) == 3
    assert ck.corrupt_skipped == [str(tmp_path / "checkpoint-6")]
    assert ck._last_loaded == str(tmp_path / "checkpoint-3")


def test_garbled_array_checksum_mismatch_falls_back(tmp_path):
    """Garbling flips bits INSIDE stored arrays without breaking the zip
    container — only the per-array crc32 re-check can catch it."""
    ck = _save_steps(tmp_path, [3, 6])
    corrupt_file(str(tmp_path / "checkpoint-6" / "optim.npz"), "garble")
    assert _loaded_step(ck) == 3
    assert ck.corrupt_skipped


def test_missing_manifest_falls_back(tmp_path):
    ck = _save_steps(tmp_path, [3, 6])
    os.remove(tmp_path / "checkpoint-6" / "optim.json")
    # the dir still carries the COMPLETE marker, so it stays a
    # candidate structurally; load() skips it on the missing manifest
    assert ck.latest() == str(tmp_path / "checkpoint-6")
    assert _loaded_step(ck) == 3


def test_unparseable_manifest_falls_back(tmp_path):
    ck = _save_steps(tmp_path, [3, 6])
    (tmp_path / "checkpoint-6" / "model.json").write_text("{not json")
    assert _loaded_step(ck) == 3


def test_all_candidates_corrupt_raises(tmp_path):
    ck = _save_steps(tmp_path, [3])
    corrupt_file(str(tmp_path / "checkpoint-3" / "model.npz"), "truncate")
    with pytest.raises(CheckpointCorruptError, match="no valid checkpoint"):
        ck.load()


def test_no_checkpoint_raises_filenotfound(tmp_path):
    with pytest.raises(FileNotFoundError):
        Checkpoint(str(tmp_path)).load()


def test_explicit_directory_damage_raises(tmp_path):
    """Asking for a SPECIFIC directory must surface its damage, not
    silently substitute an older checkpoint."""
    ck = _save_steps(tmp_path, [3, 6])
    corrupt_file(str(tmp_path / "checkpoint-6" / "model.npz"), "garble")
    with pytest.raises(CheckpointCorruptError):
        ck.load(directory=str(tmp_path / "checkpoint-6"))


# ------------------------------------------------- torn dirs / latest()

def test_torn_unmarked_dir_skipped_by_latest(tmp_path):
    ck = _save_steps(tmp_path, [3])
    torn = tmp_path / "checkpoint-9"
    torn.mkdir()
    save_pytree(str(torn), "model", _vars(9), metadata={})  # no optim
    assert ck.latest() == str(tmp_path / "checkpoint-3")
    assert _loaded_step(ck) == 3


def test_staging_dir_never_a_candidate(tmp_path):
    ck = _save_steps(tmp_path, [3])
    staging = tmp_path / "checkpoint-9.inprogress"
    staging.mkdir()
    save_pytree(str(staging), "model", _vars(9), metadata={})
    save_pytree(str(staging), "optim", {"m": np.ones(7)}, metadata={})
    assert ck.latest() == str(tmp_path / "checkpoint-3")


def test_latest_allow_unmarked_pinned(tmp_path):
    """Marker-less dir with both manifests: a candidate under the
    default (pre-marker-format compatibility), excluded under
    allow_unmarked=False."""
    ck = _save_steps(tmp_path, [3])
    legacy = tmp_path / "checkpoint-8"
    legacy.mkdir()
    save_pytree(str(legacy), "model", _vars(8),
                metadata={"train_state": {"neval": 8}})
    save_pytree(str(legacy), "optim", {"m": np.ones(7, np.float32)},
                metadata={})
    assert not os.path.exists(legacy / Checkpoint.MARKER)
    assert ck.latest() == str(legacy)
    assert ck.latest(allow_unmarked=False) == str(tmp_path / "checkpoint-3")
    assert _loaded_step(ck) == 8
    assert _loaded_step(ck, allow_unmarked=False) == 3


# ----------------------------------------------- format / unit behavior

def test_pre_checksum_format_loads(tmp_path):
    """Manifests written before format 2 carry no 'checksums' key:
    structural checks only, no verification failure."""
    save_pytree(str(tmp_path), "unit", {"x": np.arange(5.0)})
    mpath = tmp_path / "unit.json"
    manifest = json.loads(mpath.read_text())
    del manifest["checksums"]
    del manifest["format"]
    mpath.write_text(json.dumps(manifest))
    tree, _ = load_pytree(str(tmp_path), "unit")
    np.testing.assert_array_equal(np.asarray(tree["x"]), np.arange(5.0))


def test_verify_pytree_and_verify_flag(tmp_path):
    save_pytree(str(tmp_path), "unit", {"x": np.arange(64.0)})
    verify_pytree(str(tmp_path), "unit")
    corrupt_file(str(tmp_path / "unit.npz"), "garble")
    with pytest.raises(CheckpointCorruptError):
        verify_pytree(str(tmp_path), "unit")


def test_missing_array_detected(tmp_path):
    """An npz missing an array the structure references (partial write
    that still forms a valid zip) is caught by the expected-keys check."""
    save_pytree(str(tmp_path), "unit", {"x": np.arange(3.0),
                                        "y": np.arange(4.0)})
    npz = tmp_path / "unit.npz"
    with np.load(npz) as z:
        kept = {k: z[k] for k in z.files if not k.endswith("y")}
    np.savez(npz, **kept)
    with pytest.raises(CheckpointCorruptError, match="missing arrays"):
        load_pytree(str(tmp_path), "unit")


def test_corrupt_accum_sidecar_dropped_not_fatal(tmp_path):
    ck = Checkpoint(str(tmp_path))
    ck.save(4, _vars(4), {"m": np.ones(7, np.float32)},
            accum_state={"g_acc": np.ones(7, np.float32), "micro_n": 2})
    d = str(tmp_path / "checkpoint-4")
    assert ck.load_accum(d) is not None
    corrupt_file(os.path.join(d, "accum.npz"), "garble")
    assert ck.load_accum(d) is None  # warn + restart cycle, never fail


def test_load_accum_follows_last_loaded_not_latest(tmp_path):
    """After load() fell back past a corrupt newest checkpoint, the
    accumulator must come from the SAME dir that was loaded."""
    ck = Checkpoint(str(tmp_path))
    ck.save(3, _vars(3), {"m": np.ones(7, np.float32)},
            accum_state={"g_acc": np.full(7, 3.0, np.float32),
                         "micro_n": 1})
    ck.save(6, _vars(6), {"m": np.ones(7, np.float32)},
            accum_state={"g_acc": np.full(7, 6.0, np.float32),
                         "micro_n": 2})
    corrupt_file(str(tmp_path / "checkpoint-6" / "model.npz"), "truncate")
    ck.load()
    acc = ck.load_accum()
    assert int(acc["micro_n"]) == 1
    np.testing.assert_array_equal(np.asarray(acc["g_acc"]),
                                  np.full(7, 3.0, np.float32))
