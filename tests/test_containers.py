"""Container and Graph tests (reference: nn/GraphSpec.scala, SequentialSpec)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.utils.table import T

KEY = jax.random.PRNGKey(0)


class TestSequential:
    def test_chained_forward(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2)).build(KEY)
        out = m.evaluate().forward(jnp.ones((3, 4)))
        assert out.shape == (3, 2)

    def test_add_api(self):
        m = nn.Sequential()
        m.add(nn.Linear(4, 4)).add(nn.Tanh())
        assert len(m) == 2
        out = m.build(KEY).evaluate().forward(jnp.ones((1, 4)))
        assert out.shape == (1, 4)

    def test_params_namespaced(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2)).build(KEY)
        names = [n for n, _ in m.parameters()]
        assert len(names) == 4  # 2 weights + 2 biases
        assert len(set(names)) == 4

    def test_get_parameters_flat(self):
        m = nn.Sequential(nn.Linear(2, 3)).build(KEY)
        flat = m.get_parameters()
        assert flat.shape == (2 * 3 + 3,)


class TestConcatContainers:
    def test_concat_table(self):
        m = nn.ConcatTable(nn.Identity(), nn.Identity()).build(KEY)
        out = m.evaluate().forward(jnp.ones(3))
        assert len(out) == 2

    def test_parallel_table(self):
        m = nn.ParallelTable(nn.Linear(2, 3), nn.Linear(4, 5)).build(KEY)
        out = m.evaluate().forward(T(jnp.ones((1, 2)), jnp.ones((1, 4))))
        assert out[1].shape == (1, 3)
        assert out[2].shape == (1, 5)

    def test_concat_dim(self):
        m = nn.Concat(2, nn.Linear(3, 2), nn.Linear(3, 4)).build(KEY)
        out = m.evaluate().forward(jnp.ones((5, 3)))
        assert out.shape == (5, 6)

    def test_residual_block_pattern(self):
        # ConcatTable + CAddTable = residual connection, the reference's
        # ResNet idiom (models/resnet/ResNet.scala)
        block = nn.Sequential(
            nn.ConcatTable(nn.Linear(4, 4), nn.Identity()),
            nn.CAddTable(),
        ).build(KEY)
        out = block.evaluate().forward(jnp.ones((2, 4)))
        assert out.shape == (2, 4)


class TestGraph:
    def test_linear_graph(self):
        x = nn.Input()
        h = nn.Linear(4, 8)(x)
        r = nn.ReLU()(h)
        y = nn.Linear(8, 2)(r)
        g = nn.Graph(x, y).build(KEY)
        out = g.evaluate().forward(jnp.ones((3, 4)))
        assert out.shape == (3, 2)

    def test_diamond_graph(self):
        x = nn.Input()
        a = nn.Linear(4, 4)(x)
        b1 = nn.ReLU()(a)
        b2 = nn.Tanh()(a)
        merged = nn.CAddTable()(b1, b2)
        g = nn.Graph(x, merged).build(KEY)
        out = g.evaluate().forward(jnp.ones((2, 4)))
        assert out.shape == (2, 4)

    def test_multi_input_output(self):
        x1, x2 = nn.Input(), nn.Input()
        h1 = nn.Linear(2, 3)(x1)
        h2 = nn.Linear(2, 3)(x2)
        s = nn.CAddTable()(h1, h2)
        g = nn.Graph([x1, x2], [s, h1]).build(KEY)
        out = g.evaluate().forward(jnp.ones((1, 2)), jnp.ones((1, 2)))
        assert len(out) == 2
        assert out[1].shape == (1, 3)

    def test_shared_stateful_module_composes_state(self):
        # a module object used at two graph nodes shares weights AND
        # must COMPOSE running-stat updates: the second application
        # starts from the first's new state (not overwrite it)
        bn = nn.BatchNormalization(4, momentum=0.1)
        x = nn.Input()
        h = bn(x)
        y = bn(nn.ReLU()(h))
        g = nn.Graph(x, y).build(KEY)
        xv = jnp.arange(12.0).reshape(3, 4)
        _, new_state = g.apply(g.variables, xv, training=True)
        key = [k for k in new_state if new_state[k]][0]
        got = np.asarray(new_state[key]["running_mean"])

        # oracle: two sequential EMA updates through the same bn
        v1 = {"params": g.variables["params"][key], "state": bn.init_state()}
        o1, s1 = bn.apply(v1, xv, training=True)
        _, s2 = bn.apply({"params": v1["params"], "state": s1},
                         jnp.maximum(o1, 0.0), training=True)
        np.testing.assert_allclose(got, np.asarray(s2["running_mean"]),
                                   rtol=1e-6, atol=1e-6)

    def test_grad_through_graph(self):
        x = nn.Input()
        y = nn.Linear(3, 1)(nn.Tanh()(nn.Linear(3, 3)(x)))
        g = nn.Graph(x, y)
        variables = g.init(KEY)

        def loss(params):
            out, _ = g.apply({"params": params, "state": variables["state"]},
                             jnp.ones((4, 3)))
            return jnp.sum(out)

        grads = jax.grad(loss)(variables["params"])
        total = sum(float(np.abs(np.asarray(l)).sum())
                    for l in jax.tree_util.tree_leaves(grads))
        assert total > 0

    def test_jit_apply(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.ReLU()).build(KEY)

        @jax.jit
        def f(variables, x):
            return m.apply(variables, x)[0]

        out = f(m.variables, jnp.ones((2, 4)))
        assert out.shape == (2, 4)


class TestModuleEvaluatePredict:
    """AbstractModule.evaluate(dataset, methods) / predict parity."""

    def _fixture(self):
        import numpy as np
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, Sample

        m = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
        m.build(jax.random.PRNGKey(0)).evaluate()
        rng = np.random.RandomState(0)
        samples = [Sample(rng.rand(4).astype(np.float32),
                          np.int32(rng.randint(3))) for _ in range(10)]
        return m, DataSet.array(samples)

    def test_evaluate_overload(self):
        from bigdl_tpu.optim import Top1Accuracy

        m, ds = self._fixture()
        res = m.evaluate(ds, [Top1Accuracy()], batch_size=4)
        (name, r), = res.items()
        assert name == "Top1Accuracy"
        assert 0.0 <= r.result()[0] <= 1.0
        # no-arg overload still mode-switches
        assert m.evaluate() is m

    def test_predict_and_predict_class(self):
        import numpy as np

        m, ds = self._fixture()
        out = m.predict(ds, batch_size=4)
        assert out.shape == (10, 3)
        cls = m.predict_class(ds, batch_size=4)
        assert cls.shape == (10,)
        np.testing.assert_array_equal(cls, np.argmax(out, axis=1))
