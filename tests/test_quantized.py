"""INT8 quantized inference vs the float models."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.quantized import (
    QuantizedLinear, QuantizedSpatialConvolution, quantize)


def test_quantized_linear_close_to_float():
    lin = nn.Linear(32, 16, name="fc")
    variables = lin.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32))
    ref, _ = lin.apply(variables, x)

    qlin, qvars = QuantizedLinear.from_float(lin, variables)
    out, _ = qlin.apply(qvars, x)
    # int8 symmetric quantization: ~1% relative error on these activations
    err = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert err < 0.05, err
    assert qvars["params"]["qweight"].dtype == jnp.int8


def test_quantized_conv_close_to_float():
    conv = nn.SpatialConvolution(3, 8, 3, pad_w=1, pad_h=1, name="c1")
    variables = conv.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    ref, _ = conv.apply(variables, x)
    qconv, qvars = QuantizedSpatialConvolution.from_float(conv, variables)
    out, _ = qconv.apply(qvars, x)
    err = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert err < 0.05, err


def test_quantize_whole_model_keeps_predictions():
    # train-free check: same argmax on most inputs after quantization
    from bigdl_tpu.models import lenet

    model = lenet.build(10)
    variables = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 28, 28, 1))
    ref, _ = model.apply(variables, x)

    qmodel, qvars = quantize(model, variables)
    out, _ = qmodel.apply(qvars, x)
    agree = float(np.mean(np.asarray(ref).argmax(-1) ==
                          np.asarray(out).argmax(-1)))
    assert agree > 0.9, agree
    # pytree keys preserved so checkpointing stays compatible
    assert set(qvars["params"].keys()) == set(variables["params"].keys())
    # weights really are int8 underneath
    leaves = jax.tree_util.tree_leaves(qvars["params"])
    assert any(l.dtype == jnp.int8 for l in leaves)


def test_quantized_model_size_shrinks():
    from bigdl_tpu.models import lenet

    model = lenet.build(10)
    variables = model.init(jax.random.PRNGKey(0))
    qmodel, qvars = quantize(model, variables)

    def nbytes(tree):
        return sum(l.size * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(tree))

    assert nbytes(qvars["params"]) < 0.35 * nbytes(variables["params"])
