"""Cross-layout checkpoint resume: Local (pytree slots) <-> Distri
(ZeRO-1 flat slots) in both directions, and across mesh sizes."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import Optimizer, SGD, Trigger
from bigdl_tpu.parallel import make_mesh
from bigdl_tpu.serialization.checkpoint import Checkpoint

KEY = jax.random.PRNGKey(0)


def _train(model, mesh, path, end_iter, resume=False, n_data=128):
    opt = (Optimizer(model, DataSet.array(synthetic_mnist(n_data)),
                     nn.ClassNLLCriterion(), batch_size=64)
           .set_optim_method(SGD(learningrate=0.05, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_iteration(end_iter))
           .set_checkpoint(str(path), Trigger.several_iteration(2)))
    if mesh is not None:
        opt.set_mesh(mesh)
    if resume:
        opt.resume_from_checkpoint()
    opt.log_every = 100
    return opt.optimize()


def test_distri_to_local_resume(tmp_path):
    mesh = make_mesh({"data": 8})
    _train(lenet.build(10).build(KEY), mesh, tmp_path, 4)
    # resume the distri checkpoint in a LOCAL optimizer
    _train(lenet.build(10).build(jax.random.PRNGKey(1)), None, tmp_path, 8,
           resume=True)
    _, _, ts = Checkpoint(str(tmp_path)).load()
    assert ts["neval"] == 8


def test_local_to_distri_resume(tmp_path):
    _train(lenet.build(10).build(KEY), None, tmp_path, 4)
    mesh = make_mesh({"data": 8})
    _train(lenet.build(10).build(jax.random.PRNGKey(1)), mesh, tmp_path, 8,
           resume=True)
    _, _, ts = Checkpoint(str(tmp_path)).load()
    assert ts["neval"] == 8


def test_distri_mesh_size_change(tmp_path):
    _train(lenet.build(10).build(KEY), make_mesh({"data": 8}), tmp_path, 4)
    # resume on a 4-device mesh (different padded size)
    mesh4 = make_mesh({"data": 4}, devices=jax.devices()[:4])
    _train(lenet.build(10).build(jax.random.PRNGKey(1)), mesh4, tmp_path, 8,
           resume=True)
    _, _, ts = Checkpoint(str(tmp_path)).load()
    assert ts["neval"] == 8
