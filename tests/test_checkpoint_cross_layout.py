"""Cross-layout checkpoint resume: Local (pytree slots) <-> Distri
(ZeRO-1 flat slots) in both directions, and across mesh sizes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import Optimizer, SGD, Trigger
from bigdl_tpu.parallel import make_mesh
from bigdl_tpu.serialization.checkpoint import Checkpoint

KEY = jax.random.PRNGKey(0)


def _train(model, mesh, path, end_iter, resume=False, n_data=128):
    opt = (Optimizer(model, DataSet.array(synthetic_mnist(n_data)),
                     nn.ClassNLLCriterion(), batch_size=64)
           .set_optim_method(SGD(learningrate=0.05, momentum=0.9, dampening=0.0))
           .set_end_when(Trigger.max_iteration(end_iter))
           .set_checkpoint(str(path), Trigger.several_iteration(2)))
    if mesh is not None:
        opt.set_mesh(mesh)
    if resume:
        opt.resume_from_checkpoint()
    opt.log_every = 100
    return opt.optimize()


def test_distri_to_local_resume(tmp_path):
    mesh = make_mesh({"data": 8})
    _train(lenet.build(10).build(KEY), mesh, tmp_path, 4)
    # resume the distri checkpoint in a LOCAL optimizer
    _train(lenet.build(10).build(jax.random.PRNGKey(1)), None, tmp_path, 8,
           resume=True)
    _, _, ts = Checkpoint(str(tmp_path)).load()
    assert ts["neval"] == 8


def test_local_to_distri_resume(tmp_path):
    _train(lenet.build(10).build(KEY), None, tmp_path, 4)
    mesh = make_mesh({"data": 8})
    _train(lenet.build(10).build(jax.random.PRNGKey(1)), mesh, tmp_path, 8,
           resume=True)
    _, _, ts = Checkpoint(str(tmp_path)).load()
    assert ts["neval"] == 8


def test_distri_mesh_size_change(tmp_path):
    _train(lenet.build(10).build(KEY), make_mesh({"data": 8}), tmp_path, 4)
    # resume on a 4-device mesh (different padded size)
    mesh4 = make_mesh({"data": 4}, devices=jax.devices()[:4])
    _train(lenet.build(10).build(jax.random.PRNGKey(1)), mesh4, tmp_path, 8,
           resume=True)
    _, _, ts = Checkpoint(str(tmp_path)).load()
    assert ts["neval"] == 8


class TestAtomicPublish:
    """save() publishes via staging dir + rename: a crash anywhere
    mid-save leaves the previous checkpoint untouched and loadable
    (ADVICE r3 stale-marker hazard + review r4 no-loadable window)."""

    def _save(self, ck, step, value):
        ck.save(step, {"params": {"w": np.full(3, value, np.float32)},
                       "state": {}}, {"slots": {}})

    def test_crash_mid_overwrite_keeps_previous(self, tmp_path, monkeypatch):
        import os

        from bigdl_tpu.serialization import checkpoint as C

        ck = Checkpoint(str(tmp_path))
        self._save(ck, 1, 1.0)
        d = os.path.join(str(tmp_path), "checkpoint-1")
        assert os.path.exists(os.path.join(d, "COMPLETE"))

        orig = C.save_pytree

        def boom(*a, **k):
            raise RuntimeError("simulated crash mid-save")

        monkeypatch.setattr(C, "save_pytree", boom)
        with pytest.raises(RuntimeError):
            ck.save(1, {"params": {}, "state": {}}, {})
        monkeypatch.setattr(C, "save_pytree", orig)
        # the old checkpoint survived the crashed overwrite intact
        assert ck.latest() == d
        vars1, _, _ = ck.load()
        np.testing.assert_array_equal(vars1["params"]["w"],
                                      np.full(3, 1.0, np.float32))
        # and a subsequent good save replaces it atomically
        self._save(ck, 1, 2.0)
        vars2, _, _ = ck.load()
        np.testing.assert_array_equal(vars2["params"]["w"],
                                      np.full(3, 2.0, np.float32))
        assert not os.path.isdir(d + ".inprogress")

    def test_inprogress_dir_never_matches_latest(self, tmp_path):
        import os

        ck = Checkpoint(str(tmp_path))
        os.makedirs(os.path.join(str(tmp_path),
                                 "checkpoint-9.inprogress"))
        assert ck.latest() is None

    def test_unmarked_legacy_dir_accepted_unless_strict(self, tmp_path):
        import os

        ck = Checkpoint(str(tmp_path))
        self._save(ck, 3, 1.0)
        os.remove(os.path.join(str(tmp_path), "checkpoint-3", "COMPLETE"))
        # pre-marker-era checkpoints (both manifests) remain resumable
        assert ck.latest() is not None
        # strict mode trusts only marked dirs
        assert ck.latest(allow_unmarked=False) is None
