"""Quantized serving layout (ISSUE 17): the serving/quant.py int8
repack (structure preservation, dequant error bound, bytes win), the
int8-weight/bf16-KV engine end to end under the TOLERANCE contract
(lossy by design — the fp32 bitwise pins stay fp32-scoped and are
re-run untouched by test_kv_pool/test_tp_serving/test_speculative),
per-engine constructor gating (layout and attn_impl are ctor args,
never env; tp engines refuse both — their pins are bitwise), the
#buckets+1 compile contract re-run with quant + attn_impl armed, and
the router refusing cross-layout-family failover."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import obs
from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.serving import EngineRouter, InferenceEngine, Request
from bigdl_tpu.serving.quant import (QuantWeight, params_bytes,
                                     quantize_serving_params)
from bigdl_tpu.utils import faults

_LM = None


def _lm():
    global _LM
    if _LM is None:
        _LM = build_lm(vocab_size=61, dim=32, num_heads=2, num_layers=2,
                       max_len=64)
        _LM.build(jax.random.PRNGKey(0))
    return _LM


def _engine(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("block_size", 4)
    return InferenceEngine(_lm(), **kw)


def _quant_kw():
    return dict(weight_dtype="int8", cache_dtype=jnp.bfloat16)


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


class TestRepack:
    def test_structure_and_leaf_types(self):
        model = _lm()
        sp = model.serving_params(model.variables)
        qp = quantize_serving_params(sp)
        assert isinstance(qp["embed"], QuantWeight)
        assert qp["embed"].q.dtype == jnp.int8
        # per-ROW embed scales: one per vocab row (gather-then-scale)
        assert qp["embed"].scale.shape == (61, 1)
        for bp, qbp in zip(sp["blocks"], qp["blocks"]):
            for k in ("wq", "wk", "wv", "wo", "w1", "w2"):
                assert isinstance(qbp[k], QuantWeight)
                assert qbp[k].shape == bp[k].shape
            for k in bp:
                if not isinstance(qbp[k], QuantWeight):
                    assert qbp[k] is bp[k]  # biases/LN pass through

    def test_dequant_error_bound(self):
        model = _lm()
        sp = model.serving_params(model.variables)
        qp = quantize_serving_params(sp)
        w = sp["blocks"][0]["wq"]
        dq = qp["blocks"][0]["wq"].deq()
        # symmetric per-channel: |err| <= scale/2 = max|w|/254
        bound = float(jnp.abs(w).max()) / 254 + 1e-7
        assert float(jnp.abs(dq - w).max()) <= bound

    def test_requires_serving_layout(self):
        model = _lm()
        with pytest.raises(ValueError, match="serving"):
            quantize_serving_params(model.variables["params"])

    def test_bytes_win(self):
        model = _lm()
        sp = model.serving_params(model.variables)
        ratio = params_bytes(sp) / params_bytes(
            quantize_serving_params(sp))
        assert ratio >= 2.5  # ~4x on gemms, diluted by fp32 scales


class TestQuantEngine:
    def _run(self, **kw):
        eng = _engine(**kw)
        res = eng.run([Request(id=i, prompt=[3 + i, 7, 11 + i],
                               max_new_tokens=6) for i in range(4)])
        return eng, {r.id: r.tokens for r in res}

    def test_tolerance_contract_vs_fp32(self):
        _, ref = self._run()
        eng, toks = self._run(**_quant_kw())
        assert set(toks) == set(ref)
        assert all(len(toks[i]) == len(ref[i]) for i in ref)
        # the documented contract (lmdecode_quant row): first-token
        # agreement (pure function of the prompt) on most requests,
        # agreed-prefix fraction well above noise
        first = sum(toks[i][0] == ref[i][0] for i in ref)
        assert first >= len(ref) - 1
        agreed = horizon = 0
        for i in ref:
            for a, b in zip(ref[i], toks[i]):
                if a != b:
                    break
                agreed += 1
            horizon += len(ref[i])
        assert agreed / horizon >= 0.25

    def test_health_and_layout_family(self):
        eng, _ = self._run(**_quant_kw())
        h = eng.health()
        assert h["weight_dtype"] == "int8"
        assert h["cache_dtype"] == "bfloat16"
        assert h["attn_impl"] == "xla"
        assert eng.layout_family == "int8/bfloat16"
        assert _engine().layout_family == "fp32/float32"

    def test_pool_bytes_gauge_reflects_cache_dtype(self):
        def gauge(eng):
            key = (f"serving_kv_pool_bytes{{engine={eng.obs_name},"
                   f"tp=1}}")
            return obs.provenance("serving_kv_pool_bytes")[
                "metrics"][key]

        # 7-token prompts (inside the 8 bucket) so the radix tree
        # RETAINS a block after the run ((7-1)//4 = 1 reusable block
        # per chain) — the gauge reports retained + live pool bytes
        prompt = [3, 7, 11, 13, 2, 5, 8]
        e32 = _engine()
        eq = _engine(**_quant_kw())
        for eng in (e32, eq):
            eng.run([Request(id=i, prompt=list(prompt),
                             max_new_tokens=4) for i in range(2)])
        # same retained block count, half the bytes per block (bf16)
        b32, bq = gauge(e32), gauge(eq)
        assert b32 > 0 and bq > 0
        assert bq * 2 == b32

    def test_compile_contract_with_quant_armed(self):
        from bigdl_tpu.serving.engine import _TRACES

        model = build_lm(vocab_size=53, dim=32, num_heads=2,
                         num_layers=2, max_len=32)
        model.build(jax.random.PRNGKey(1))

        def engine():
            return InferenceEngine(model, slots=2, max_len=32,
                                   prefill_buckets=(4, 8),
                                   block_size=4, **_quant_kw())

        # prompts hitting BOTH buckets (len 3 -> 4, len 6 -> 8)
        reqs = lambda: [Request(id=i, prompt=[2 + i, 5, 9] if i == 0
                                else [2 + i, 5, 9, 4, 6, 8],
                                max_new_tokens=4) for i in range(3)]
        before = dict(_TRACES)
        engine().run(reqs())
        # the quant layout is its own executable family: #buckets + 1
        assert _TRACES["prefill"] == before["prefill"] + 2
        assert _TRACES["decode"] == before["decode"] + 1
        # pool growth over the same model compiles NOTHING more
        mid = dict(_TRACES)
        engine().run(reqs())
        assert dict(_TRACES) == mid


class TestGating:
    def test_ctor_rejects_unknown_layout(self):
        with pytest.raises(ValueError, match="weight_dtype"):
            _engine(weight_dtype="fp16")
        with pytest.raises(ValueError, match="attn_impl"):
            _engine(attn_impl="mosaic")

    def test_tp_mesh_refuses_lossy_and_kernel(self):
        # a 1-device mesh exercises the guard without multi-device
        # XLA flags: the refusal is about the LAYOUT, not the degree
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("model",))
        with pytest.raises(ValueError, match="tp"):
            _engine(tp_mesh=mesh, weight_dtype="int8")
        with pytest.raises(ValueError, match="tp"):
            _engine(tp_mesh=mesh, attn_impl="interpret")

    def test_router_refuses_cross_family_failover(self):
        """An fp32 engine dies mid-decode with only an int8 survivor:
        the router must NOT reroute (the survivor's tokens are not the
        ones the dead engine would have produced) — requests fail, the
        loss is counted, and nothing lands on the quant engine."""
        e0 = _engine(step_timeout_s=0.05)
        eq = _engine(**_quant_kw())
        router = EngineRouter([e0, eq])
        faults.set_plan(faults.FaultPlan("serve_slow@1"))
        try:
            out = router.run([Request(prompt=[1, 2, 3],
                                      max_new_tokens=4, seed=1)])
        finally:
            faults.set_plan(None)
        assert e0.degraded is not None
        assert [r.status for r in out] == ["failed"]
        assert router.stats["failover_lost"] == 1
        assert router.stats["failover"] == 0
        assert eq.stats["requests_done"] == 0

    def test_router_failover_within_family_still_works(self):
        e0 = _engine(step_timeout_s=0.05)
        e1 = _engine()
        router = EngineRouter([e0, e1])
        faults.set_plan(faults.FaultPlan("serve_slow@1"))
        try:
            out = router.run([Request(prompt=[1, 2, 3],
                                      max_new_tokens=4, seed=1)])
        finally:
            faults.set_plan(None)
        assert e0.degraded is not None
        assert [r.status for r in out] == ["done"]
        assert router.stats["failover"] == 1
