"""The param-layout spine (ISSUE 18 tentpole (c)) + the flywheel's
compile/adaptation contracts.

Four layout consumers used to hand-roll the same flatten/pad/shard/
unstack algebra; `parallel/param_layout.py` now owns it once, and the
original call sites delegate. Each rerouted path is pinned here
against its pre-refactor form, hand-rolled in numpy:

* ZeRO slices — `FlatParamSpec` flatten/unflatten round-trips bitwise
  and `shard_slice` produces disjoint slices that cover the padded
  vector exactly (the construction behind the zero2==zero1 pin);
* checkpoint reshard — `repad_flat`/`adapt_flat_tree` convert a saved
  world size's layout into this run's, and `concat_shard_trees` is the
  bitwise load-side inverse of slicing;
* serving repack — `unstack_blocks`/`map_block_leaves` reproduce the
  stacked-(L, ...)-to-per-layer walk `TransformerLM.serving_params`
  runs, leaf-for-leaf bitwise;
* tp gather/shard — `tp_serving_block_specs`/`tp_serving_specs` emit
  the exact column/replicated placement table `serving/tp.py` serves
  under, and `gather_tree` round-trips to host bitwise.

Also pinned: draft hot-swap is COMPILE-FREE (the engine `_TRACES`
census stays flat across `swap_params`, a same-weights swap is
token-invisible, and layout/shape mismatches are refused — never
silently retraced), and the adaptive-k ladder's hysteresis (raise /
hold / lower / collapse-to-suspend / probe-resume transitions,
threshold validation, swap-record accept_before/after settling)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.parallel.param_layout import (
    TP_COL, TP_COL_BIAS, FlatParamSpec, adapt_flat_tree,
    concat_shard_trees, gather_tree, map_block_leaves, repad_flat,
    tp_serving_block_specs, tp_serving_specs, unstack_blocks)
from bigdl_tpu.serving import InferenceEngine, Request, SpeculativeEngine


def _tree(seed=0):
    """A small mixed-shape params pytree (total size NOT a multiple of
    the shard counts below, so padding is actually exercised)."""
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(3, 5), jnp.float32),
            "b": jnp.asarray(rng.randn(7), jnp.float32),
            "nested": {"g": jnp.asarray(rng.randn(2, 2, 2),
                                        jnp.float32)}}


# ------------------------------------------------------------ zero slices

class TestFlatSpec:
    def test_flatten_matches_handrolled(self):
        tree = _tree()
        spec = FlatParamSpec(tree, num_shards=4)
        flat = np.asarray(spec.flatten(tree))
        # pre-refactor form: ravel leaves in tree order, concat, pad
        leaves = [np.asarray(l).ravel()
                  for l in jax.tree_util.tree_leaves(tree)]
        ref = np.concatenate(leaves)
        assert spec.total == ref.size
        assert spec.padded == ((ref.size + 3) // 4) * 4
        assert spec.padded % 4 == 0 and spec.padded >= ref.size
        np.testing.assert_array_equal(flat[:spec.total], ref)
        np.testing.assert_array_equal(flat[spec.total:], 0.0)

    def test_unflatten_roundtrip_bitwise(self):
        tree = _tree()
        spec = FlatParamSpec(tree, num_shards=3)
        out = spec.unflatten(spec.flatten(tree))
        assert jax.tree_util.tree_structure(out) \
            == jax.tree_util.tree_structure(tree)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(tree)):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_shard_slices_disjoint_cover(self):
        tree = _tree()
        spec = FlatParamSpec(tree, num_shards=4)
        flat = spec.flatten(tree)
        slices = [np.asarray(spec.shard_slice(flat, i))
                  for i in range(4)]
        assert all(s.size == spec.shard_size for s in slices)
        # disjoint cover: concatenating the shards IS the flat vector
        # — the all_gather-of-slices == replicated-vector construction
        np.testing.assert_array_equal(np.concatenate(slices),
                                      np.asarray(flat))


# ------------------------------------------------------- ckpt reshard

class TestReshard:
    def test_repad_across_world_sizes(self):
        tree = _tree()
        old = FlatParamSpec(tree, num_shards=8)
        new = FlatParamSpec(tree, num_shards=3)
        flat8 = old.flatten(tree)
        flat3 = repad_flat(flat8, old.total, new.padded)
        assert flat3.shape == (new.padded,)
        # real parameters survive bitwise; only padding moved
        np.testing.assert_array_equal(np.asarray(flat3),
                                      np.asarray(new.flatten(tree)))

    def test_adapt_flat_tree_same_layout_passthrough(self):
        tree = _tree()
        spec = FlatParamSpec(tree, num_shards=4)
        slots = {"m": spec.flatten(tree)}
        meta = {"layout": "zero2_flat", "padded": spec.padded,
                "total": spec.total}
        assert adapt_flat_tree(slots, meta, spec) is slots

    def test_adapt_flat_tree_resharded(self):
        tree = _tree()
        old = FlatParamSpec(tree, num_shards=8)
        new = FlatParamSpec(tree, num_shards=3)
        slots = {"m": old.flatten(tree), "v": old.flatten(tree)}
        meta = {"layout": "zero1_flat", "padded": old.padded,
                "total": old.total}
        out = adapt_flat_tree(slots, meta, new)
        for k in slots:
            np.testing.assert_array_equal(
                np.asarray(out[k]), np.asarray(new.flatten(tree)))

    def test_adapt_flat_tree_local_pytree(self):
        tree = _tree()
        spec = FlatParamSpec(tree, num_shards=2)
        slots = {"m": tree}           # LocalOptimizer pytree-per-slot
        out = adapt_flat_tree(slots, {}, spec)
        np.testing.assert_array_equal(np.asarray(out["m"]),
                                      np.asarray(spec.flatten(tree)))

    def test_concat_shards_inverts_slicing(self):
        tree = _tree()
        spec = FlatParamSpec(tree, num_shards=4)
        flat = spec.flatten(tree)
        parts = [{"m": np.asarray(spec.shard_slice(flat, i))}
                 for i in range(4)]
        out = concat_shard_trees(parts)
        np.testing.assert_array_equal(out["m"], np.asarray(flat))


# ------------------------------------------------------ serving repack

class TestServingRepack:
    def test_unstack_matches_handrolled(self):
        rng = np.random.RandomState(1)
        stacked = {"embed": jnp.asarray(rng.randn(5, 4), jnp.float32),
                   "blocks": {"wq": jnp.asarray(rng.randn(3, 4, 4),
                                                jnp.float32),
                              "bq": jnp.asarray(rng.randn(3, 4),
                                                jnp.float32)}}
        blocks = unstack_blocks(stacked, num_layers=3)
        assert isinstance(blocks, tuple) and len(blocks) == 3
        for l in range(3):
            # pre-refactor form: index the stack's leading dim
            np.testing.assert_array_equal(
                np.asarray(blocks[l]["wq"]),
                np.asarray(stacked["blocks"]["wq"])[l])
            np.testing.assert_array_equal(
                np.asarray(blocks[l]["bq"]),
                np.asarray(stacked["blocks"]["bq"])[l])
        # per-layer layouts pass through untouched
        assert unstack_blocks({"blocks": blocks}, 3) == blocks

    def test_model_serving_params_routes_through_spine(self):
        model = build_lm(vocab_size=20, dim=8, num_heads=2,
                         num_layers=2, max_len=16)
        model.build(jax.random.PRNGKey(3))
        p = model.variables["params"]
        sp = model.serving_params(model.variables)
        assert isinstance(sp["blocks"], tuple) \
            and len(sp["blocks"]) == 2
        manual = unstack_blocks(p, 2)
        for got, ref in zip(sp["blocks"], manual):
            assert set(got) == set(ref)
            for k in got:
                np.testing.assert_array_equal(np.asarray(got[k]),
                                              np.asarray(ref[k]))

    def test_map_block_leaves(self):
        model = build_lm(vocab_size=20, dim=8, num_heads=2,
                         num_layers=2, max_len=16)
        model.build(jax.random.PRNGKey(3))
        sp = model.serving_params(model.variables)
        seen = []
        out = map_block_leaves(sp, lambda k, v: (seen.append(k), v)[1])
        # identity walk rebuilds the tree bitwise; top-level entries
        # pass through as the same objects
        for k in sp:
            if k != "blocks":
                assert out[k] is sp[k]
        for got, ref in zip(out["blocks"], sp["blocks"]):
            for k in got:
                np.testing.assert_array_equal(np.asarray(got[k]),
                                              np.asarray(ref[k]))
        assert len(seen) == sum(len(b) for b in sp["blocks"])

    def test_map_block_leaves_refuses_stacked(self):
        with pytest.raises(ValueError, match="per-layer serving"):
            map_block_leaves({"blocks": {"wq": jnp.zeros((2, 3))}},
                             lambda k, v: v)


# ------------------------------------------------------------- tp spec

class TestTpSpecs:
    def test_block_spec_table(self):
        from jax.sharding import PartitionSpec as P

        spec = tp_serving_block_specs("model")
        for k in TP_COL:
            assert spec[k] == P(None, "model"), k
        for k in TP_COL_BIAS:
            assert spec[k] == P("model"), k
        for k in ("wo", "bo", "w2", "b2", "ln1_g", "ln1_b", "ln2_g",
                  "ln2_b"):
            assert spec[k] == P(), k

    def test_tree_specs_match_serving_layout(self):
        from jax.sharding import PartitionSpec as P

        model = build_lm(vocab_size=20, dim=8, num_heads=2,
                         num_layers=2, max_len=16)
        model.build(jax.random.PRNGKey(3))
        sp = model.serving_params(model.variables)
        specs = tp_serving_specs(sp, "model")
        assert len(specs["blocks"]) == len(sp["blocks"])
        for k in sp:
            if k != "blocks":
                assert specs[k] == P()
        # the spec pytree must cover the param pytree leaf-for-leaf
        for bp, bs in zip(sp["blocks"], specs["blocks"]):
            assert set(bp) <= set(bs)

    def test_gather_tree_roundtrip_bitwise(self):
        tree = _tree(seed=2)
        host = gather_tree(tree)
        for a, b in zip(jax.tree_util.tree_leaves(host),
                        jax.tree_util.tree_leaves(tree)):
            assert isinstance(a, np.ndarray)
            np.testing.assert_array_equal(a, np.asarray(b))


# --------------------------------------------------- hot-swap contract

_LM = None


def _lm():
    global _LM
    if _LM is None:
        _LM = build_lm(vocab_size=50, dim=16, num_heads=2,
                       num_layers=1, max_len=64)
        _LM.build(jax.random.PRNGKey(1))
    return _LM


class TestHotSwap:
    def test_swap_is_compile_free_and_token_invisible(self):
        from bigdl_tpu.serving.engine import _TRACES

        eng = InferenceEngine(_lm(), slots=2, prefill_buckets=(8,))
        reqs = lambda: [Request(prompt=[1, 2, 3], max_new_tokens=4),
                        Request(prompt=[4, 5], max_new_tokens=4)]
        ref = eng.run(reqs())
        t0 = dict(_TRACES)
        # same weights, fresh buffers: the swap must be invisible
        copy = jax.tree_util.tree_map(jnp.array, _lm().variables)
        eng.swap_params(copy)
        assert eng.stats["weight_swaps"] == 1
        got = eng.run(reqs())
        assert [g.tokens for g in got] == [r.tokens for r in ref]
        assert dict(_TRACES) == t0, "hot-swap must compile nothing"

    def test_swap_new_weights_changes_tokens_not_executables(self):
        from bigdl_tpu.serving.engine import _TRACES

        eng = InferenceEngine(_lm(), slots=2, prefill_buckets=(8,))
        reqs = lambda: [Request(prompt=[7, 8, 9], max_new_tokens=6)]
        ref = eng.run(reqs())
        other = build_lm(vocab_size=50, dim=16, num_heads=2,
                         num_layers=1, max_len=64)
        other.build(jax.random.PRNGKey(9))
        t0 = dict(_TRACES)
        eng.swap_params(other.variables)
        got = eng.run(reqs())
        assert [g.tokens for g in got] != [r.tokens for r in ref], \
            "different weights must actually serve"
        assert dict(_TRACES) == t0, "hot-swap must compile nothing"

    def test_swap_refuses_different_config(self):
        eng = InferenceEngine(_lm(), slots=2, prefill_buckets=(8,))
        wide = build_lm(vocab_size=50, dim=32, num_heads=2,
                        num_layers=1, max_len=64)
        wide.build(jax.random.PRNGKey(2))
        with pytest.raises(ValueError, match="hot-swap|shapes"):
            eng.swap_params(wide.variables)


# ------------------------------------------------------ adaptive ladder

_TGT = None


def _tgt_lm():
    global _TGT
    if _TGT is None:
        _TGT = build_lm(vocab_size=50, dim=16, num_heads=2,
                        num_layers=1, max_len=64)
        _TGT.build(jax.random.PRNGKey(0))
    return _TGT


def _spec(**kw):
    d = InferenceEngine(_lm(), slots=2, prefill_buckets=(8,))
    t = InferenceEngine(_tgt_lm(), slots=2, prefill_buckets=(8,))
    kw.setdefault("k", 4)
    return SpeculativeEngine(d, t, **kw)


class TestAdaptiveLadder:
    """The hysteresis ladder is host arithmetic over the accept
    window; drive `_evaluate_k` directly with planted window
    observations — no decode required."""

    @staticmethod
    def _ev(eng, *vals):
        for v in vals:
            eng._m_accept_frac.observe(v)
        eng._evaluate_k()

    def test_ladder_transitions(self):
        eng = _spec(adapt_k=True, adapt_window=2, raise_at=0.6,
                    lower_at=0.3, collapse_at=0.1)
        assert eng.k_live == 4                  # starts at the ceiling
        self._ev(eng, 0.2, 0.2)                 # below lower_at: -1
        assert eng.k_live == 3 and not eng._suspended
        self._ev(eng, 0.4, 0.5)                 # hysteresis band: hold
        assert eng.k_live == 3
        self._ev(eng, 0.9, 0.8)                 # >= raise_at: +1
        assert eng.k_live == 4
        self._ev(eng, 0.9, 0.9)                 # ceiling caps at k
        assert eng.k_live == 4
        self._ev(eng, 0.05, 0.0)                # collapse: floor+suspend
        assert eng.k_live == 1 and eng._suspended
        self._ev(eng, 0.3)                      # probe below the bar
        assert eng._suspended
        self._ev(eng, 0.8)                      # probe clears: resume
        assert not eng._suspended and eng.k_live == 1
        self._ev(eng, 0.9, 0.9)                 # climbs off the floor
        assert eng.k_live == 2
        assert eng.health()["speculative"]["k_adjusts"] == 8

    def test_empty_window_holds(self):
        eng = _spec(adapt_k=True, adapt_window=2)
        eng._evaluate_k()                       # no observations
        assert eng.k_live == 4
        assert eng.health()["speculative"]["k_adjusts"] == 0

    def test_floor_respects_k_min(self):
        eng = _spec(adapt_k=True, adapt_window=1, k_min=2,
                    raise_at=0.6, lower_at=0.3, collapse_at=0.1)
        for _ in range(5):
            self._ev(eng, 0.2)                  # lower repeatedly
        assert eng.k_live == 2                  # never below k_min
        self._ev(eng, 0.0)                      # collapse → k_min
        assert eng.k_live == 2 and eng._suspended

    def test_threshold_validation(self):
        with pytest.raises(ValueError, match="k_min"):
            _spec(k=3, k_min=4)
        with pytest.raises(ValueError, match="lower_at < raise_at"):
            _spec(adapt_k=True, raise_at=0.5, lower_at=0.5)
        with pytest.raises(ValueError, match="collapse_at"):
            _spec(adapt_k=True, collapse_at=0.4, lower_at=0.3)
        with pytest.raises(ValueError, match="adapt_window"):
            _spec(adapt_k=True, adapt_window=0)
        with pytest.raises(ValueError, match="probe_every"):
            _spec(adapt_k=True, probe_every=0)

    def test_swap_record_settles(self):
        eng = _spec(adapt_k=False, adapt_window=2)
        s = eng._stats
        s["proposed"] += 10
        s["accepted"] += 2                      # cumulative 0.2
        eng.swap_draft(_lm().variables, source="unit")
        rec = eng.swap_records[0]
        assert rec["accept_before"] == 0.2
        assert rec["accept_after"] is None      # not settled yet
        s["proposed"] += 4
        s["accepted"] += 3                      # post-swap 0.75
        eng._settle_swap()
        assert eng.swap_records[0]["accept_after"] == 0.75
        h = eng.health()["speculative"]
        assert h["swaps"] == 1
        assert h["last_swap"]["accept_after"] == 0.75

    def test_swap_refused_after_fallback(self):
        eng = _spec()
        eng._fallback = "draft watchdog"
        with pytest.raises(RuntimeError, match="fallback"):
            eng.swap_draft(_lm().variables)
