"""utils.file (File.save/load parity) + utils.debug tests."""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.utils import debug, file as bfile


class TestFile:
    def test_object_roundtrip(self, tmp_path):
        obj = {"a": 1, "b": [1.5, "x"]}
        p = str(tmp_path / "sub" / "obj.bin")
        bfile.save(obj, p)
        assert bfile.load(p) == obj

    def test_no_overwrite(self, tmp_path):
        p = str(tmp_path / "o.bin")
        bfile.save(1, p)
        with pytest.raises(FileExistsError):
            bfile.save(2, p, overwrite=False)

    def test_tensor_tree_roundtrip(self, tmp_path):
        tree = {"layer1": {"weight": np.arange(6.0).reshape(2, 3),
                           "bias": np.zeros(3)},
                "top": np.ones(2)}
        p = str(tmp_path / "t.npz")
        bfile.save_tensors(tree, p)
        back = bfile.load_tensors(p)
        np.testing.assert_array_equal(back["layer1"]["weight"],
                                      tree["layer1"]["weight"])
        np.testing.assert_array_equal(back["top"], tree["top"])


class TestDebug:
    def test_assert_all_finite_passes(self):
        debug.assert_all_finite({"w": jnp.ones(3)})

    def test_assert_all_finite_names_bad_leaf(self):
        with pytest.raises(FloatingPointError, match="bad"):
            debug.assert_all_finite(
                {"ok": jnp.ones(2), "bad": jnp.asarray([1.0, jnp.nan])},
                name="grads")

    def test_debug_nans_traps(self):
        import jax

        with debug.debug_nans():
            with pytest.raises(FloatingPointError):
                jax.jit(lambda x: 0.0 / x)(jnp.asarray(0.0))

    def test_deterministic_repeats(self):
        import jax

        with debug.deterministic(7) as k1:
            a = jax.random.normal(k1, (4,))
        with debug.deterministic(7) as k2:
            b = jax.random.normal(k2, (4,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestSparkAdapter:
    def test_rdd_like_and_sharding(self):
        from bigdl_tpu.dataset.spark_adapter import rdd_to_dataset

        class FakeRDD:
            def __init__(self, rows):
                self.rows = rows

            def collect(self):
                return list(self.rows)

        rows = [(np.ones(3) * i, i % 2) for i in range(10)]
        ds = rdd_to_dataset(FakeRDD(rows), process_id=1, num_processes=2)
        assert ds.size() == 5  # odd indices only
        feats = [s.feature[0] for s in ds.elements]
        assert feats == [1.0, 3.0, 5.0, 7.0, 9.0]

    def test_dataframe_stand_in(self):
        from bigdl_tpu.dataset.spark_adapter import dataframe_to_dataset

        df = {"features": [np.zeros(2), np.ones(2)], "label": [0, 1]}
        ds = dataframe_to_dataset(df, process_id=0, num_processes=1)
        assert ds.size() == 2


class TestEngineEnvValidation:
    def test_partial_pod_env_raises_descriptive(self, monkeypatch):
        """BIGDL_COORDINATOR without its two companions must raise a
        ValueError naming all three variables, not a bare KeyError
        (ADVICE r1)."""
        import pytest

        from bigdl_tpu.utils.engine import Engine

        monkeypatch.setenv("BIGDL_COORDINATOR", "10.0.0.1:8476")
        monkeypatch.delenv("BIGDL_NUM_PROCESSES", raising=False)
        monkeypatch.delenv("BIGDL_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="BIGDL_NUM_PROCESSES"):
            Engine.init_distributed()
