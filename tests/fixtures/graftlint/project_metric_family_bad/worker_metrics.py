# graftlint project fixture: metric-family-contract TRUE POSITIVES —
# label drift, an orphan family, and a bump through a metric binding
# (`_m_*` convention) nobody ever registered.
from bigdl_tpu import obs


class Worker:
    def __init__(self):
        reg = obs.get_registry()
        self._m_jobs = reg.counter(
            "worker_jobs_total", "jobs finished",
            labelnames=("queue",))
        self._m_orphan = reg.gauge(  # BAD
            "worker_orphan_depth", "registered but never bumped")

    def bump(self, queue):
        self._m_jobs.labels(queue=queue, shard="0").inc()  # BAD
        self._m_ghost.inc()  # BAD
