# graftlint project fixture: metric-family-contract TRUE POSITIVES,
# cross-file — a second registration of a family worker_metrics.py
# already owns, and a by-name fetch of a family nobody registers.
from bigdl_tpu import obs


def report():
    reg = obs.get_registry()
    dup = reg.counter("worker_jobs_total", "duplicate owner")  # BAD
    ghost = reg.get("worker_never_registered_total")  # BAD
    return dup, ghost
