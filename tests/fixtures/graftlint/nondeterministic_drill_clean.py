# graftlint fixture: nondeterministic-drill CLEAN — injectable clock,
# seeded streams, and sleep-as-straggler-model are all sanctioned.
import time

import jax
import numpy as np


class Engine:
    def __init__(self, clock=time.monotonic):  # reference, not a call
        self._clock = clock

    def admit(self, queue, seed):
        now = self._clock()
        rng = np.random.RandomState(seed)
        rng.shuffle(queue)
        return now

    def decode_keys(self, seed, nout):
        return jax.random.fold_in(jax.random.PRNGKey(seed), nout)

    def straggler_model(self, slow_s):
        if slow_s:
            time.sleep(slow_s)  # injected hang model, not a clock read


class Router:
    def __init__(self, clock=time.monotonic):  # injection point
        self._clock = clock

    def make_trace(self, n, seed):
        rng = np.random.RandomState(seed)   # seeded: trace is a pure
        return rng.exponential(0.25, n)     # function of its args

    def autoscale_decision(self):
        return {"t": self._clock()}         # injected, not wall clock


def schedule_preempt(n_steps, seed):
    # ISSUE 9: the kill step comes from a FAULT-PLAN SCHEDULE — a
    # seeded draw baked into a `kind@step` string, so every drill
    # invocation preempts at the same step and resume bit-identity is
    # a falsifiable assertion
    rng = np.random.RandomState(seed)
    kill_step = int(rng.randint(2, n_steps))
    return f"preempt@{kill_step},ckpt_async_torn@{n_steps - 1}"


class AlertEngine:
    # ISSUE 14: alert transitions are stamped from the INJECTED clock
    # (the sampler's virtual cell in a drill) — evaluation stays a
    # pure function of (window contents, clock)
    def __init__(self, clock=time.monotonic):  # injection point
        self._clock = clock

    def evaluate(self, rule, window_s):
        return {"alert": rule, "fired_at": self._clock(),
                "window_s": window_s}


def compile_scenario(spec):
    # ISSUE 20: ONE seeded stream per compile — the trace is a pure
    # function of the spec (same seed, same arrivals, every time)
    rng = np.random.RandomState(spec["seed"])
    return sorted(rng.exponential(0.25, spec["n"]))


class SimulatedEngine:
    # ISSUE 20: simulated time IS the injected clock — the ctor
    # refuses clock=None, and every stamp reads self._clock()
    def __init__(self, cost_model, clock):
        self._model, self._clock = cost_model, clock

    def step(self):
        return self._clock()
