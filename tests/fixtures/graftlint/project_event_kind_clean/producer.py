# graftlint project fixture: event-kind-contract FALSE-POSITIVE guard,
# producer side — registered kinds, declared fields, required fields
# present (or hidden behind a **splat, which waives the static check).
from bigdl_tpu import obs


def finish(job):
    obs.emit_event("job_done", job=job, status="ok")
    obs.emit_event("job_done", job=job, status="ok", duration_s=1.0)
    obs.emit_event("job_retry", **job.fields())
