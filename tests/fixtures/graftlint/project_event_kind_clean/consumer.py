# graftlint project fixture: event-kind-contract FALSE-POSITIVE guard,
# consumer side — registered kinds only, plus the shapes the rule must
# NOT confuse with event kinds: a metric-family snapshot's "kind" key
# and a module-local `kind` variable that never aliases an event.


def drill_asserts(log):
    return log.events("job_done"), log.events(kind="job_retry")


def fold(events):
    out = []
    for e in events:
        kind = e.get("kind")
        # graftlint: disable=event-kind-contract (suppression-with-why demo)
        if kind == "job_axed":
            pass
        if e["kind"] in ("job_done", "job_retry"):
            out.append(e)
    return out


def histogram_families(snapshot):
    return [name for name, fam in snapshot["metrics"].items()
            if fam["kind"] == "histogram"]


def spec_kind(spec):
    kind = spec["__kind__"]
    return kind == "leaf"
