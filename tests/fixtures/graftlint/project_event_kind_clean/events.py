# graftlint project fixture: clean variant registry.
EVENT_KINDS = {
    "job_done": {"required": ("job", "status"),
                 "optional": ("duration_s",)},
    "job_retry": {"required": ("job",), "optional": ()},
}
