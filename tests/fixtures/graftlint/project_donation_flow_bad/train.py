# graftlint project fixture: donation-flow TRUE POSITIVES — buffers
# donated to a jitted call (via the cross-file factory, and via the
# decorated callable) read again in the caller's scope.
import jax

from .compute import apply_grads, make_named_step, make_step


def run(params, batches):
    step = make_step()
    out = None
    for b in batches:
        new_params = step(params, b)
        out = params["w"]  # BAD
        params = new_params
    return out


def update(grads, opt_state):
    new_state = apply_grads(grads, opt_state)
    stale = opt_state  # BAD
    return new_state, stale


def inline(params, batch):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    fresh = step(params, batch)
    return fresh, params  # BAD


def run_named(params, batch):
    step = make_named_step()
    new_params = step(params, batch)
    stale = params  # BAD (donate_argnames resolves to position 0)
    return new_params, stale


class Trainer:
    # the setup-in-__init__, call-elsewhere shape: the binding is a
    # CLASS attribute, resolved across methods
    def __init__(self):
        self._step = make_step()

    def advance(self, params, batch):
        new_params = self._step(params, batch)
        stale = params  # BAD
        return new_params, stale
