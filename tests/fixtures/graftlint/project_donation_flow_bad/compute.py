# graftlint project fixture: donation-flow — the donating side. A
# factory returning a jit with donate_argnums (the make_*_step
# pattern) and a decorated donating callable.
import functools

import jax


def make_step():
    def step(params, batch):
        return params

    return jax.jit(step, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(1,))
def apply_grads(grads, opt_state):
    return opt_state


def make_named_step():
    def named_step(params, batch):
        return params

    return jax.jit(named_step, donate_argnames=("params",))
