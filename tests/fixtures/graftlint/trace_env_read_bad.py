# graftlint fixture: trace-env-read TRUE POSITIVES.
# Judged as if at bigdl_tpu/ops/fixture.py; the BAD markers name the
# expected finding lines.
import os


def resolve_block(n):
    v = os.environ.get("BIGDL_FIXTURE_BLOCK")  # BAD
    return int(v) if v else n


def kill_switch():
    if os.environ["BIGDL_FIXTURE"] == "0":  # BAD
        return "xla"
    return os.getenv("BIGDL_FIXTURE_IMPL", "pallas")  # BAD


# ISSUE 17: the paged-decode tile knob is an IMPORT-time snapshot
# (BIGDL_PAGED_DECODE_TILES, utils/envknobs) — resolving it at launch
# time would freeze the first value into every compiled decode step
def resolve_decode_tiles(num_blocks, num_heads):
    raw = os.environ.get("BIGDL_PAGED_DECODE_TILES")  # BAD
    if raw:
        bt, ht = raw.split("x")
        return int(bt), int(ht)
    return 1, 1
