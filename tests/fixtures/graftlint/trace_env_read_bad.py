# graftlint fixture: trace-env-read TRUE POSITIVES.
# Judged as if at bigdl_tpu/ops/fixture.py; the BAD markers name the
# expected finding lines.
import os


def resolve_block(n):
    v = os.environ.get("BIGDL_FIXTURE_BLOCK")  # BAD
    return int(v) if v else n


def kill_switch():
    if os.environ["BIGDL_FIXTURE"] == "0":  # BAD
        return "xla"
    return os.getenv("BIGDL_FIXTURE_IMPL", "pallas")  # BAD
