# graftlint fixture: retrace-hazard TRUE POSITIVES.
import functools

import jax


@jax.jit
def branch_on_traced(x, flag):
    if flag:  # BAD
        return x * 2
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def coerce_traced(x, mode):
    if mode == "scale":
        return float(x)  # BAD
    return x


@jax.jit
def loop_on_traced(x, n):
    while n > 0:  # BAD
        x = x * 2
        n = n - 1
    return x


# ISSUE 10: shard_map bodies are trace roots with NO static-arg
# escape — every parameter is a traced operand (serving/tp.py shape)
def sharded_decode(params, pools, tokens, mesh, specs):
    from jax.experimental.shard_map import shard_map

    def body(p, pool, tok):
        if tok:  # BAD
            return p @ pool
        return float(tok)  # BAD

    return shard_map(body, mesh=mesh, in_specs=specs,
                     out_specs=specs)(params, pools, tokens)
