# graftlint fixture: retrace-hazard TRUE POSITIVES.
import functools

import jax


@jax.jit
def branch_on_traced(x, flag):
    if flag:  # BAD
        return x * 2
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def coerce_traced(x, mode):
    if mode == "scale":
        return float(x)  # BAD
    return x


@jax.jit
def loop_on_traced(x, n):
    while n > 0:  # BAD
        x = x * 2
        n = n - 1
    return x


# ISSUE 10: shard_map bodies are trace roots with NO static-arg
# escape — every parameter is a traced operand (serving/tp.py shape)
def sharded_decode(params, pools, tokens, mesh, specs):
    from jax.experimental.shard_map import shard_map

    def body(p, pool, tok):
        if tok:  # BAD
            return p @ pool
        return float(tok)  # BAD

    return shard_map(body, mesh=mesh, in_specs=specs,
                     out_specs=specs)(params, pools, tokens)


# ISSUE 17: pallas kernel bodies are trace roots — partial-bound args
# are the static escape; unbound params are traced Refs (the
# ops/paged_decode.py launch idiom)
def paged_launch(q, table):
    from jax.experimental import pallas as pl

    def kernel(tbl_ref, q_ref, o_ref, *, block_tile):
        if tbl_ref:  # BAD
            o_ref[...] = q_ref[...] * block_tile

    body = functools.partial(kernel, block_tile=2)
    return pl.pallas_call(body, out_shape=None)(table, q)


def paged_launch_inline(q, table):
    from jax.experimental import pallas as pl

    def kernel2(tbl_ref, q_ref, o_ref, *, seq):
        o_ref[...] = q_ref[...] * float(tbl_ref)  # BAD

    return pl.pallas_call(functools.partial(kernel2, seq=64),
                          out_shape=None)(table, q)
