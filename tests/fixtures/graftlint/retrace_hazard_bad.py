# graftlint fixture: retrace-hazard TRUE POSITIVES.
import functools

import jax


@jax.jit
def branch_on_traced(x, flag):
    if flag:  # BAD
        return x * 2
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def coerce_traced(x, mode):
    if mode == "scale":
        return float(x)  # BAD
    return x


@jax.jit
def loop_on_traced(x, n):
    while n > 0:  # BAD
        x = x * 2
        n = n - 1
    return x
