# graftlint fixture: retrace-hazard CLEAN — static args and shape
# metadata branches are trace-safe.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("mode",))
def static_kwarg(x, mode):
    if mode == "double":
        return x * 2
    return x


@jax.jit
def shape_metadata(x):
    if x.ndim == 3:
        return x[0]
    if len(x.shape) > 4:
        return x.reshape(-1)
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def static_positional(x, steps):
    while steps > 0:
        x = x + 1
        steps = steps - 1
    return x


@jax.jit
def traced_math_only(x, y):
    return jnp.where(y > 0, x, -x)  # traced select, not a branch


@jax.jit
def optional_operand(x, mask=None):
    # `is None` tests the ARGUMENT STRUCTURE (pytree), static under
    # trace — the standard optional-operand pattern
    if mask is None:
        return x
    if mask is not None and x.ndim == 2:
        return x * mask
    return x


# ISSUE 10: shard_map bodies may branch on shape metadata and pytree
# structure exactly like jit roots — only VALUE branches are hazards
def sharded_decode(params, pools, tokens, mesh, specs):
    from jax.experimental.shard_map import shard_map

    def body(p, pool, tok):
        if tok.ndim == 2:
            tok = tok[None]
        if pool is None:
            return p * tok
        return jnp.where(tok > 0, p, -p)  # traced select, not a branch

    return shard_map(body, mesh=mesh, in_specs=specs,
                     out_specs=specs)(params, pools, tokens)


# ISSUE 17: pallas kernel bodies may branch on their partial-BOUND
# statics (tile sizes, dup flags) — those are Python values by
# construction, exactly like jit static_argnames
def paged_launch(q, table):
    from jax.experimental import pallas as pl

    def kernel(tbl_ref, q_ref, o_ref, *, block_tile, dup_batch):
        if dup_batch:
            o_ref[...] = q_ref[...] * 2
        for i in range(block_tile):
            o_ref[...] = q_ref[...] + i

    body = functools.partial(kernel, block_tile=2, dup_batch=True)
    return pl.pallas_call(body, out_shape=None)(table, q)
