# graftlint fixture: retrace-hazard CLEAN — static args and shape
# metadata branches are trace-safe.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("mode",))
def static_kwarg(x, mode):
    if mode == "double":
        return x * 2
    return x


@jax.jit
def shape_metadata(x):
    if x.ndim == 3:
        return x[0]
    if len(x.shape) > 4:
        return x.reshape(-1)
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def static_positional(x, steps):
    while steps > 0:
        x = x + 1
        steps = steps - 1
    return x


@jax.jit
def traced_math_only(x, y):
    return jnp.where(y > 0, x, -x)  # traced select, not a branch


@jax.jit
def optional_operand(x, mask=None):
    # `is None` tests the ARGUMENT STRUCTURE (pytree), static under
    # trace — the standard optional-operand pattern
    if mask is None:
        return x
    if mask is not None and x.ndim == 2:
        return x * mask
    return x
