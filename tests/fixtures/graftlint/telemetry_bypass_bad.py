# graftlint fixture: telemetry-bypass TRUE POSITIVES (judged as if in
# bigdl_tpu/ core).
import sys


def emit_metric(step, loss):
    print(f"step {step}: loss={loss}")  # BAD


def write_raw(msg):
    sys.stdout.write(msg + "\n")  # BAD
    sys.stderr.write("warn: " + msg)  # BAD
