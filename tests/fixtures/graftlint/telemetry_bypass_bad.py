# graftlint fixture: telemetry-bypass TRUE POSITIVES (judged as if in
# bigdl_tpu/ core).
import sys


def emit_metric(step, loss):
    print(f"step {step}: loss={loss}")  # BAD


def write_raw(msg):
    sys.stdout.write(msg + "\n")  # BAD
    sys.stderr.write("warn: " + msg)  # BAD


# ISSUE 11: the flight recorder writes bundle FILES, never stdout — a
# print() would interleave with the bench/drill JSON that indexes it
def dump_bundle(outdir, manifest):
    print(f"incident dumped to {outdir}")  # BAD
    return manifest


def build_journeys(events):
    print(len(events), "events")  # BAD
    return []


# ISSUE 14: the scrape endpoint serves exposition BYTES over HTTP — a
# print() in its render path would interleave operator chatter with
# the bench/drill JSON on stdout and bypass the BIGDL_OBS kill switch
def scrape_metrics(registry):
    text = registry.render_prometheus()
    print(text)  # BAD
    return text.encode()


def health_view(alert_engine):
    firing = alert_engine.firing()
    print("firing:", firing)  # BAD
    return {"firing": firing}
