# graftlint fixture: trace-env-read CLEAN — import-time snapshots are
# the sanctioned pattern (utils/envknobs).
import os

_BLOCK = os.environ.get("BIGDL_FIXTURE_BLOCK")
_IMPL = os.getenv("BIGDL_FIXTURE_IMPL", "pallas")


def resolve_block(n):
    return int(_BLOCK) if _BLOCK else n


def resolve_impl():
    return _IMPL

_PAGED_TILES = os.environ.get("BIGDL_PAGED_DECODE_TILES")


# ISSUE 17: launch-time tile resolution reads the import snapshot —
# in-process sweeps mutate env then call envknobs.refresh() with a
# fresh jit root per config
def resolve_decode_tiles(num_blocks, num_heads):
    if _PAGED_TILES:
        bt, ht = _PAGED_TILES.split("x")
        return int(bt), int(ht)
    return 1, 1
