# graftlint fixture: trace-env-read CLEAN — import-time snapshots are
# the sanctioned pattern (utils/envknobs).
import os

_BLOCK = os.environ.get("BIGDL_FIXTURE_BLOCK")
_IMPL = os.getenv("BIGDL_FIXTURE_IMPL", "pallas")


def resolve_block(n):
    return int(_BLOCK) if _BLOCK else n


def resolve_impl():
    return _IMPL
