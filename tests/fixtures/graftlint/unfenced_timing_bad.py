# graftlint fixture: unfenced-timing TRUE POSITIVES.
import time


def bench_dispatch_only(step_fn, batches):
    t0 = time.perf_counter()
    loss = None
    for b in batches:
        loss = step_fn(b)
    return time.perf_counter() - t0  # BAD


def bench_decode(decode_fn, n):
    t0 = time.time()
    for i in range(n):
        decode_fn(i)
    dt = time.time() - t0  # BAD
    return dt
