# graftlint fixture: missing-reference-docstring CLEAN — the four
# sanctioned citation styles plus the exemptions.
"""Fixture layers.

Reference parity: nn/HeaderCited.scala (the module-header style).
"""

from bigdl_tpu.nn.module import Module


class DirectlyCited(Module):
    """Identity (reference: nn/DirectlyCited.scala)."""


class ParityCited(Module):
    """Identity. Reference parity: nn/abstractnn/ParityCited.scala."""


class HeaderCited(Module):
    """Named in the module docstring's Reference parity header."""


class TpuExtension(Module):
    """No reference counterpart — TPU-first extension."""


class DisclaimedExtension(Module):
    """No direct reference counterpart (predates the concept)."""


class _PrivateHelper(Module):
    """Private: exempt."""


class PlainDataHolder:
    """No bases: exempt."""
