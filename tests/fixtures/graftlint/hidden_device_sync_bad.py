# graftlint fixture: hidden-device-sync TRUE POSITIVES (judged as if
# at bigdl_tpu/serving/fixture.py — hot-path function names).
import jax
import numpy as np


def decode_step(logits, cache):
    tok = logits.item()  # BAD
    host = np.asarray(cache)  # BAD
    jax.device_get(logits)  # BAD
    logits.block_until_ready()  # BAD
    return tok, host


def observe_latency(registry, value):
    registry.observe(float(np.asarray(value)))  # BAD
