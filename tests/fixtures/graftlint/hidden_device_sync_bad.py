# graftlint fixture: hidden-device-sync TRUE POSITIVES (judged as if
# at bigdl_tpu/serving/fixture.py — hot-path function names).
import jax
import numpy as np


def decode_step(logits, cache):
    tok = logits.item()  # BAD
    host = np.asarray(cache)  # BAD
    jax.device_get(logits)  # BAD
    logits.block_until_ready()  # BAD
    return tok, host


def observe_latency(registry, value):
    registry.observe(float(np.asarray(value)))  # BAD


# ISSUE 8: the paged-cache lookup/insert/evict/alloc paths are hot —
# block-table surgery runs between every decode step
def lookup_prefix(tree, tokens):
    return tree.walk(np.asarray(tokens))  # BAD


def evict_lru_block(pool, stamp_leaf):
    return stamp_leaf.item()  # BAD


def alloc_blocks(pool, n, stats):
    jax.device_get(stats)  # BAD
    return pool[:n]


def insert_chain(tree, blocks):
    blocks.block_until_ready()  # BAD
    return tree


# ISSUE 10: handoff export/import and pool placement are hot — a
# handoff moves once per request, placement runs on the step path
def import_handoff(pool, pkg):
    return np.asarray(pkg.kv)  # BAD


def place_pools(pools, stats):
    jax.device_get(stats)  # BAD
    return pools


# ISSUE 11: journey/flight-recorder paths run inside emit (an EventLog
# listener) — a sync there stalls the decode loop once per event
def build_journeys(events, loss):
    return [loss.item()]  # BAD


def dump_bundle(outdir, tail, gauge_leaf):
    return np.asarray(gauge_leaf)  # BAD


def record_event(ring, rec, value):
    ring.append(float(np.asarray(value)))  # BAD


# ISSUE 15: the speculative verify/rollback/mirror paths run between
# every draft-verify round — a stealth sync there stalls the whole
# batch once per round
def verify_round(nxt, finite):
    return np.asarray(nxt), finite.item()  # BAD


def rollback_slot(table, pos_leaf):
    return int(pos_leaf.item())  # BAD


def mirror_slot(draft_pool, pkg):
    return jax.device_get(draft_pool)  # BAD


# ISSUE 16: the host spill tier's spill/readmit/migrate paths run
# between decode steps (eviction cascade, prefix re-admission, trip-
# time tree migration) — only the export's ONE batched fetch may sync
def spill_victims(pool, victims, stamps):
    order = np.asarray(stamps)  # BAD
    return [pool[v] for v in victims], order


def readmit_chain(host_blocks, table, occupancy_leaf):
    jax.device_get(occupancy_leaf)  # BAD
    return table


def migrate_tree(entries, survivor, depth_leaf):
    return survivor.graft(entries, depth_leaf.item())  # BAD


# ISSUE 17: quant/repack paths — quantization runs once at engine
# construction, but a fetch inside the repack pulls the whole fp32
# tree through the tunnel leaf by leaf
def quantize_serving_params(params):
    return {k: np.asarray(v) for k, v in params.items()}  # BAD


def repack_weight(w, scale_leaf):
    return w, scale_leaf.item()  # BAD


# ISSUE 18 speculation flywheel: swap/distill/adapt paths run BETWEEN
# decode rounds on a LIVE engine — the hot-swap is re-placement over
# tree metadata and the k ladder is host arithmetic; any fetch here
# stalls serving once per swap or per evaluation
def swap_params(engine, variables):
    return np.asarray(variables["params"]["embed"])  # BAD


def swap_draft(spec, leaves):
    return [leaf.item() for leaf in leaves]  # BAD


def distill_round(corpus, params_leaf):
    return corpus, jax.device_get(params_leaf)  # BAD


def adapt_lookahead(window_leaf, k_live):
    return min(k_live, int(window_leaf.item()))  # BAD
