# graftlint project fixture: lock-discipline TRUE POSITIVES — a
# Thread-entrypoint method writing shared attributes outside the lock,
# and main-path methods touching them bare.
import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self.dropped = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            self._items.append(1)  # BAD
            with self._lock:
                self.dropped += 1

    def drain(self):
        out = list(self._items)  # BAD
        self._items.clear()  # BAD
        with self._lock:
            n = self.dropped
        return out, n


class StepRunner:
    # closure-entry shape (the watchdog pattern): only the closure
    # runs on the thread — the HOST method is main-path and its bare
    # read races the closure's write
    def __init__(self):
        self._lock = threading.Lock()
        self._results = []

    def step(self, x):
        def work():
            self._results.append(x)  # BAD

        t = threading.Thread(target=work, daemon=True)
        t.start()
        return list(self._results)  # BAD


class Listener:
    def __init__(self, log):
        self._lock = threading.Lock()
        self._tail = []
        log.add_listener(self._on_event)

    def _on_event(self, rec):
        self._tail.append(rec)  # BAD

    def snapshot(self):
        return list(self._tail)  # BAD


class ScrapeServer:
    # ISSUE 14 shape: the scrape endpoint's daemon serving thread
    # shares scrape bookkeeping with the main path — both sides bare
    def __init__(self):
        self._lock = threading.Lock()
        self._scrapes = 0
        self._last_body = b""
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        while True:
            self._scrapes += 1  # BAD
            self._last_body = b"metrics"  # BAD

    def health_view(self):
        return {"scrapes": self._scrapes}  # BAD
