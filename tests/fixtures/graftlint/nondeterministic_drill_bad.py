# graftlint fixture: nondeterministic-drill TRUE POSITIVES (judged as
# if at bigdl_tpu/serving/fixture.py).
import random
import time
from datetime import datetime

import numpy as np


def admit(queue):
    now = time.time()  # BAD
    random.shuffle(queue)  # BAD
    jitter = np.random.rand()  # BAD
    return now + jitter


def deadline_check(req):
    return time.monotonic() > req.deadline  # BAD


def make_trace(n):
    # a loadgen-shaped trace from global streams: two-runs-identical
    # JSON is impossible with either of these
    gaps = np.random.exponential(0.25, n)  # BAD
    t0 = time.perf_counter()  # BAD
    return t0, gaps


def autoscale_decision(router):
    return {"t": datetime.now()}  # BAD


def schedule_preempt(n_steps):
    # ISSUE 9: drawing the kill step from a global stream — two drill
    # invocations preempt at different steps, so "resume-after-kill is
    # bit-identical" becomes unfalsifiable run to run
    kill_step = np.random.randint(2, n_steps)  # BAD
    torn_at = random.randrange(n_steps)  # BAD
    return f"preempt@{kill_step},ckpt_async_torn@{torn_at}"


def alert_evaluate(rule, window_s):
    # ISSUE 14: an alert engine stamping transitions off the wall
    # clock — firing times (and therefore the slo_alert drill's
    # report and bundle bytes) drift run to run
    fired_at = time.time()  # BAD
    return {"alert": rule, "fired_at": fired_at, "window_s": window_s}


def compile_scenario(spec):
    # ISSUE 20: scenario arrival draws from the global stream — the
    # compiled trace differs run to run, so "two replays are
    # byte-identical" is dead before the simulator even starts
    times = np.random.exponential(0.25, spec["n"])  # BAD
    return sorted(times)


class SimulatedEngine:
    def step(self):
        # ISSUE 20: a wall-clock read inside the simulator mixes real
        # milliseconds into the virtual-seconds timeline
        return time.monotonic()  # BAD
