# graftlint fixture: nondeterministic-drill TRUE POSITIVES (judged as
# if at bigdl_tpu/serving/fixture.py).
import random
import time

import numpy as np


def admit(queue):
    now = time.time()  # BAD
    random.shuffle(queue)  # BAD
    jitter = np.random.rand()  # BAD
    return now + jitter


def deadline_check(req):
    return time.monotonic() > req.deadline  # BAD
