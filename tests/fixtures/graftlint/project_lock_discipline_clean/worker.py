# graftlint project fixture: lock-discipline FALSE-POSITIVE guard —
# every shared write/read under the lock (directly, or in a helper
# whose only call sites hold it), synchronized containers exempt,
# __init__ writes exempt (they precede the thread), and a justified
# bare read carrying a suppression with its why.
import queue
import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._q = queue.Queue()
        self.dropped = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            with self._lock:
                self._items.append(1)
                self._flush()
            self._q.put(1)

    def _flush(self):
        # only ever called with the lock held — effectively locked
        self.dropped += 1

    def drain(self):
        with self._lock:
            out = list(self._items)
            self._items.clear()
            n = self.dropped
        # GIL-atomic len() of a list, advisory only — safe bare
        depth = len(self._items)  # graftlint: disable=lock-discipline
        return out, n, depth


class StepRunner:
    # closure-entry shape, done right: write AND host-side read both
    # under the lock
    def __init__(self):
        self._lock = threading.Lock()
        self._results = []

    def step(self, x):
        def work():
            with self._lock:
                self._results.append(x)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        with self._lock:
            return list(self._results)


class ScrapeServer:
    # ISSUE 14 shape, done right: the serving thread's scrape
    # bookkeeping and the main path's health view share one lock
    def __init__(self):
        self._lock = threading.Lock()
        self._scrapes = 0
        self._last_body = b""
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        while True:
            with self._lock:
                self._scrapes += 1
                self._last_body = b"metrics"

    def health_view(self):
        with self._lock:
            return {"scrapes": self._scrapes}
