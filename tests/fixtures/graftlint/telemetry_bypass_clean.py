# graftlint fixture: telemetry-bypass CLEAN — logging + obs are the
# sanctioned channels; a print() in a docstring/string is not a call.
import logging

logger = logging.getLogger("bigdl_tpu.fixture")

USAGE = """example:
    print(t.elapsed)   # only a string, not a call
"""


def emit_metric(step, loss):
    logger.info("step %d: loss=%s", step, loss)


def emit_event(emit_event_fn, step):
    emit_event_fn("train_step", step=step)


# ISSUE 11: the flight recorder reports through the event log (its
# incident_dump record) and the logger — never stdout
def dump_bundle(emit_event_fn, outdir, slug):
    logger.info("flight recorder dumped %s to %s", slug, outdir)
    emit_event_fn("incident_dump", incident=slug, bundle=outdir)


# ISSUE 14: the scrape endpoint hands the exposition bytes back to its
# HTTP handler and logs through the bigdl_tpu logger — stdout stays
# untouched for the bench/drill JSON consumers
def scrape_metrics(registry):
    text = registry.render_prometheus()
    logger.debug("scrape served %d bytes", len(text))
    return text.encode()


def health_view(alert_engine):
    firing = alert_engine.firing()
    logger.info("alerts firing: %s", firing)
    return {"firing": firing}
