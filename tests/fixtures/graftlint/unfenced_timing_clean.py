# graftlint fixture: unfenced-timing CLEAN — every window over device
# work closes with a real fetch; host-only windows are free.
import time

import numpy as np


def bench_fenced(step_fn, batches):
    t0 = time.perf_counter()
    loss = None
    for b in batches:
        loss = step_fn(b)
    float(loss)  # device→host fetch bounds the whole chain
    return time.perf_counter() - t0


def bench_asarray(decode_fn, n):
    t0 = time.time()
    out = None
    for i in range(n):
        out = decode_fn(i)
    np.asarray(out)
    return time.time() - t0


def bench_self_fencing(dispatch_and_fetch, n):
    t0 = time.perf_counter()
    for i in range(n):
        dispatch_and_fetch(i)  # fetches internally (name says so)
    return time.perf_counter() - t0


def host_only_window():
    t0 = time.monotonic()
    total = sum(range(1000))
    return total, time.monotonic() - t0
