# graftlint project fixture: donation-flow FALSE-POSITIVE guard — the
# sanctioned patterns: rebinding the donated name from the call's own
# result (`state = step(state, b)`), copying BEFORE dispatch (the
# donation-aware retry), and reads that happen before the call.
import jax
import jax.numpy as jnp

from .compute import apply_grads, make_named_step, make_step, \
    wrap_model


def run(params, batches):
    step = make_step()
    for b in batches:
        params = step(params, b)
    return params


def update_with_retry(grads, opt_state):
    saved = jax.tree_util.tree_map(jnp.copy, opt_state)
    new_state = apply_grads(grads, opt_state)
    return new_state, saved


def read_before_call(params, batch):
    step = make_step()
    norm = params["w"]
    new_params = step(params, batch)
    return new_params, norm


def run_named(params, batch):
    step = make_named_step()
    params = step(params, batch)
    return params


class Trainer:
    def __init__(self):
        self._step = make_step()

    def advance(self, params, batch):
        params = self._step(params, batch)
        return params


def use_wrapped(params, batch):
    # wrap_model's INNER helper returns a donating jit, but the outer
    # function donates nothing — callers must stay clean
    fn = wrap_model(lambda p, b: p)
    out = fn(params, batch)
    return out, params
