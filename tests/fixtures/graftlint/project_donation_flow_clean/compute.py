# graftlint project fixture: donation-flow clean side — same donating
# factory/callable shapes as the bad variant.
import functools

import jax


def make_step():
    def step(params, batch):
        return params

    return jax.jit(step, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(1,))
def apply_grads(grads, opt_state):
    return opt_state


def make_named_step():
    def named_step(params, batch):
        return params

    return jax.jit(named_step, donate_argnames=("params",))


def wrap_model(model):
    """NOT a donating factory: only the inner helper returns a jit —
    nested defs are pruned, so callers of wrap_model stay unchecked."""
    def _unused_jit_builder():
        return jax.jit(lambda p, b: p, donate_argnums=(0,))

    return model
