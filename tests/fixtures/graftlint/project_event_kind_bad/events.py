# graftlint project fixture: the mini-package's EVENT_KINDS registry
# (the single source of truth the rule pins producers/consumers to).
EVENT_KINDS = {
    "job_done": {"required": ("job", "status"),
                 "optional": ("duration_s",)},
    "job_retry": {"required": ("job",), "optional": ()},
}
