# graftlint project fixture: event-kind-contract TRUE POSITIVES,
# producer side (cross-file: the registry lives in events.py).
from bigdl_tpu import obs


def finish(job):
    obs.emit_event("job_started", job=job)  # BAD
    obs.emit_event("job_done", job=job)  # BAD
    obs.emit_event("job_done", job=job, status="ok", color="red")  # BAD
    obs.emit_event("job_done", job=job, status="ok", duration_s=1.0)
    obs.emit_event("job_retry", **job.fields())
