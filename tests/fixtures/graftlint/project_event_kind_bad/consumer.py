# graftlint project fixture: event-kind-contract TRUE POSITIVES,
# consumer side — kind literals no producer can ever emit.


def drill_asserts(log):
    finished = log.events("job_finished")  # BAD
    retried = log.events("job_retry")
    return finished, retried


def fold(events):
    out = []
    for e in events:
        kind = e.get("kind")
        if kind == "job_axed":  # BAD
            continue
        if e["kind"] in ("job_done", "job_killed"):  # BAD
            out.append(e)
    return out
