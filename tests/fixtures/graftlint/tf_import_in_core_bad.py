# graftlint fixture: tf-import-in-core TRUE POSITIVES.
import tensorflow as tf  # BAD
from tensorflow.io import gfile  # BAD


def read(path):
    with gfile.GFile(path) as f:
        return tf.constant(f.read())
