# graftlint project fixture: metric-family-contract FALSE-POSITIVE
# guard — one registration per family, matching label sets, keyed
# family maps, a chained-child binding, and an inline
# register-and-observe chain (the checkpoint pattern).
from bigdl_tpu import obs


class Worker:
    def __init__(self):
        reg = obs.get_registry()
        self._m_jobs = reg.counter(
            "worker_jobs_total", "jobs finished",
            labelnames=("queue",))
        self._m_ops = {
            key: reg.counter(f"worker_{key}_total", help_,
                             labelnames=("queue",)
                             ).labels(queue="default")
            for key, help_ in {"retries": "job retries"}.items()}
        self._m_depth = reg.gauge(
            "worker_queue_depth", "queued jobs",
            labelnames=("queue",)).labels(queue="default")

    def bump(self, queue, n):
        self._m_jobs.labels(queue=queue).inc()
        self._m_ops["retries"].inc(n)
        self._m_depth.set(n)


def observe_once(reg, seconds):
    reg.histogram("worker_save_seconds", "save wall seconds",
                  labelnames=("mode",)).labels(mode="sync") \
        .observe(seconds)
