# graftlint project fixture: metric-family-contract FALSE-POSITIVE
# guard, cross-file — a by-name fetch of a family worker_metrics.py
# registers (this is the sanctioned way to read a family another
# module owns; re-registering it would be the violation).
from bigdl_tpu import obs


def report():
    reg = obs.get_registry()
    fam = reg.get("worker_jobs_total")
    retries = reg.get("worker_retries_total")  # matches the keyed map
    return fam, retries
