# graftlint fixture: tf-import-in-core CLEAN — the bundled
# wire-compatible protos are the sanctioned interop path (and a module
# merely NAMED tensorflowish is not TF).
import tensorflow_datasets_shim_that_is_not_tf as shim  # noqa: F401


def read(path):
    with open(path, "rb") as f:
        return f.read()
