# graftlint fixture: hidden-device-sync CLEAN (judged as if at
# bigdl_tpu/serving/fixture.py).
import numpy as np


def build_buckets(lengths):
    # not a hot-path function name: host-side setup may fetch freely
    return np.asarray(sorted(lengths))


def decode_step(host_tokens, host_finite):
    # hot path consuming ALREADY-FETCHED host values: plain host math
    done = [int(t) for t in host_tokens]
    ok = all(bool(f) for f in host_finite)
    return done, ok


def dispatch_and_fetch(step_fn, operands):
    nxt = step_fn(*operands)
    # the one deliberate fence, justified + suppressed:
    return np.asarray(nxt)  # graftlint: disable=hidden-device-sync


# ISSUE 8 paged-cache paths: pure host bookkeeping is fine
def lookup_prefix(tree, tokens, block_size):
    # radix walk over python ints/dicts — no device work
    out = []
    for i in range(len(tokens) // block_size):
        node = tree.get(tuple(tokens[i * block_size:(i + 1)
                                     * block_size]))
        if node is None:
            break
        out.append(node)
    return out


def evict_lru_leaf(cached):
    # min over logical-clock stamps: deterministic, host-only
    return min(cached, key=lambda b: b[1])[0] if cached else None


def alloc_blocks(free_list, n):
    return [free_list.pop() for _ in range(n)] \
        if len(free_list) >= n else None


# ISSUE 10 sharded-serving paths
def export_handoff(pool, idx):
    # the ONE deliberate per-request fetch at the disaggregation
    # boundary, justified + suppressed:
    return np.asarray(pool[idx])  # graftlint: disable=hidden-device-sync


def place_pools(pools, mesh, specs):
    # re-COMMITS shardings (device-side placement), fetches nothing
    return [mesh.place(p, s) for p, s in zip(pools, specs)]


def gather_serving_params(params):
    # not a hot-path name: the checkpoint form is a deliberate
    # whole-tree host fetch in host-side setup
    return np.asarray(params)


# ISSUE 11 journey/flight-recorder paths: pure host post-processing
# over already-emitted event dicts is fine
def build_journeys(events):
    by_trace = {}
    for e in events:
        if e.get("trace") is not None:
            by_trace.setdefault(e["trace"], []).append(e)
    return by_trace


def record_event(ring, rec):
    # an EventLog listener consumes the already-host record verbatim
    ring.append(rec)


def dump_bundle(write_fn, tail, health_sources):
    # bundle content = host dicts only (events, health snapshots)
    write_fn("events.jsonl", list(tail))
    write_fn("health.json", {k: fn() for k, fn in health_sources})


# ISSUE 15 speculative paths: acceptance/rollback consume the round's
# ONE already-fetched verify result; the dispatch carries the fence
def verify_dispatch(step_fn, operands):
    nxt = step_fn(*operands)
    # THE one deliberate per-round target fetch, justified + suppressed:
    return np.asarray(nxt)  # graftlint: disable=hidden-device-sync


def accept_and_rollback(host_samples, host_proposals, table_row):
    # coupled acceptance + table truncation: plain host ints
    matched = 0
    for g, d in zip(host_samples, host_proposals):
        if int(g) != int(d):
            break
        matched += 1
    for j in range(matched + 1, len(table_row)):
        table_row[j] = 0
    return matched


def mirror_slot(draft, slot, prompt):
    # shadow seat = host bookkeeping + the draft's own prefill path
    return draft.admit(slot, list(prompt))


# ISSUE 16 host spill tier: the export's batched device_get IS the
# spill (host parking needs the bytes down); everything else is host
# bookkeeping over block ids and already-parked numpy arrays
def spill_victims(pool, victims):
    # THE one deliberate batched spill fetch, justified + suppressed:
    return np.asarray(pool[victims])  # graftlint: disable=hidden-device-sync


def readmit_chain(parked, table, slot, free_blocks):
    # re-admission = block-table patch over host ints; the device_put
    # side is placement, not a fetch
    for j, blk in enumerate(free_blocks[:len(parked)]):
        table[slot][j] = blk
    return table


def migrate_tree(entries, survivor):
    # warm-state migration grafts already-parked host entries — pure
    # tree surgery, no device round-trips
    return sum(survivor.graft_host(e) for e in entries)


# ISSUE 17: the repack stays device-side (jnp ops in, jax arrays
# out); bytes provenance reads STATIC leaf metadata, never values
def quantize_serving_params(params, quantize_fn):
    return {k: quantize_fn(v) for k, v in params.items()}


def quant_params_bytes(leaves):
    return sum(leaf.size * leaf.dtype.itemsize for leaf in leaves)


# ISSUE 18 speculation flywheel: the swap checks STRUCTURE and leaf
# metadata (shapes), never values; the adaptive ladder consumes
# already-fetched host ints from the accept histogram
def swap_params(engine, old_params, new_params, tree_structure):
    if tree_structure(new_params) != tree_structure(old_params):
        raise ValueError("layout changed")
    return new_params


def swap_draft(spec, new_vars, accept_before):
    spec.draft.swap(new_vars)
    return {"accept_before": accept_before, "accept_after": None}


def distill_corpus(streams, seq_len):
    return [s[i:i + seq_len + 1] for s in streams
            for i in range(0, max(1, len(s) - seq_len), seq_len)]


def adapt_lookahead(window_accept, k_live, k_min, k_max, raise_at,
                    lower_at):
    if window_accept >= raise_at:
        return min(k_max, k_live + 1)
    if window_accept < lower_at:
        return max(k_min, k_live - 1)
    return k_live
