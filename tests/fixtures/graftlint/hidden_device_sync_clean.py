# graftlint fixture: hidden-device-sync CLEAN (judged as if at
# bigdl_tpu/serving/fixture.py).
import numpy as np


def build_buckets(lengths):
    # not a hot-path function name: host-side setup may fetch freely
    return np.asarray(sorted(lengths))


def decode_step(host_tokens, host_finite):
    # hot path consuming ALREADY-FETCHED host values: plain host math
    done = [int(t) for t in host_tokens]
    ok = all(bool(f) for f in host_finite)
    return done, ok


def dispatch_and_fetch(step_fn, operands):
    nxt = step_fn(*operands)
    # the one deliberate fence, justified + suppressed:
    return np.asarray(nxt)  # graftlint: disable=hidden-device-sync
