# graftlint fixture: missing-reference-docstring TRUE POSITIVES
# (judged as if at bigdl_tpu/nn/fixture.py).
"""Fixture layers with no reference citations anywhere."""

from bigdl_tpu.nn.module import Module


class UncitedLayer(Module):  # BAD
    """Does something, cites nothing."""

    def apply(self, variables, x, training=False, rng=None):
        return x, variables["state"]


class UndocumentedLayer(Module):  # BAD
    def apply(self, variables, x, training=False, rng=None):
        return x, variables["state"]
