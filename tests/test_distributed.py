"""Distributed DP tests on the virtual 8-device CPU mesh — the
`local[N]`-without-a-cluster strategy of the reference
(optim/DistriOptimizerSpec, parameters/AllReduceParameterSpec)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import (
    Adam, SGD, Optimizer, Trigger, Top1Accuracy, Evaluator,
)
from bigdl_tpu.parallel import (
    FlatParamSpec, make_dp_train_step, make_mesh, DistriOptimizer,
)

KEY = jax.random.PRNGKey(0)


@pytest.fixture(scope="module")
def mesh8():
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    return make_mesh({"data": 8})


class TestFlatParamSpec:
    def test_roundtrip(self):
        model = nn.Sequential(nn.Linear(5, 3), nn.Linear(3, 2)).build(KEY)
        spec = FlatParamSpec(model.variables["params"], 8)
        flat = spec.flatten(model.variables["params"])
        assert flat.shape == (spec.padded,)
        back = spec.unflatten(flat)
        for (n1, a), (n2, b) in zip(model.parameters(),
                                    model.parameters({"params": back, "state": {}})):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_padding_multiple(self):
        params = {"w": jnp.ones((7,))}
        spec = FlatParamSpec(params, 4)
        assert spec.padded == 8
        assert spec.shard_size == 2


class TestDPStepEquivalence:
    def test_dp_matches_single_device_sgd(self, mesh8):
        """8-way DP with mean-gradient must match a single-device step on
        the same global batch — the invariant the reference's
        AllReduceParameter guarantees."""
        model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
        model.build(KEY)
        crit = nn.CrossEntropyCriterion()
        method = SGD(learningrate=0.1)
        params0 = model.variables["params"]
        spec = FlatParamSpec(params0, 8)

        bx = jax.random.normal(jax.random.PRNGKey(1), (32, 6))
        by = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 4)

        # single-device reference step
        def loss_fn(p):
            out, _ = model.apply({"params": p, "state": model.variables["state"]},
                                 bx, training=True)
            return crit(out, by)

        g = jax.grad(loss_fn)(params0)
        ref_params, _ = method.update(g, params0, method.init_slots(params0),
                                      jnp.asarray(0.1), jnp.asarray(0))

        # 8-way DP step (f32 wire to compare exactly)
        step = make_dp_train_step(model, crit, method, mesh8, spec,
                                  grad_dtype=None)
        flat_w = spec.flatten(params0)
        slots = method.init_slots(jnp.zeros((spec.padded,)))
        new_flat, _, _, loss = step(flat_w, slots, model.variables["state"],
                                    bx, by, jnp.asarray(0.1, jnp.float32),
                                    jnp.asarray(0, jnp.int32), KEY)
        dp_params = jax.jit(spec.unflatten)(new_flat)
        for (_, a), (_, b) in zip(
                model.parameters({"params": ref_params, "state": {}}),
                model.parameters({"params": dp_params, "state": {}})):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)

    def test_momentum_slots_stay_sharded(self, mesh8):
        model = nn.Sequential(nn.Linear(4, 4)).build(KEY)
        crit = nn.MSECriterion()
        method = SGD(learningrate=0.05, momentum=0.9, dampening=0.0)
        spec = FlatParamSpec(model.variables["params"], 8)
        step = make_dp_train_step(model, crit, method, mesh8, spec)
        flat_w = spec.flatten(model.variables["params"])
        slots = method.init_slots(jnp.zeros((spec.padded,)))
        bx = jnp.ones((16, 4))
        by = jnp.zeros((16, 4))
        mod_state = model.variables["state"]
        for i in range(3):
            flat_w, slots, mod_state, loss = step(
                flat_w, slots, mod_state, bx, by,
                jnp.asarray(0.05, jnp.float32), jnp.asarray(i, jnp.int32), KEY)
        # global slot shape is (padded,), sharded over the mesh
        assert slots["velocity"].shape == (spec.padded,)
        assert float(jnp.abs(slots["velocity"]).sum()) > 0


class TestDistriOptimizerE2E:
    def test_lenet_dp_converges(self, mesh8, tmp_path):
        train = synthetic_mnist(512, seed=0)
        test = synthetic_mnist(128, seed=5)
        model = lenet.build(10).build(jax.random.PRNGKey(7))
        opt = (Optimizer(model, DataSet.array(train), nn.ClassNLLCriterion(),
                         batch_size=64)
               .set_optim_method(Adam(learningrate=2e-3))
               .set_end_when(Trigger.max_epoch(2))
               .set_validation(Trigger.every_epoch(), DataSet.array(test),
                               [Top1Accuracy()], 64)
               .set_checkpoint(str(tmp_path), Trigger.every_epoch())
               .set_mesh(mesh8))
        opt.log_every = 4
        trained = opt.optimize()
        res = Evaluator(trained).test(DataSet.array(test), [Top1Accuracy()], 64)
        assert res["Top1Accuracy"].result()[0] > 0.9

    def test_bad_batch_size_raises(self, mesh8):
        model = lenet.build(10).build(KEY)
        opt = (Optimizer(model, DataSet.array(synthetic_mnist(32)),
                         nn.ClassNLLCriterion(), batch_size=30)
               .set_mesh(mesh8))
        with pytest.raises(ValueError, match="divisible"):
            opt.optimize()

    def test_bf16_wire_still_converges(self, mesh8):
        train = synthetic_mnist(256, seed=1)
        model = lenet.build(10).build(jax.random.PRNGKey(3))
        opt = (Optimizer(model, DataSet.array(train), nn.ClassNLLCriterion(),
                         batch_size=64)
               .set_optim_method(Adam(learningrate=2e-3))
               .set_end_when(Trigger.max_iteration(12))
               .set_mesh(mesh8))
        opt.log_every = 100
        trained = opt.optimize()
        res = Evaluator(trained).test(DataSet.array(train), [Top1Accuracy()], 64)
        assert res["Top1Accuracy"].result()[0] > 0.8


class TestMeshGradAccumulation:
    def test_accum_matches_large_batch_dp(self, mesh8):
        """n-microbatch accumulation over the mesh == one large-batch DP
        step (VERDICT r1 #3): 2 micro-batches of 16 accumulated then
        applied must match a single 32-row DP step (f32 wire)."""
        from bigdl_tpu.parallel.data_parallel import make_dp_accum_steps

        model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
        model.build(KEY)
        crit = nn.CrossEntropyCriterion()
        method = SGD(learningrate=0.1)
        params0 = model.variables["params"]
        mod_state = model.variables["state"]
        spec = FlatParamSpec(params0, 8)

        bx = jax.random.normal(jax.random.PRNGKey(1), (32, 6))
        by = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 4)

        # one large-batch DP step
        step = make_dp_train_step(model, crit, method, mesh8, spec,
                                  grad_dtype=None)
        flat_w0 = spec.flatten(params0)
        slots0 = method.init_slots(jnp.zeros((spec.padded,)))
        big_flat, _, _, _ = step(flat_w0, slots0, mod_state, bx, by,
                                 jnp.asarray(0.1, jnp.float32),
                                 jnp.asarray(0, jnp.int32), KEY)

        # 2 micro-steps of 16 + apply
        micro_fn, apply_fn = make_dp_accum_steps(
            model, crit, method, mesh8, spec, grad_dtype=None)
        flat_w = spec.flatten(params0)
        slots = method.init_slots(jnp.zeros((spec.padded,)))
        g_acc = jnp.zeros((spec.padded,), jnp.float32)
        st = mod_state
        for lo in (0, 16):
            g_acc, st, _ = micro_fn(flat_w, g_acc, st,
                                    bx[lo:lo + 16], by[lo:lo + 16], KEY)
        acc_flat, _, g_acc = apply_fn(flat_w, slots, g_acc,
                                      jnp.asarray(0.1, jnp.float32),
                                      jnp.asarray(0, jnp.int32),
                                      jnp.asarray(2.0, jnp.float32))

        np.testing.assert_allclose(np.asarray(big_flat),
                                   np.asarray(acc_flat),
                                   rtol=2e-5, atol=1e-6)
        # accumulator came back zeroed for the next cycle
        assert float(jnp.abs(g_acc).max()) == 0.0

    def test_distri_optimizer_accum_e2e(self, mesh8):
        """End-to-end: DistriOptimizer with set_gradient_accumulation(2)
        matches the same run with double the batch size and no
        accumulation (seeded data order, SGD)."""
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Optimizer, Trigger
        from bigdl_tpu.parallel import make_mesh

        rng = np.random.RandomState(1)
        xs = rng.rand(64, 4).astype(np.float32)
        ys = rng.randint(0, 2, 64).astype(np.int32)

        def train(batch_size, accum):
            model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
            model.build(jax.random.PRNGKey(5))
            ds = DataSet.array(
                [Sample(x, int(y)) for x, y in zip(xs, ys)], seed=7)
            opt = (Optimizer(model, ds, nn.ClassNLLCriterion(),
                             batch_size=batch_size, seed=3)
                   .set_optim_method(SGD(learningrate=0.5))
                   .set_mesh(make_mesh({"data": 8}))
                   .set_end_when(Trigger.max_iteration(64 // batch_size)))
            if accum > 1:
                opt.set_gradient_accumulation(accum)
            # f32 wire: micro-batch grads rounded to bf16 independently
            # would differ from the one-big-batch rounding by ~3e-3
            m = DistriOptimizer(opt, opt.mesh, opt.mesh_axis,
                                grad_dtype=None).run()
            return [np.asarray(p) for _, p in m.parameters()]

        big = train(32, 1)
        small = train(16, 2)
        for a, b in zip(big, small):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestStateReduction:
    def test_non_reducible_state_kept_local(self, mesh8):
        """Float state under a '_'-prefixed key (or a known counter key)
        must NOT be pmean'd (VERDICT r1 weak #6): only declared-reducible
        leaves are averaged."""
        from bigdl_tpu.parallel.data_parallel import _reduce_state
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.parallel.shard_map_compat import shard_map

        def body():
            i = jax.lax.axis_index("data").astype(jnp.float32)
            tree = {"bn_mean": i, "_counter": i,
                    "step": i, "nested": {"_hidden": i, "var": i}}
            red = _reduce_state(tree, "data")
            return jax.tree_util.tree_map(lambda v: v[None], red)

        out = shard_map(body, mesh=mesh8, in_specs=(),
                        out_specs=P("data"), check_vma=False)()
        np.testing.assert_allclose(np.asarray(out["bn_mean"]),
                                   np.full(8, 3.5), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out["nested"]["var"]),
                                   np.full(8, 3.5), rtol=1e-6)
        # non-reducible leaves keep their per-shard value
        np.testing.assert_allclose(np.asarray(out["_counter"]),
                                   np.arange(8, dtype=np.float32))
        np.testing.assert_allclose(np.asarray(out["step"]),
                                   np.arange(8, dtype=np.float32))
        np.testing.assert_allclose(np.asarray(out["nested"]["_hidden"]),
                                   np.arange(8, dtype=np.float32))

    def test_named_key_exemption_is_leaf_only(self, mesh8):
        """NON_REDUCIBLE_STATE_KEYS must exempt only a direct leaf — a
        SUBTREE under a generic name like 'step' still gets averaged
        (ADVICE r2 #3), while '_'-prefixed keys exempt the whole subtree."""
        from bigdl_tpu.parallel.data_parallel import _reduce_state
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.parallel.shard_map_compat import shard_map

        def body():
            i = jax.lax.axis_index("data").astype(jnp.float32)
            tree = {"step": {"running_mean": i}, "counter": i,
                    "_private": {"anything": i}}
            red = _reduce_state(tree, "data")
            return jax.tree_util.tree_map(lambda v: v[None], red)

        out = shard_map(body, mesh=mesh8, in_specs=(),
                        out_specs=P("data"), check_vma=False)()
        # subtree under the named key IS reduced
        np.testing.assert_allclose(np.asarray(out["step"]["running_mean"]),
                                   np.full(8, 3.5), rtol=1e-6)
        # direct leaf under the named key is exempt
        np.testing.assert_allclose(np.asarray(out["counter"]),
                                   np.arange(8, dtype=np.float32))
        # '_' prefix still exempts its whole subtree
        np.testing.assert_allclose(np.asarray(out["_private"]["anything"]),
                                   np.arange(8, dtype=np.float32))


class TestStandaloneMeshEvaluator:
    def test_uneven_batch_mesh_eval(self, mesh8):
        """Standalone Evaluator on a mesh pads+masks uneven batches
        (VERDICT r1 weak #7): results equal the single-device Evaluator
        on a dataset whose size is NOT divisible by the mesh axis."""
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Evaluator, Loss, Top1Accuracy

        rng = np.random.RandomState(2)
        samples = [Sample(rng.rand(6).astype(np.float32),
                          int(rng.randint(0, 4)))
                   for _ in range(37)]  # 37 % 8 != 0, final batch 5 rows
        model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax()).build(KEY)
        methods = lambda: [Top1Accuracy(), Loss(nn.ClassNLLCriterion())]

        local = Evaluator(model).test(DataSet.array(samples), methods(),
                                      batch_size=16)
        mesh = Evaluator(model, mesh=mesh8).test(DataSet.array(samples),
                                                 methods(), batch_size=16)
        for name in local:
            lv, lc = local[name].result()
            mv, mc = mesh[name].result()
            assert lc == mc, (name, lc, mc)
            np.testing.assert_allclose(lv, mv, rtol=1e-5, atol=1e-6)

    def test_nondivisible_batch_loss_unbiased(self, mesh8):
        """Batch size NOT divisible by the mesh axis forces the
        Evaluator's own row padding; with edge padding + the last-row
        correction in Loss.stats, the Loss metric must match the
        single-device Evaluator exactly (ADVICE r2 #1 — zero-padding
        silently biased it)."""
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Evaluator, Loss, Top1Accuracy

        rng = np.random.RandomState(7)
        samples = [Sample(rng.rand(6).astype(np.float32),
                          int(rng.randint(0, 4)))
                   for _ in range(25)]  # 3 batches of size 10 (last: real 5), each padded 10 -> 16 rows
        model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax()).build(KEY)
        methods = lambda: [Top1Accuracy(), Loss(nn.ClassNLLCriterion())]

        local = Evaluator(model).test(DataSet.array(samples), methods(),
                                      batch_size=10)
        mesh = Evaluator(model, mesh=mesh8).test(DataSet.array(samples),
                                                 methods(), batch_size=10)
        for name in local:
            lv, lc = local[name].result()
            mv, mc = mesh[name].result()
            assert lc == mc, (name, lc, mc)
            np.testing.assert_allclose(lv, mv, rtol=1e-5, atol=1e-6)


class TestSyncBatchNorm:
    def test_sync_bn_equals_full_batch_bn(self, mesh8):
        """sync=True BN inside shard_map == BN over the FULL batch on
        one device. Round 4 made this exact: averaging E[x] and E[x^2]
        across replicas yields the true global variance (the old
        averaged-local-variance form only approximated it)."""
        from bigdl_tpu.parallel.shard_map_compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        bn_sync = nn.SpatialBatchNormalization(3, sync=True,
                                               axis_name="data")
        bn_ref = nn.SpatialBatchNormalization(3)
        v = bn_ref.init(KEY)
        x = jax.random.normal(jax.random.PRNGKey(4), (16, 4, 4, 3)) \
            * 3.0 + 1.0

        ref, ref_state = bn_ref.apply(v, x, training=True)

        def body(x_local):
            y, st = bn_sync.apply(v, x_local, training=True)
            return y, st

        fn = jax.jit(shard_map(
            body, mesh=mesh8,
            in_specs=P("data", None, None, None),
            out_specs=(P("data", None, None, None), P()),
            check_vma=False))
        out, state = fn(jax.device_put(
            x, NamedSharding(mesh8, P("data", None, None, None))))

        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(state["running_mean"]),
            np.asarray(ref_state["running_mean"]), atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(state["running_var"]),
            np.asarray(ref_state["running_var"]), atol=1e-5)
