"""Distributed DP tests on the virtual 8-device CPU mesh — the
`local[N]`-without-a-cluster strategy of the reference
(optim/DistriOptimizerSpec, parameters/AllReduceParameterSpec)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import (
    Adam, SGD, Optimizer, Trigger, Top1Accuracy, Evaluator,
)
from bigdl_tpu.parallel import (
    FlatParamSpec, make_dp_train_step, make_mesh, DistriOptimizer,
)

KEY = jax.random.PRNGKey(0)


@pytest.fixture(scope="module")
def mesh8():
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    return make_mesh({"data": 8})


class TestFlatParamSpec:
    def test_roundtrip(self):
        model = nn.Sequential(nn.Linear(5, 3), nn.Linear(3, 2)).build(KEY)
        spec = FlatParamSpec(model.variables["params"], 8)
        flat = spec.flatten(model.variables["params"])
        assert flat.shape == (spec.padded,)
        back = spec.unflatten(flat)
        for (n1, a), (n2, b) in zip(model.parameters(),
                                    model.parameters({"params": back, "state": {}})):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_padding_multiple(self):
        params = {"w": jnp.ones((7,))}
        spec = FlatParamSpec(params, 4)
        assert spec.padded == 8
        assert spec.shard_size == 2


class TestDPStepEquivalence:
    def test_dp_matches_single_device_sgd(self, mesh8):
        """8-way DP with mean-gradient must match a single-device step on
        the same global batch — the invariant the reference's
        AllReduceParameter guarantees."""
        model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
        model.build(KEY)
        crit = nn.CrossEntropyCriterion()
        method = SGD(learningrate=0.1)
        params0 = model.variables["params"]
        spec = FlatParamSpec(params0, 8)

        bx = jax.random.normal(jax.random.PRNGKey(1), (32, 6))
        by = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 4)

        # single-device reference step
        def loss_fn(p):
            out, _ = model.apply({"params": p, "state": model.variables["state"]},
                                 bx, training=True)
            return crit(out, by)

        g = jax.grad(loss_fn)(params0)
        ref_params, _ = method.update(g, params0, method.init_slots(params0),
                                      jnp.asarray(0.1), jnp.asarray(0))

        # 8-way DP step (f32 wire to compare exactly)
        step = make_dp_train_step(model, crit, method, mesh8, spec,
                                  grad_dtype=None)
        flat_w = spec.flatten(params0)
        slots = method.init_slots(jnp.zeros((spec.padded,)))
        new_flat, _, _, loss = step(flat_w, slots, model.variables["state"],
                                    bx, by, jnp.asarray(0.1, jnp.float32),
                                    jnp.asarray(0, jnp.int32), KEY)
        dp_params = jax.jit(spec.unflatten)(new_flat)
        for (_, a), (_, b) in zip(
                model.parameters({"params": ref_params, "state": {}}),
                model.parameters({"params": dp_params, "state": {}})):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)

    def test_momentum_slots_stay_sharded(self, mesh8):
        model = nn.Sequential(nn.Linear(4, 4)).build(KEY)
        crit = nn.MSECriterion()
        method = SGD(learningrate=0.05, momentum=0.9, dampening=0.0)
        spec = FlatParamSpec(model.variables["params"], 8)
        step = make_dp_train_step(model, crit, method, mesh8, spec)
        flat_w = spec.flatten(model.variables["params"])
        slots = method.init_slots(jnp.zeros((spec.padded,)))
        bx = jnp.ones((16, 4))
        by = jnp.zeros((16, 4))
        mod_state = model.variables["state"]
        for i in range(3):
            flat_w, slots, mod_state, loss = step(
                flat_w, slots, mod_state, bx, by,
                jnp.asarray(0.05, jnp.float32), jnp.asarray(i, jnp.int32), KEY)
        # global slot shape is (padded,), sharded over the mesh
        assert slots["velocity"].shape == (spec.padded,)
        assert float(jnp.abs(slots["velocity"]).sum()) > 0


class TestDistriOptimizerE2E:
    def test_lenet_dp_converges(self, mesh8, tmp_path):
        train = synthetic_mnist(512, seed=0)
        test = synthetic_mnist(128, seed=5)
        model = lenet.build(10).build(jax.random.PRNGKey(7))
        opt = (Optimizer(model, DataSet.array(train), nn.ClassNLLCriterion(),
                         batch_size=64)
               .set_optim_method(Adam(learningrate=2e-3))
               .set_end_when(Trigger.max_epoch(2))
               .set_validation(Trigger.every_epoch(), DataSet.array(test),
                               [Top1Accuracy()], 64)
               .set_checkpoint(str(tmp_path), Trigger.every_epoch())
               .set_mesh(mesh8))
        opt.log_every = 4
        trained = opt.optimize()
        res = Evaluator(trained).test(DataSet.array(test), [Top1Accuracy()], 64)
        assert res["Top1Accuracy"].result()[0] > 0.9

    def test_bad_batch_size_raises(self, mesh8):
        model = lenet.build(10).build(KEY)
        opt = (Optimizer(model, DataSet.array(synthetic_mnist(32)),
                         nn.ClassNLLCriterion(), batch_size=30)
               .set_mesh(mesh8))
        with pytest.raises(ValueError, match="divisible"):
            opt.optimize()

    def test_bf16_wire_still_converges(self, mesh8):
        train = synthetic_mnist(256, seed=1)
        model = lenet.build(10).build(jax.random.PRNGKey(3))
        opt = (Optimizer(model, DataSet.array(train), nn.ClassNLLCriterion(),
                         batch_size=64)
               .set_optim_method(Adam(learningrate=2e-3))
               .set_end_when(Trigger.max_iteration(12))
               .set_mesh(mesh8))
        opt.log_every = 100
        trained = opt.optimize()
        res = Evaluator(trained).test(DataSet.array(train), [Top1Accuracy()], 64)
        assert res["Top1Accuracy"].result()[0] > 0.8
