"""Keras-style API tests (reference: the nn/keras layer wrappers +
Sequential compile/fit/evaluate/predict surface)."""

import numpy as np
import pytest

from bigdl_tpu import keras


def _toy_data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 2, n).astype(np.int32)
    xs = (rng.rand(n, 8, 8, 1) * 0.4 +
          ys[:, None, None, None] * 0.6).astype(np.float32)
    return xs, ys


class TestBuild:
    def test_shape_inference_chain(self):
        m = keras.Sequential([
            keras.Conv2D(4, 3, input_shape=(8, 8, 1), activation="relu"),
            keras.MaxPooling2D(2),
            keras.Flatten(),
            keras.Dense(10, activation="softmax"),
        ])
        module = m.build()
        assert m.output_shape == (10,)
        out = module.build().evaluate().forward(
            np.zeros((2, 8, 8, 1), np.float32))
        assert out.shape == (2, 10)

    def test_same_padding_conv(self):
        m = keras.Sequential([
            keras.Conv2D(3, 3, padding="same", input_shape=(7, 7, 2)),
        ])
        m.build()
        assert m.output_shape == (7, 7, 3)

    def test_first_layer_needs_shape(self):
        with pytest.raises(ValueError):
            keras.Sequential([keras.Dense(4)])

    def test_embedding_lstm(self):
        m = keras.Sequential([
            keras.Embedding(50, 8, input_length=12),
            keras.LSTM(16),
            keras.Dense(2, activation="log_softmax"),
        ])
        m.build()
        assert m.output_shape == (2,)
        out = m.module.build().evaluate().forward(
            np.zeros((3, 12), np.int32))
        assert out.shape == (3, 2)

    def test_summary(self):
        m = keras.Sequential([
            keras.Flatten(input_shape=(4, 4, 1)),
            keras.Dense(5),
        ])
        s = m.summary()
        assert "Flatten" in s and "(None, 5)" in s


class TestFit:
    def test_fit_evaluate_predict(self):
        xs, ys = _toy_data()
        m = keras.Sequential([
            keras.Conv2D(4, 3, input_shape=(8, 8, 1), activation="relu"),
            keras.MaxPooling2D(2),
            keras.Flatten(),
            keras.Dense(2),
        ])
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        # 60 epochs, not 30: at 30 the run is still mid-convergence and
        # seed-sensitive (measured 0.86/0.62/0.91 across data seeds
        # 0/1/2); at 60 every probed seed reaches 1.00, so the threshold
        # tests convergence, not optimizer luck
        m.fit(xs[:192], ys[:192], batch_size=64, epochs=60,
              validation_data=(xs[192:], ys[192:]))
        scores = m.evaluate(xs[192:], ys[192:])
        acc = scores["Top1Accuracy"]
        assert acc > 0.9, f"keras-API training failed: {acc}"
        preds = m.predict_classes(xs[192:200])
        assert preds.shape == (8,)
        assert (preds == ys[192:200]).mean() > 0.8


class TestExtraLayers:
    def test_conv3d_chain(self):
        m = keras.Sequential([
            keras.Conv3D(4, 2, input_shape=(4, 6, 6, 1),
                         activation="relu"),
            keras.MaxPooling3D(2),
            keras.Flatten(),
            keras.Dense(3),
        ])
        m.build()
        out = m.module.build().evaluate().forward(
            np.zeros((2, 4, 6, 6, 1), np.float32))
        assert out.shape == (2, 3)

    def test_upsampling(self):
        m = keras.Sequential([
            keras.UpSampling2D(2, input_shape=(3, 3, 2)),
        ])
        m.build()
        assert m.output_shape == (6, 6, 2)

    def test_global_max_pool(self):
        m = keras.Sequential([
            keras.GlobalMaxPooling2D(input_shape=(5, 5, 7)),
        ])
        m.build()
        assert m.output_shape == (7,)

    def test_gru_and_bidirectional(self):
        m = keras.Sequential([
            keras.Embedding(30, 8, input_length=10),
            keras.Bidirectional(keras.LSTM(12)),
            keras.Dense(2),
        ])
        m.build()
        assert m.output_shape == (2,)
        out = m.module.build().evaluate().forward(
            np.zeros((3, 10), np.int32))
        assert out.shape == (3, 2)

        m2 = keras.Sequential([
            keras.Embedding(30, 8, input_length=10),
            keras.GRU(6, return_sequences=True),
        ])
        m2.build()
        assert m2.output_shape == (10, 6)


class TestShapeLayers:
    def test_zero_padding_and_cropping(self):
        m = keras.Sequential([
            keras.ZeroPadding2D((1, 2), input_shape=(4, 4, 3)),
            keras.Cropping2D(((1, 0), (2, 1))),
        ])
        m.build()
        assert m.output_shape == (5, 5, 3)
        x = np.random.RandomState(0).rand(2, 4, 4, 3).astype(np.float32)
        out = m.module.build().evaluate().forward(x)
        assert out.shape == (2, 5, 5, 3)

    def test_permute(self):
        m = keras.Sequential([
            keras.Permute((2, 1, 3), input_shape=(3, 4, 5)),
        ])
        m.build()
        assert m.output_shape == (4, 3, 5)
        x = np.random.RandomState(0).rand(2, 3, 4, 5).astype(np.float32)
        out = np.asarray(m.module.build().evaluate().forward(x))
        np.testing.assert_allclose(out, x.transpose(0, 2, 1, 3))

    def test_permute_3cycle(self):
        m = keras.Sequential([
            keras.Permute((3, 1, 2), input_shape=(3, 4, 5)),
        ])
        m.build()
        assert m.output_shape == (5, 3, 4)
        x = np.random.RandomState(1).rand(1, 3, 4, 5).astype(np.float32)
        out = np.asarray(m.module.build().evaluate().forward(x))
        np.testing.assert_allclose(out, x.transpose(0, 3, 1, 2))

    def test_repeat_vector(self):
        m = keras.Sequential([
            keras.RepeatVector(5, input_shape=(7,)),
        ])
        m.build()
        assert m.output_shape == (5, 7)
        x = np.random.RandomState(0).rand(2, 7).astype(np.float32)
        out = np.asarray(m.module.build().evaluate().forward(x))
        np.testing.assert_allclose(out[:, 3], x)


class TestBidirectionalLastState:
    def test_backward_half_is_final_state(self):
        """Regression (ADVICE r1): with return_sequences=False the
        backward half must be the backward RNN's FINAL step (all frames
        seen). After BiRecurrent re-flips the backward stream to input
        order that step sits at t=0 — the old Select(2, -1) took the
        backward RNN's first step (one frame seen) instead."""
        import jax

        from bigdl_tpu import nn
        from bigdl_tpu.keras.layers_extra import _BiLastState

        rng = np.random.RandomState(5)
        x = rng.randn(3, 7, 4).astype(np.float32)

        bi = nn.BiRecurrent(nn.LSTM(4, 6), nn.LSTM(4, 6))
        variables = bi.init(jax.random.PRNGKey(9))
        seq, _ = bi.apply(variables, x)
        out, _ = _BiLastState(6).apply({"params": {}, "state": {}},
                                       seq)
        out = np.asarray(out)
        assert out.shape == (3, 12)

        # independent oracle: run each direction as a plain Recurrent
        # with the SAME params; Keras last-state = fwd final step concat
        # bwd final step (bwd runs on the reversed sequence)
        fwd = nn.Recurrent(nn.LSTM(4, 6))
        fwd_seq, _ = fwd.apply(
            {"params": variables["params"]["fwd"], "state": {}}, x)
        bwd = nn.Recurrent(nn.LSTM(4, 6))
        bwd_seq, _ = bwd.apply(
            {"params": variables["params"]["bwd"], "state": {}},
            x[:, ::-1])
        expect = np.concatenate(
            [np.asarray(fwd_seq)[:, -1], np.asarray(bwd_seq)[:, -1]],
            axis=-1)
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
        # and it must NOT equal the old Select(2, -1) result
        wrong = np.asarray(seq)[:, -1, :]
        assert not np.allclose(out, wrong)

    def test_keras_bidirectional_uses_last_state(self):
        """The built keras graph must end in _BiLastState, not Select."""
        from bigdl_tpu.keras.layers_extra import _BiLastState

        m = keras.Sequential([
            keras.Bidirectional(keras.LSTM(6), input_shape=(7, 4)),
        ])
        m.build()

        found = []

        def walk(mod):
            found.append(type(mod).__name__)
            for child in getattr(mod, "modules", []):
                walk(child)

        walk(m.module)
        assert "_BiLastState" in found
        assert "Select" not in found


class TestFunctionalModel:
    """keras.Model functional API (reference nn/keras Model wiring)."""

    def test_two_input_merge_train_predict(self):
        from bigdl_tpu.keras import Add, Dense, Input, Model

        rng = np.random.RandomState(0)
        a = Input(shape=(6,))
        b = Input(shape=(6,))
        x = Dense(8, activation="relu")(a)
        y = Dense(8, activation="relu")(b)
        z = Add()([x, y])
        out = Dense(3, activation="log_softmax")(z)
        model = Model(inputs=[a, b], outputs=out)
        assert model.output_shape == (3,)

        xa = rng.rand(64, 6).astype(np.float32)
        xb = rng.rand(64, 6).astype(np.float32)
        labels = rng.randint(0, 3, 64)
        model.compile("adam", "nll", metrics=["accuracy"])
        model.fit([xa, xb], labels, batch_size=16, epochs=2)
        preds = model.predict([xa[:8], xb[:8]])
        assert preds.shape == (8, 3)
        scores = model.evaluate([xa, xb], labels, batch_size=16)
        assert "Top1Accuracy" in scores

    def test_merge_layers_math(self):
        import jax

        from bigdl_tpu.keras import (Average, Concatenate, Dense, Input,
                                     Maximum, Model, Multiply, Subtract,
                                     merge)

        rng = np.random.RandomState(1)
        xa = rng.rand(4, 5).astype(np.float32)
        xb = rng.rand(4, 5).astype(np.float32)

        cases = [
            (Multiply(), xa * xb),
            (Subtract(), xa - xb),
            (Maximum(), np.maximum(xa, xb)),
            (Average(), (xa + xb) / 2),
            (Concatenate(), np.concatenate([xa, xb], axis=1)),
        ]
        for layer, want in cases:
            a, b = Input(shape=(5,)), Input(shape=(5,))
            m = Model([a, b], layer([a, b]))
            g = m.module.build(jax.random.PRNGKey(0))
            got, _ = g.apply(g.variables, xa, xb)
            np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6,
                                       atol=1e-6)

        a, b = Input(shape=(5,)), Input(shape=(5,))
        m = Model([a, b], merge([a, b], mode="sum"))
        g = m.module.build(jax.random.PRNGKey(0))
        got, _ = g.apply(g.variables, xa, xb)
        np.testing.assert_allclose(np.asarray(got), xa + xb, rtol=1e-6)

    def test_shared_graph_reuse_and_diamond(self):
        import jax

        from bigdl_tpu.keras import Add, Dense, Input, Model

        # diamond: one input feeding two branches merged back
        inp = Input(shape=(4,))
        h = Dense(4, activation="relu")(inp)
        z = Add()([h, inp])  # residual-style
        m = Model(inp, Dense(2)(z))
        g = m.module.build(jax.random.PRNGKey(0))
        out, _ = g.apply(g.variables,
                         np.ones((3, 4), np.float32))
        assert np.asarray(out).shape == (3, 2)

    def test_errors(self):
        from bigdl_tpu.keras import Add, Dense, Input, Model, merge

        a = Input(shape=(4,))
        b = Input(shape=(3,))
        with pytest.raises(ValueError, match="identical shapes"):
            Add()([a, b])
        with pytest.raises(TypeError, match="merge layer"):
            Dense(2)([a, b])
        with pytest.raises(ValueError, match="unknown merge mode"):
            merge([a, a], mode="frobnicate")

    def test_layer_reuse_shares_weights(self):
        import jax

        from bigdl_tpu.keras import Add, Dense, Input, Model

        # Keras functional contract: one layer instance called twice is
        # ONE set of weights (siamese towers)
        a, b = Input(shape=(5,)), Input(shape=(5,))
        shared = Dense(4)
        m = Model([a, b], Add()([shared(a), shared(b)]))
        g = m.module.build(jax.random.PRNGKey(0))
        dense_keys = [k for k in g.variables["params"] if "Linear" in k]
        assert len(dense_keys) == 1, dense_keys
        # symmetric by construction: f(x,y) == f(y,x)
        xa = np.random.RandomState(0).rand(3, 5).astype(np.float32)
        xb = np.random.RandomState(1).rand(3, 5).astype(np.float32)
        o1, _ = g.apply(g.variables, xa, xb)
        o2, _ = g.apply(g.variables, xb, xa)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-6)
        # shape mismatch on reuse is an error, not silent new weights
        c = Input(shape=(7,))
        with pytest.raises(ValueError, match="same input shape"):
            shared(c)

    def test_concatenate_axis_out_of_range(self):
        from bigdl_tpu.keras import Concatenate, Input

        a, b = Input(shape=(5,)), Input(shape=(5,))
        with pytest.raises(ValueError, match="out of range"):
            Concatenate(axis=-2)([a, b])
