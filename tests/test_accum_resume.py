"""Checkpoint-safe gradient accumulation (VERDICT r2 #7).

A checkpoint taken mid-accumulation-cycle persists the partial gradient
accumulator and micro-batch count; resuming from it — through either
optimizer — reproduces the uninterrupted run BIT-FOR-BIT. The data
stream is re-aligned on resume by fast-forwarding the deterministic
epoch permutations (optim.optimizer._batch_iterator skip).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.parallel import make_mesh

KEY = jax.random.PRNGKey(3)


def _samples(n=64, dim=6, classes=4, seed=11):
    rng = np.random.RandomState(seed)
    return [Sample(rng.rand(dim).astype(np.float32),
                   int(rng.randint(0, classes)))
            for _ in range(n)]


def _model():
    return nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4),
                         nn.LogSoftMax()).build(KEY)


def _flat(model):
    return np.concatenate([np.ravel(np.asarray(a))
                           for _, a in model.parameters()])


def _train(tmp_path, mesh, end_iter, ckpt_iter=None, resume=False,
           tag="run"):
    opt = (Optimizer(_model(), DataSet.array(_samples()),
                     nn.ClassNLLCriterion(), batch_size=8)
           .set_optim_method(Adam(learningrate=1e-2))
           .set_gradient_accumulation(4)
           .set_end_when(Trigger.max_iteration(end_iter)))
    if ckpt_iter is not None:
        opt.set_checkpoint(str(tmp_path / tag),
                           Trigger.several_iteration(ckpt_iter))
    if resume:
        opt.resume_from_checkpoint()
    if mesh is not None:
        opt.set_mesh(mesh)
    return opt.optimize()


@pytest.mark.parametrize("use_mesh", [False, True])
def test_midcycle_resume_bitwise(tmp_path, use_mesh):
    mesh = make_mesh({"data": 8}) if use_mesh else None

    # uninterrupted: 10 micro-batches (updates at 4, 8; flush of 9-10)
    ref = _flat(_train(tmp_path, mesh, end_iter=10))

    # interrupted at 6 (mid-cycle: micro 5,6 pending) + resumed to 10
    _train(tmp_path, mesh, end_iter=6, ckpt_iter=6, tag="ck")
    resumed = _flat(_train(tmp_path, mesh, end_iter=10, ckpt_iter=6,
                           resume=True, tag="ck"))

    np.testing.assert_array_equal(ref, resumed)


def test_boundary_resume_bitwise(tmp_path):
    """Checkpoint at an update boundary (iteration 8 with accum=4) has
    no accum sidecar and still resumes bit-for-bit."""
    ref = _flat(_train(tmp_path, None, end_iter=12))
    _train(tmp_path, None, end_iter=8, ckpt_iter=8, tag="ckb")
    ck_dir = tmp_path / "ckb" / "checkpoint-8"
    assert not (ck_dir / "accum.json").exists()
    resumed = _flat(_train(tmp_path, None, end_iter=12, ckpt_iter=8,
                           resume=True, tag="ckb"))
    np.testing.assert_array_equal(ref, resumed)


def test_stale_accum_sidecar_removed_on_reuse(tmp_path):
    """Re-saving into an existing checkpoint-{step} dir at an update
    boundary must remove a previous run's mid-cycle accum sidecar —
    loading it would install foreign gradients."""
    _train(tmp_path, None, end_iter=6, ckpt_iter=6, tag="st")
    ck = tmp_path / "st" / "checkpoint-6"
    assert (ck / "accum.json").exists()

    # fresh run, same path, checkpoint at the same step but accum=1
    opt = (Optimizer(_model(), DataSet.array(_samples()),
                     nn.ClassNLLCriterion(), batch_size=8)
           .set_optim_method(Adam(learningrate=1e-2))
           .set_end_when(Trigger.max_iteration(6))
           .set_checkpoint(str(tmp_path / "st"),
                           Trigger.several_iteration(6)))
    opt.optimize()
    assert not (ck / "accum.json").exists()
    assert not (ck / "accum.npz").exists()


def test_shrunk_grad_accum_restarts_cycle(tmp_path):
    """Resume with a SMALLER grad_accum than the checkpointed cycle:
    the saved accumulator cannot fit (n >= accum would never trigger an
    update again) — it is discarded with a warning and training still
    makes updates."""
    _train(tmp_path, None, end_iter=7, ckpt_iter=7, tag="sh")  # micro_n=3
    before = _flat(_train(tmp_path, None, end_iter=7, ckpt_iter=7,
                          resume=True, tag="sh"))  # reload state only
    opt = (Optimizer(_model(), DataSet.array(_samples()),
                     nn.ClassNLLCriterion(), batch_size=8)
           .set_optim_method(Adam(learningrate=1e-2))
           .set_gradient_accumulation(2)
           .set_end_when(Trigger.max_iteration(11))
           .set_checkpoint(str(tmp_path / "sh"),
                           Trigger.several_iteration(100)))
    opt.resume_from_checkpoint()
    m = opt.optimize()
    after = _flat(m)
    assert np.isfinite(after).all()
    # updates happened after resume (params moved from the checkpoint)
    assert not np.array_equal(before, after)


def test_mesh_size_change_midcycle_resume(tmp_path):
    """Mid-cycle ZeRO-1 checkpoint from an 8-device mesh resumes on a
    4-device mesh: the flat accumulator is re-padded like the slots."""
    from jax.sharding import Mesh

    mesh4 = Mesh(np.array(jax.devices()[:4]), ("data",))
    _train(tmp_path, make_mesh({"data": 8}), end_iter=6, ckpt_iter=6,
           tag="mz")
    m = _train(tmp_path, mesh4, end_iter=10,
               ckpt_iter=6, resume=True, tag="mz")
    assert np.isfinite(_flat(m)).all()


def test_cross_optimizer_midcycle_resume(tmp_path):
    """A mid-cycle LocalOptimizer checkpoint resumes on the mesh (the
    pytree accumulator is flattened into the ZeRO-1 layout) and the
    other way round — losses stay finite and training completes."""
    mesh = make_mesh({"data": 8})
    _train(tmp_path, None, end_iter=6, ckpt_iter=6, tag="x1")
    m1 = _train(tmp_path, mesh, end_iter=10, ckpt_iter=6, resume=True,
                tag="x1")
    assert np.isfinite(_flat(m1)).all()

    _train(tmp_path, mesh, end_iter=6, ckpt_iter=6, tag="x2")
    m2 = _train(tmp_path, None, end_iter=10, ckpt_iter=6, resume=True,
                tag="x2")
    assert np.isfinite(_flat(m2)).all()
