"""Unified telemetry plane (ISSUE 5): registry determinism under an
injected clock, histogram percentiles vs a numpy oracle, the
structured event log (ring/sink/schema), Chrome-trace span export
(pure-parse), the serving compile-count guard re-run with telemetry
fully enabled, and the single training emission path."""

import json

import numpy as np
import pytest

from bigdl_tpu import obs


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Every test gets fresh registry/log/tracer and telemetry ON;
    global state never leaks between tests."""
    prev = obs.set_enabled(True)
    obs.reset_all()
    yield
    obs.reset_all()
    obs.set_enabled(prev)


# ------------------------------------------------------------- registry

def test_registry_deterministic_under_injected_clock():
    """Identical metric activity + injected clock → byte-identical
    snapshot JSON and Prometheus text, run to run (what makes drill
    telemetry assertable bit-for-bit)."""
    def run():
        reg = obs.set_registry(obs.MetricsRegistry(clock=lambda: 7.0))
        c = reg.counter("req_total", "requests", ("status",))
        c.labels(status="done").inc(3)
        c.labels(status="shed").inc()
        reg.gauge("depth", "queue depth").set(4)
        h = reg.histogram("lat_seconds", "latency")
        for v in (0.002, 0.011, 0.4, 0.011):
            h.observe(v)
        return reg.to_json(), reg.render_prometheus()
    a, b = run(), run()
    assert a == b
    # label/name ordering is sorted, not insertion-dependent
    reg = obs.set_registry(obs.MetricsRegistry(clock=lambda: 7.0))
    c = reg.counter("req_total", "requests", ("status",))
    c.labels(status="shed").inc()           # reversed insertion order
    c.labels(status="done").inc(3)
    reg.gauge("depth", "queue depth").set(4)
    h = reg.histogram("lat_seconds", "latency")
    for v in (0.011, 0.4, 0.002, 0.011):    # permuted observations
        h.observe(v)
    assert reg.to_json() == a[0]


def test_registry_schema_conflicts_raise():
    reg = obs.get_registry()
    reg.counter("a_total", "x", ("k",))
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("a_total")
    with pytest.raises(ValueError, match="labelnames mismatch"):
        reg.counter("a_total", "x", ("other",))
    reg.histogram("h_seconds", buckets=(1.0, 2.0))
    with pytest.raises(ValueError, match="bucket mismatch"):
        reg.histogram("h_seconds", buckets=(1.0, 3.0))
    with pytest.raises(ValueError, match="only go up"):
        reg.counter("b_total").inc(-1)
    with pytest.raises(ValueError, match="do not match"):
        reg.counter("a_total", "x", ("k",)).labels(wrong="v")


def test_histogram_percentiles_vs_numpy_oracle():
    """Bucket-interpolated quantiles must track np.quantile within one
    bucket width, across distributions."""
    edges = tuple(np.linspace(0.01, 1.0, 100))     # width 0.01
    rng = np.random.RandomState(0)
    for data in (rng.uniform(0, 1, 2000),
                 rng.beta(2, 5, 2000),             # skewed low
                 rng.beta(5, 1, 2000)):            # skewed high
        reg = obs.set_registry(obs.MetricsRegistry())
        h = reg.histogram("h", buckets=edges)
        for v in data:
            h.observe(float(v))
        for q in (0.1, 0.5, 0.9, 0.95, 0.99):
            est = h.quantile(q)
            oracle = float(np.quantile(data, q))
            assert abs(est - oracle) <= 0.011, (q, est, oracle)
    # degenerate cases
    reg = obs.set_registry(obs.MetricsRegistry())
    h = reg.histogram("h2", buckets=(0.1, 1.0))
    assert h.quantile(0.5) is None                 # empty
    h.observe(5.0)                                 # +Inf bucket
    assert h.quantile(0.99) == 1.0                 # clamps to top edge
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_prometheus_exposition_format():
    reg = obs.get_registry()
    reg.counter("req_total", "reqs", ("status",)).labels(
        status="done").inc(2)
    reg.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.05)
    text = reg.render_prometheus()
    assert "# TYPE req_total counter" in text
    assert 'req_total{status="done"} 2' in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_count 1" in text


# ------------------------------------------------------------ event log

def test_event_log_ring_sink_and_schema(tmp_path):
    path = tmp_path / "ev.jsonl"
    log = obs.set_event_log(obs.EventLog(capacity=4, path=str(path),
                                         clock=lambda: 9.0))
    for i in range(6):
        obs.emit_event("tick", i=i)
    # ring keeps the newest `capacity` records; seq keeps counting
    assert len(log) == 4
    assert [e["i"] for e in log.events("tick")] == [2, 3, 4, 5]
    assert [e["seq"] for e in log.events()] == [2, 3, 4, 5]
    assert all(e["schema"] == 1 and e["ts"] == 9.0
               for e in log.events())
    # the file sink kept ALL records (ring bounds memory, not disk)
    ondisk = obs.read_jsonl(str(path))
    assert [e["i"] for e in ondisk] == list(range(6))
    # field filtering
    assert log.events("tick", i=3)[0]["seq"] == 3
    assert log.events("other") == []
    assert log.counts_by_kind() == {"tick": 4}
    log.close()
    # torn final line (crash mid-write) is dropped, not an error
    with open(path, "a") as f:
        f.write('{"schema": 1, "kind": "to')
    assert len(obs.read_jsonl(str(path))) == 6


def test_event_log_disabled_emits_nothing():
    obs.set_enabled(False)
    assert obs.emit_event("x") is None
    assert len(obs.get_event_log()) == 0
    obs.set_enabled(True)
    assert obs.emit_event("x")["kind"] == "x"


# ---------------------------------------------------------------- spans

def test_span_tracer_chrome_trace_parses(tmp_path):
    """Span JSON must satisfy the chrome://tracing schema: a
    traceEvents array of objects with name/ph/ts/pid/tid, "X" events
    carrying dur — asserted on a re-parsed file (pure parse)."""
    clk = {"t": 1.0}

    def clock():
        clk["t"] += 0.5
        return clk["t"]

    tr = obs.set_tracer(obs.SpanTracer(clock=clock, enabled=True))
    with tr.span("prefill", cat="serving", args={"slot": 0}):
        pass
    tr.instant("poisoned", cat="serving")
    tr.complete("queued", "serving", 0.25, 1.5, args={"request": 7})
    path = str(tmp_path / "trace.json")
    tr.save(path)
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and len(evs) == 3
    for e in evs:
        assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(e)
        assert e["ph"] in ("X", "i")
        if e["ph"] == "X":
            assert e["dur"] >= 0
    x = [e for e in evs if e["name"] == "prefill"][0]
    assert x["ts"] == pytest.approx(1.5e6)        # seconds → µs
    assert x["dur"] == pytest.approx(0.5e6)
    assert x["args"] == {"slot": 0}
    q = [e for e in evs if e["name"] == "queued"][0]
    assert q["dur"] == pytest.approx(1.25e6)


def test_span_tracer_disabled_is_noop():
    tr = obs.get_tracer()
    assert not tr.enabled
    with tr.span("x"):
        pass
    tr.instant("y")
    tr.complete("z", "c", 0.0, 1.0)
    assert tr.to_chrome_trace()["traceEvents"] == []


# ------------------------------------------- serving: guard + telemetry

def _tiny_lm():
    import jax

    from bigdl_tpu.models.transformer import build_lm

    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=1,
                 max_len=64)
    m.build(jax.random.PRNGKey(0))
    return m


def test_compile_guard_with_telemetry_enabled():
    """The zero-recompile contract with EVERY telemetry path armed —
    registry mirrors, event log, span tracer: still exactly (#buckets
    used) prefill traces + 1 decode trace, because telemetry is
    host-side by construction. health() percentiles come from the
    fixed-bucket histogram and the event log carries the request
    lifecycle."""
    from bigdl_tpu.serving import InferenceEngine, Request

    obs.set_tracer(obs.SpanTracer(enabled=True))
    log = obs.get_event_log()
    m = _tiny_lm()
    eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16))
    rng = np.random.RandomState(0)
    reqs = [Request(prompt=list(rng.randint(1, 50, n)),
                    max_new_tokens=3) for n in (3, 10, 6, 12)]
    res = eng.run(reqs)
    assert all(r.status == "done" for r in res)
    assert eng.stats["prefill_traces"] == 2       # both buckets
    assert eng.stats["decode_traces"] == 1        # ONE executable
    # second wave with telemetry still on: nothing new compiles
    res2 = eng.run([Request(prompt=[1, 2, 3], max_new_tokens=2)])
    assert eng.stats["prefill_traces"] == 2
    assert eng.stats["decode_traces"] == 1
    # health: histogram-backed percentiles + registry view
    h = eng.health()
    assert h["decode_p50_ms"] is not None
    assert h["metrics"]["decode_step_seconds"]["count"] == \
        eng.stats["decode_steps"]
    assert h["metrics"]["requests_total"]["done"] == 5
    # events: one submit + one terminal per request
    assert len(log.events("request_submit")) == 5
    done = log.events("request_terminal", status="done")
    assert len(done) == 5
    assert sum(e["tokens"] for e in done) == \
        sum(len(r.tokens) for r in res) + len(res2[0].tokens)
    # spans: queued/prefill per admission, decode_step per step,
    # request[...] per terminal — all in one coherent trace doc
    tr = obs.get_tracer()
    assert len(tr.events("prefill")) == 5
    assert len(tr.events("queued")) == 5
    assert len(tr.events("decode_step")) == eng.stats["decode_steps"]
    assert len(tr.events("request[done]")) == 5
    doc = json.loads(json.dumps(tr.to_chrome_trace()))
    assert all(e["ph"] in ("X", "i") for e in doc["traceEvents"])


def test_engine_metrics_off_keeps_core_bookkeeping():
    """BIGDL_OBS=off: stats AND health() — including the latency
    percentiles, which are core bookkeeping fed unconditionally —
    still work; events, spans, and counter mirrors stay silent."""
    from bigdl_tpu.serving import InferenceEngine, Request

    obs.set_enabled(False)
    obs.set_tracer(obs.SpanTracer(enabled=True))  # still muted by off
    m = _tiny_lm()
    eng = InferenceEngine(m, slots=1, prefill_buckets=(8,))
    res = eng.run([Request(prompt=[1, 2, 3], max_new_tokens=2)])[0]
    assert res.status == "done"
    assert eng.stats["requests_done"] == 1
    h = eng.health()
    assert h["requests_done"] == 1
    assert h["decode_p50_ms"] is not None         # core, not telemetry
    assert h["metrics"]["decode_step_seconds"]["count"] == \
        eng.stats["decode_steps"]
    assert len(obs.get_event_log()) == 0
    assert obs.get_tracer().to_chrome_trace()["traceEvents"] == []
    # counter MIRRORS are gated (the _stats dict is the core copy)
    snap = obs.get_registry().snapshot()["metrics"]
    assert "serving_requests_total" not in snap \
        or all(s["value"] == 0
               for s in snap["serving_requests_total"]["series"])


# ------------------------------------------------------- training plane

def test_step_telemetry_single_emission_path():
    """One emit_step call fans out to registry + event log + summary
    sink — the duplicate Loss/Throughput bookkeeping the satellites
    called out is structurally gone."""
    from bigdl_tpu.obs.training import StepTelemetry

    sunk = []

    class Sink:
        def add_scalar(self, tag, value, step):
            sunk.append((tag, float(value), step))

        def add_histogram(self, tag, values, step):
            sunk.append(("hist:" + tag, None, step))

    t = StepTelemetry(summary=Sink())
    t.emit_step(epoch=1, step=3, loss=0.5, lr=0.01, throughput=100.0,
                records=8, gnorm=2.0,
                hists=[("w", np.zeros(3))], metrics_summary="")
    t.emit_step(epoch=1, step=4, loss=0.4, lr=0.01, throughput=110.0,
                records=8, update_applied=False, metrics_summary="")
    assert ("Loss", 0.5, 3) in sunk and ("LearningRate", 0.01, 3) in sunk
    assert ("hist:w", None, 3) in sunk
    snap = obs.get_registry().snapshot()["metrics"]
    assert snap["training_steps_total"]["series"][0]["value"] == 2
    assert snap["training_updates_applied_total"]["series"][0][
        "value"] == 1
    assert snap["training_loss"]["series"][0]["value"] == 0.4
    evs = obs.get_event_log().events("train_step")
    assert [e["step"] for e in evs] == [3, 4]
    assert evs[0]["gnorm"] == 2.0 and "gnorm" not in evs[1]
    assert not evs[1]["update_applied"]
    # piggyback contract: a non-fence step passes loss=None — the
    # event still records every host-side field, omits loss, and the
    # summary sink/log line (which need the fetch) are skipped
    n_sunk = len(sunk)
    t.emit_step(epoch=1, step=5, loss=None, lr=0.01,
                throughput=120.0, records=8, metrics_summary="")
    ev = obs.get_event_log().events("train_step", step=5)[0]
    assert "loss" not in ev and ev["throughput"] == 120.0
    assert len(sunk) == n_sunk
    snap = obs.get_registry().snapshot()["metrics"]
    assert snap["training_loss"]["series"][0]["value"] == 0.4  # kept
    assert snap["training_steps_total"]["series"][0]["value"] == 3


def test_set_event_log_closes_replaced_sink(tmp_path):
    """Replacing the active log must close the old file sink (no fd
    leak across resets) while keeping its ring readable — and a fresh
    default re-attaches the BIGDL_OBS_EVENTS sink in append mode."""
    path = tmp_path / "a.jsonl"
    old = obs.set_event_log(obs.EventLog(path=str(path)))
    obs.emit_event("x")
    obs.set_event_log(obs.EventLog())
    assert old._sink is None                  # closed on replacement
    assert old.events("x")                    # ring still readable
    assert obs.set_event_log(obs.get_event_log()) is not None  # no-op


def test_metrics_timers_feed_registry_and_tracer():
    from bigdl_tpu.optim.metrics import Metrics, Timer

    obs.set_tracer(obs.SpanTracer(enabled=True))
    m = Metrics()
    with Timer(m, "data_fetch_s"):
        pass
    with Timer(m, "dispatch_s"):
        pass
    m.set("lr", 0.1)
    snap = obs.get_registry().snapshot()["metrics"]
    phases = {s["labels"]["phase"]: s["count"]
              for s in snap["training_phase_seconds"]["series"]}
    assert phases == {"data_fetch_s": 1, "dispatch_s": 1}
    gauges = {s["labels"]["name"]: s["value"]
              for s in snap["training_metric"]["series"]}
    assert gauges == {"lr": 0.1}
    names = {e["name"] for e in obs.get_tracer().events()}
    assert names == {"data_fetch", "dispatch"}
    # the local running-mean view is unchanged
    assert "data_fetch_s=" in m.summary()


def test_provenance_compact_view():
    reg = obs.get_registry()
    reg.counter("serving_x_total", "x", ("engine",)).labels(
        engine="engine0").inc(4)
    reg.histogram("serving_lat_seconds").observe(0.01)
    reg.counter("training_steps_total").inc()
    p = obs.provenance("serving_")
    assert p["telemetry"] == "on"
    assert p["metrics"]["serving_x_total{engine=engine0}"] == 4
    assert p["metrics"]["serving_lat_seconds"]["count"] == 1
    assert "training_steps_total" not in p["metrics"]
    assert "training_steps_total" in obs.provenance()["metrics"]


# ------------------------------------------------------------ obs_report

def _load_report():
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "obs_report.py")
    spec = importlib.util.spec_from_file_location("obs_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_summarize_and_render(tmp_path, capsys):
    """obs_report digests a JSONL file: counts, training/serving
    summaries, percentiles from an embedded metrics snapshot."""
    path = tmp_path / "run.jsonl"
    obs.set_event_log(obs.EventLog(path=str(path), clock=lambda: 1.0))
    for i in range(3):
        obs.emit_event("train_step", plane="training", epoch=1,
                       step=i + 1, loss=1.0 - 0.1 * i, lr=0.01,
                       throughput=100.0, update_applied=i != 1)
    obs.emit_event("anomaly", plane="training", step=2,
                   action="skipped", policy="skip_step", gnorm=0.0)
    obs.emit_event("fault_injected", fault="nan", step=2)
    obs.emit_event("request_terminal", plane="serving",
                   engine="engine0", request=0, status="done",
                   reason="max_tokens", tokens=5)
    obs.emit_event("request_terminal", plane="serving",
                   engine="engine0", request=1, status="poisoned",
                   reason="poisoned", tokens=2)
    obs.get_registry().histogram("serving_decode_step_seconds",
                                 labelnames=("engine",)).labels(
        engine="engine0").observe(0.02)
    obs.log_metrics_snapshot()
    obs.get_event_log().close()

    rep = _load_report()
    s = rep.summarize(rep.read_jsonl(str(path))
                      if hasattr(rep, "read_jsonl")
                      else obs.read_jsonl(str(path)))
    assert s["training"]["steps"] == 3
    assert s["training"]["updates_applied"] == 2
    assert s["training"]["anomalies"] == 1
    assert s["serving"]["by_status"] == {"done": 1, "poisoned": 1}
    assert s["serving"]["tokens_generated"] == 7
    assert s["faults"] == ["nan@2"]
    lat = s["metrics"][
        "serving_decode_step_seconds{engine=engine0}"]
    assert lat["count"] == 1 and lat["p50"] is not None
    # quantile helper matches the registry estimator
    assert rep.quantile_from_buckets([1.0, 2.0], [1, 1, 0], 0.5) \
        == pytest.approx(1.0)
    assert rep.quantile_from_buckets([1.0], [0, 0], 0.5) is None
    # CLI renders and exits 0
    assert rep.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "training:" in out and "serving:" in out
    assert "status poisoned" in out
    assert rep.main([str(tmp_path / "missing.jsonl")]) == 2

def test_obs_report_checkpoint_section(tmp_path, capsys):
    """ISSUE 9: the checkpoint digest — save cadence and durations
    from the enriched checkpoint_save events (async/duration_s/shard/
    nshards), shard-unit tally, corrupt-skip count, and the
    training_checkpoint_seconds histogram from the snapshot."""
    path = tmp_path / "run.jsonl"
    obs.set_event_log(obs.EventLog(path=str(path), clock=lambda: 1.0))
    h = obs.get_registry().histogram(
        "training_checkpoint_seconds", "save seconds", ("mode",))
    for step, dur in ((3, 0.010), (6, 0.030), (9, 0.020)):
        for shard in range(2):
            obs.emit_event("checkpoint_save", step=step, path=f"c-{step}",
                           **{"async": True}, duration_s=dur / 4,
                           nshards=2, shard=shard)
        obs.emit_event("checkpoint_save", step=step, path=f"c-{step}",
                       **{"async": True}, duration_s=dur, nshards=2,
                       mid_cycle=False)
        h.labels(mode="async").observe(dur)
    obs.emit_event("checkpoint_corrupt_skipped", path="c-9",
                   error="crc mismatch")
    obs.emit_event("checkpoint_load", path="c-6", sharded=True, nshards=2)
    obs.log_metrics_snapshot()
    obs.get_event_log().close()

    rep = _load_report()
    s = rep.summarize(obs.read_jsonl(str(path)))
    c = s["checkpoints"]
    assert c["saves"] == 3 and c["async_saves"] == 3
    assert c["shard_unit_writes"] == 6 and c["nshards"] == 2
    assert c["save_cadence_steps"] == 3.0
    assert c["loads"] == 1 and c["sharded_loads"] == 1
    assert c["corrupt_skipped"] == 1
    assert c["save_duration_p50_s"] == pytest.approx(0.020)
    assert c["save_duration_max_s"] == pytest.approx(0.030)
    hist = c["histogram"]["async"]
    assert hist["count"] == 3 and hist["p50_s"] is not None
    assert rep.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "checkpoints:" in out and "save_cadence_steps" in out
    assert "async save (hist)" in out
