"""LBFGS convergence tests (reference: optim/LBFGSSpec — tiny synthetic
problems)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.optim.lbfgs import LBFGS


def test_quadratic():
    A = jnp.asarray([[3.0, 0.5], [0.5, 1.0]])
    b = jnp.asarray([1.0, -2.0])

    def f(x):
        return 0.5 * x @ A @ x - b @ x

    x, fx, it = LBFGS(max_iter=50).minimize(f, jnp.zeros(2))
    ref = jnp.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref), atol=1e-4)


def test_rosenbrock():
    def f(p):
        x, y = p[0], p[1]
        return (1 - x) ** 2 + 100.0 * (y - x * x) ** 2

    # Armijo-only backtracking needs more iterations than strong-Wolfe
    # on Rosenbrock's curved valley (converges exactly at ~670)
    x, fx, it = LBFGS(max_iter=800, history_size=10).minimize(
        f, jnp.asarray([-1.2, 1.0]))
    np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=1e-3)
    assert float(fx) < 1e-6


def test_under_jit():
    def f(x):
        return jnp.sum((x - 3.0) ** 2)

    @jax.jit
    def run(x0):
        return LBFGS(max_iter=30).minimize(f, x0)

    x, fx, it = run(jnp.zeros(5))
    np.testing.assert_allclose(np.asarray(x), 3.0, atol=1e-5)
    assert int(it) < 30  # converged early


def test_fits_tiny_net_on_xor():
    from bigdl_tpu import nn

    model = nn.Sequential(nn.Linear(2, 8), nn.Tanh(), nn.Linear(8, 1))
    variables = model.init(jax.random.PRNGKey(0))
    x = jnp.asarray([[0, 0], [0, 1], [1, 0], [1, 1]], jnp.float32)
    y = jnp.asarray([[0.0], [1.0], [1.0], [0.0]])

    def feval(params):
        out, _ = model.apply({"params": params,
                              "state": variables["state"]}, x)
        return jnp.mean((out - y) ** 2)

    params, fx, it = LBFGS(max_iter=200).minimize(
        feval, variables["params"])
    assert float(fx) < 1e-3, float(fx)
