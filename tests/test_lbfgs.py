"""LBFGS convergence tests (reference: optim/LBFGSSpec — tiny synthetic
problems)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.optim.lbfgs import LBFGS


def test_quadratic():
    A = jnp.asarray([[3.0, 0.5], [0.5, 1.0]])
    b = jnp.asarray([1.0, -2.0])

    def f(x):
        return 0.5 * x @ A @ x - b @ x

    x, fx, it = LBFGS(max_iter=50).minimize(f, jnp.zeros(2))
    ref = jnp.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref), atol=1e-4)


def test_rosenbrock():
    def f(p):
        x, y = p[0], p[1]
        return (1 - x) ** 2 + 100.0 * (y - x * x) ** 2

    # default strong-Wolfe converges in ~33 iterations (Armijo: ~670)
    x, fx, it = LBFGS(max_iter=100, history_size=10).minimize(
        f, jnp.asarray([-1.2, 1.0]))
    np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=1e-3)
    assert float(fx) < 1e-6


def test_under_jit():
    def f(x):
        return jnp.sum((x - 3.0) ** 2)

    @jax.jit
    def run(x0):
        return LBFGS(max_iter=30).minimize(f, x0)

    x, fx, it = run(jnp.zeros(5))
    np.testing.assert_allclose(np.asarray(x), 3.0, atol=1e-5)
    assert int(it) < 30  # converged early


def test_fits_tiny_net_on_xor():
    from bigdl_tpu import nn

    model = nn.Sequential(nn.Linear(2, 8), nn.Tanh(), nn.Linear(8, 1))
    variables = model.init(jax.random.PRNGKey(0))
    x = jnp.asarray([[0, 0], [0, 1], [1, 0], [1, 1]], jnp.float32)
    y = jnp.asarray([[0.0], [1.0], [1.0], [0.0]])

    def feval(params):
        out, _ = model.apply({"params": params,
                              "state": variables["state"]}, x)
        return jnp.mean((out - y) ** 2)

    params, fx, it = LBFGS(max_iter=200).minimize(
        feval, variables["params"])
    assert float(fx) < 1e-3, float(fx)


def test_wolfe_curvature_condition_holds():
    """At the accepted step the STRONG Wolfe conditions hold: sufficient
    decrease and |g(t)·d| <= c2·|g0·d| (reference: LineSearch.lswolfe)."""
    from bigdl_tpu.optim.lbfgs import _strong_wolfe

    A = jnp.asarray([[5.0, 1.0], [1.0, 2.0]])

    def f(x):
        return 0.5 * x @ A @ x + jnp.sum(jnp.cos(x))

    vg = jax.value_and_grad(f)
    x0 = jnp.asarray([2.0, -3.0])
    f0, g0 = vg(x0)
    d = -g0
    gtd0 = jnp.dot(g0, d)
    c1, c2 = 1e-4, 0.9
    t, ft, gt, nev = _strong_wolfe(vg, x0, jnp.asarray(1.0), d, f0, g0,
                                   gtd0, c1, c2, 25)
    assert float(t) > 0.0
    assert float(ft) <= float(f0 + c1 * t * gtd0) + 1e-6
    assert abs(float(jnp.dot(gt, d))) <= c2 * abs(float(gtd0)) + 1e-6
    # the returned f/g really are f(x+td)
    f_chk, g_chk = vg(x0 + t * d)
    np.testing.assert_allclose(float(ft), float(f_chk), rtol=1e-6)
    assert int(nev) >= 1


def test_wolfe_exhausted_bracket_never_ascends():
    """Exhausting the eval budget during the bracket (extrapolation)
    phase must not accept a point that fails sufficient decrease — the
    search falls back to the last Armijo-satisfying point (worst case a
    zero step), never an ascent."""
    from bigdl_tpu.optim.lbfgs import _strong_wolfe

    def f(x):
        # steep wall just past t=1: extrapolation lands uphill
        t = x[0]
        return -t + jnp.where(t > 1.005, 5e3 * (t - 1.005) ** 2, 0.0)

    vg = jax.value_and_grad(f)
    x0 = jnp.asarray([0.0])
    f0, g0 = vg(x0)
    d = jnp.asarray([1.0])
    gtd0 = jnp.dot(g0, d)
    t, ft, gt, nev = _strong_wolfe(vg, x0, jnp.asarray(1.0), d, f0, g0,
                                   gtd0, 1e-4, 0.9, 2)
    assert float(ft) <= float(f0) + 1e-6, "accepted an ascent step"


def test_wolfe_beats_armijo_on_rosenbrock():
    """Strong-Wolfe converges on Rosenbrock in fewer function
    evaluations than Armijo backtracking (the point of lswolfe)."""
    def f(p):
        x, y = p[0], p[1]
        return (1 - x) ** 2 + 100.0 * (y - x * x) ** 2

    x0 = jnp.asarray([-1.2, 1.0])

    wolfe = LBFGS(max_iter=800, line_search="wolfe")
    xw, fw, itw = wolfe.minimize(f, x0)
    armijo = LBFGS(max_iter=800, line_search="armijo")
    xa, fa, ita = armijo.minimize(f, x0)

    np.testing.assert_allclose(np.asarray(xw), [1.0, 1.0], atol=1e-3)
    assert float(fw) < 1e-6
    assert int(wolfe.evals) < int(armijo.evals), \
        (int(wolfe.evals), int(armijo.evals))
    assert int(itw) <= int(ita)
