"""Recurrent layer tests (reference: nn/LSTMSpec, GRUSpec, RecurrentSpec)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn.recurrent import (
    LSTM, GRU, RnnCell, LSTMPeephole, Recurrent, BiRecurrent, TimeDistributed,
)

KEY = jax.random.PRNGKey(0)


class TestRecurrent:
    def test_rnn_shapes(self):
        m = Recurrent(RnnCell(3, 5)).build(KEY).evaluate()
        out = m.forward(jnp.ones((2, 7, 3)))
        assert out.shape == (2, 7, 5)

    def test_lstm_shapes(self):
        m = Recurrent(LSTM(4, 6)).build(KEY).evaluate()
        out = m.forward(jnp.ones((3, 5, 4)))
        assert out.shape == (3, 5, 6)

    def test_add_idiom(self):
        m = Recurrent().add(GRU(4, 4)).build(KEY).evaluate()
        assert m.forward(jnp.ones((1, 2, 4))).shape == (1, 2, 4)

    def test_lstm_matches_torch(self):
        torch = pytest.importorskip("torch")
        m = Recurrent(LSTM(3, 4)).build(KEY).evaluate()
        p = m.variables["params"]["cell"]
        w = np.asarray(p["weight"])  # (3+4, 4*4) order i,f,g,o
        b = np.asarray(p["bias"])
        x = np.random.RandomState(0).randn(2, 6, 3).astype(np.float32)
        ours = np.asarray(m.forward(jnp.asarray(x)))

        ref = torch.nn.LSTM(3, 4, batch_first=True)
        # torch gate order i,f,g,o matches ours; torch weights (4H, D)
        with torch.no_grad():
            ref.weight_ih_l0.copy_(torch.tensor(w[:3].T))
            ref.weight_hh_l0.copy_(torch.tensor(w[3:].T))
            ref.bias_ih_l0.copy_(torch.tensor(b))
            ref.bias_hh_l0.zero_()
        out, _ = ref(torch.tensor(x))
        np.testing.assert_allclose(ours, out.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_gru_matches_manual(self):
        # Original Cho formulation (as in the reference's nn/GRU.scala):
        # cand = tanh(W [x, r*h] + b); torch's GRU applies r AFTER the
        # hidden matmul, a different variant — so the oracle is numpy.
        m = Recurrent(GRU(3, 4)).build(KEY).evaluate()
        p = m.variables["params"]["cell"]
        x = np.random.RandomState(1).randn(2, 5, 3).astype(np.float32)
        ours = np.asarray(m.forward(jnp.asarray(x)))

        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))

        wg = np.asarray(p["gates"]["weight"])
        bg = np.asarray(p["gates"]["bias"])
        wc = np.asarray(p["cand"]["weight"])
        bc = np.asarray(p["cand"]["bias"])
        h = np.zeros((2, 4), np.float32)
        for t in range(5):
            zr = sigmoid(np.concatenate([x[:, t], h], -1) @ wg + bg)
            z, r = zr[:, :4], zr[:, 4:]
            cand = np.tanh(np.concatenate([x[:, t], r * h], -1) @ wc + bc)
            h = (1 - z) * h + z * cand
            np.testing.assert_allclose(ours[:, t], h, rtol=1e-4, atol=1e-5)

    def test_peephole_shapes(self):
        m = Recurrent(LSTMPeephole(3, 4)).build(KEY).evaluate()
        assert m.forward(jnp.ones((2, 3, 3))).shape == (2, 3, 4)

    def test_grad_through_scan(self):
        m = Recurrent(LSTM(3, 4))
        variables = m.init(KEY)

        def loss(params):
            out, _ = m.apply({"params": params, "state": {}}, jnp.ones((2, 5, 3)))
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(variables["params"])
        assert float(jnp.abs(g["cell"]["weight"]).sum()) > 0


class TestBiRecurrent:
    def test_concat_merge(self):
        m = BiRecurrent(LSTM(3, 4)).build(KEY).evaluate()
        out = m.forward(jnp.ones((2, 5, 3)))
        assert out.shape == (2, 5, 8)

    def test_add_merge(self):
        m = BiRecurrent(GRU(3, 4), merge="add").build(KEY).evaluate()
        assert m.forward(jnp.ones((2, 5, 3))).shape == (2, 5, 4)

    def test_backward_direction_differs(self):
        m = BiRecurrent(LSTM(3, 4)).build(KEY).evaluate()
        x = jax.random.normal(KEY, (1, 6, 3))
        out = np.asarray(m.forward(x))
        # reversed input should not equal forward half output reversed
        out_rev = np.asarray(m.forward(jnp.flip(x, axis=1)))
        assert not np.allclose(out[:, :, :4], np.flip(out_rev[:, :, :4], 1))


class TestTimeDistributed:
    def test_linear_over_time(self):
        m = TimeDistributed(nn.Linear(3, 2)).build(KEY).evaluate()
        out = m.forward(jnp.ones((4, 7, 3)))
        assert out.shape == (4, 7, 2)

    def test_matches_manual(self):
        inner = nn.Linear(3, 2)
        m = TimeDistributed(inner).build(KEY).evaluate()
        x = jax.random.normal(KEY, (2, 3, 3))
        out = np.asarray(m.forward(x))
        w = m.variables["params"]["inner"]["weight"]
        b = m.variables["params"]["inner"]["bias"]
        np.testing.assert_allclose(out, np.asarray(x @ w + b), rtol=1e-5)


class TestConvLSTMPeephole:
    def test_shapes_through_recurrent(self):
        m = nn.Recurrent(nn.ConvLSTMPeephole(2, 4, kernel=3))
        v = m.init(KEY)
        x = jnp.asarray(
            np.random.RandomState(0).randn(2, 5, 8, 8, 2), jnp.float32)
        y, _ = m.apply(v, x)
        assert y.shape == (2, 5, 8, 8, 4)

    def test_temporal_dependence(self):
        """Swapping two frames must change subsequent outputs."""
        m = nn.Recurrent(nn.ConvLSTMPeephole(1, 2, kernel=3))
        v = m.init(KEY)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(1, 4, 6, 6, 1), jnp.float32)
        x_swapped = x.at[:, 0].set(x[:, 1]).at[:, 1].set(x[:, 0])
        y1, _ = m.apply(v, x)
        y2, _ = m.apply(v, x_swapped)
        assert not np.allclose(np.asarray(y1[:, -1]),
                               np.asarray(y2[:, -1]), atol=1e-6)

    def test_no_peephole_param_set(self):
        cell = nn.ConvLSTMPeephole(1, 2, with_peephole=False)
        p = cell.init_params(KEY)
        assert "w_ci" not in p

    def test_grads_flow(self):
        m = nn.Recurrent(nn.ConvLSTMPeephole(1, 2, kernel=3))
        v = m.init(KEY)
        x = jnp.ones((1, 3, 4, 4, 1))

        def loss(p):
            y, _ = m.apply({"params": p, "state": {}}, x)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(v["params"])
        total = sum(float(jnp.abs(l).sum())
                    for l in jax.tree_util.tree_leaves(g))
        assert total > 0


def test_hoisted_scan_matches_unhoisted():
    """hoist_inputs=True (default, PROFILE_r04) must be numerically
    equivalent to the in-scan path — guards both paths against drift."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(3, 7, 5).astype(np.float32))
    for hoist in (True, False):
        m = nn.Recurrent(nn.LSTM(5, 6), hoist_inputs=hoist)
        v = m.init(jax.random.PRNGKey(0))
        out, _ = m.apply(v, x)
        if hoist:
            ref = out
        else:
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-6)
    # BiRecurrent exposes the knob too
    bi = nn.BiRecurrent(nn.LSTM(5, 6), hoist_inputs=False)
    assert not bi.fwd.hoist_inputs and not bi.bwd.hoist_inputs


def test_gru_hoisted_matches_unhoisted():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 9, 4).astype(np.float32))
    ref = None
    for hoist in (True, False):
        m = nn.Recurrent(nn.GRU(4, 5), hoist_inputs=hoist)
        v = m.init(jax.random.PRNGKey(2))
        out, _ = m.apply(v, x)
        if ref is None:
            ref = out
        else:
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-6)
