"""Serving-plane reliability layer (ISSUE 4): request lifecycle
(deadlines, queue-wait TTL, cancellation, terminal statuses), admission
control / backpressure, priority scheduling, health snapshot, and the
engine edge cases around slot admission. The fault-injected legs
(poison co-batch, retry, watchdog trip) are drilled bit-deterministically
in scripts/fault_drill.py --plane serving and run as tier-1 via
tests/test_fault_drill.py; this file covers the host-side lifecycle
machinery those drills ride on."""

import jax
import numpy as np
import pytest

from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.serving import (EngineDegraded, InferenceEngine,
                               OverloadError, Request, bucket_histogram)

# one module-shared model: engines over the same model share jitted
# executables, so this file pays the decode/prefill compile once
_LM = None


def _lm():
    global _LM
    if _LM is None:
        _LM = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=2,
                       max_len=64)
        _LM.build(jax.random.PRNGKey(0))
    return _LM


def _engine(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_buckets", (8,))
    return InferenceEngine(_lm(), **kw)


def _drain(eng, clk=None, dt=1.0):
    """Step until empty, advancing the fake clock between steps."""
    while eng._queue or any(r is not None for r in eng._req):
        for res in eng.step():
            eng.completed[res.id] = res
        if clk is not None:
            clk["t"] += dt


class TestLifecycle:
    def test_deadline_expiry_queued_vs_decoding(self):
        clk = {"t": 0.0}
        eng = _engine(clock=lambda: clk["t"])
        eng.submit(Request(prompt=[1, 2], max_new_tokens=8, seed=1))
        eng.submit(Request(prompt=[3, 4], max_new_tokens=8, seed=2))
        qid = eng.submit(Request(prompt=[5, 6], max_new_tokens=4,
                                 deadline_s=2.0))
        _drain(eng, clk)
        q = eng.completed[qid]
        assert q.status == "expired" and q.tokens == []
        assert q.finish_reason == "expired"
        # while decoding: partial tokens survive the expiry
        clk["t"] = 0.0
        eng2 = _engine(clock=lambda: clk["t"])
        did = eng2.submit(Request(prompt=[1, 2, 3], max_new_tokens=8,
                                  deadline_s=2.0))
        _drain(eng2, clk)
        d = eng2.completed[did]
        assert d.status == "expired" and len(d.tokens) == 3
        assert eng2.stats["deadline_misses"] == 1

    def test_max_queue_wait_expires_queued_only(self):
        """max_queue_wait_s bounds time-in-queue; once decoding it no
        longer applies (unlike deadline_s)."""
        clk = {"t": 0.0}
        eng = _engine(slots=1, clock=lambda: clk["t"])
        eng.submit(Request(prompt=[1, 2], max_new_tokens=6, seed=1))
        wid = eng.submit(Request(prompt=[3, 4], max_new_tokens=2,
                                 max_queue_wait_s=3.0))
        _drain(eng, clk)
        assert eng.completed[wid].status == "expired"
        # admitted fast → the same TTL never fires while decoding
        clk["t"] = 0.0
        eng2 = _engine(slots=1, clock=lambda: clk["t"])
        oid = eng2.submit(Request(prompt=[3, 4], max_new_tokens=6,
                                  max_queue_wait_s=3.0))
        _drain(eng2, clk)
        assert eng2.completed[oid].status == "done"

    def test_cancel_queued_and_inflight(self):
        eng = _engine(slots=1)
        a = eng.submit(Request(prompt=[1, 2], max_new_tokens=6, seed=1))
        b = eng.submit(Request(prompt=[3, 4], max_new_tokens=6, seed=2))
        eng.step()                                # a decoding, b queued
        res_b = eng.cancel(b)
        assert res_b.status == "shed"
        assert res_b.finish_reason == "cancelled" and res_b.tokens == []
        res_a = eng.cancel(a)
        assert res_a.status == "shed" and len(res_a.tokens) == 1
        assert eng.stats["cancelled"] == 2
        with pytest.raises(KeyError):
            eng.cancel(a)
        assert not eng._queue and eng._free_slots() == [0]

    def test_result_statuses_and_run_never_keyerrors(self):
        """run() returns shed/expired results in submission order —
        terminal statuses are results, not exceptions."""
        eng = _engine(max_queue=1, overload_policy="shed-oldest")
        out = eng.run([Request(prompt=[1, 2], max_new_tokens=2, seed=1),
                       Request(prompt=[3, 4], max_new_tokens=2, seed=2),
                       Request(prompt=[5, 6], max_new_tokens=2, seed=3)])
        assert [r.status for r in out] == ["shed", "shed", "done"]


class TestAdmission:
    def test_reject_policy_raises(self):
        eng = _engine(max_queue=1, overload_policy="reject")
        eng.submit(Request(prompt=[1, 2]))
        with pytest.raises(OverloadError, match="queue full"):
            eng.submit(Request(prompt=[3, 4]))
        assert eng.stats["rejected"] == 1
        eng.run()

    def test_priority_admission_order(self):
        """Highest priority leaves the queue first (FIFO within a
        priority), regardless of arrival order."""
        eng = _engine(slots=1)
        lo = eng.submit(Request(prompt=[1, 2], max_new_tokens=2,
                                priority=0))
        hi = eng.submit(Request(prompt=[3, 4], max_new_tokens=2,
                                priority=9))
        mid = eng.submit(Request(prompt=[5, 6], max_new_tokens=2,
                                 priority=5))
        order = []
        while eng._queue or any(r is not None for r in eng._req):
            for res in eng.step():
                order.append(res.id)
        assert order == [hi, mid, lo]

    def test_shed_lowest_priority_victim_selection(self):
        eng = _engine(max_queue=2, overload_policy="shed-lowest-priority")
        low = eng.submit(Request(prompt=[1, 2], priority=1))
        eng.submit(Request(prompt=[3, 4], priority=7))
        eng.submit(Request(prompt=[5, 6], priority=4))   # sheds `low`
        assert eng.completed[low].status == "shed"
        new = eng.submit(Request(prompt=[7, 8], priority=0))
        assert eng.completed[new].status == "shed"       # newcomer lowest
        assert eng.stats["shed"] == 2
        eng.run()

    def test_expired_queue_does_not_count_toward_overload(self):
        """A queue full of already-dead TTLs must not reject fresh
        traffic (submit expires stale entries before the max_queue
        check) — and the dead entries report 'expired', not 'shed'."""
        clk = {"t": 0.0}
        eng = _engine(slots=1, max_queue=2, overload_policy="reject",
                      clock=lambda: clk["t"])
        eng.submit(Request(prompt=[1, 2], max_new_tokens=6, seed=1))
        eng.step()                          # slot busy, queue empty
        s1 = eng.submit(Request(prompt=[3, 4], deadline_s=1.0))
        s2 = eng.submit(Request(prompt=[5, 6], deadline_s=1.0))
        clk["t"] = 5.0                      # both queued TTLs dead
        fresh = eng.submit(Request(prompt=[7, 8], max_new_tokens=2))
        assert eng.completed[s1].status == "expired"
        assert eng.completed[s2].status == "expired"
        assert eng.stats["rejected"] == 0
        _drain(eng)
        assert eng.completed[fresh].status == "done"

    def test_invalid_knobs_rejected(self):
        with pytest.raises(ValueError, match="overload_policy"):
            _engine(overload_policy="drop-everything")
        with pytest.raises(ValueError, match="max_queue"):
            _engine(max_queue=0)
        with pytest.raises(ValueError, match="step_retries"):
            _engine(step_retries=-1)


class TestEdgeCases:
    def test_all_slots_finish_same_step(self):
        eng = _engine()
        eng.submit(Request(prompt=[1, 2], max_new_tokens=3, seed=1))
        eng.submit(Request(prompt=[3, 4], max_new_tokens=3, seed=2))
        finished = []
        for _ in range(3):
            finished = eng.step()
        assert len(finished) == 2            # both evicted on one step
        assert all(r.status == "done" for r in finished)
        assert eng._free_slots() == [0, 1]
        # slots are immediately reusable
        res = eng.run([Request(prompt=[5, 6], max_new_tokens=2)])
        assert res[0].status == "done"

    def test_queue_longer_than_free_slots(self):
        eng = _engine()
        out = eng.run([Request(prompt=[i + 1, i + 2], max_new_tokens=2,
                               seed=i) for i in range(5)])
        assert len(out) == 5
        assert all(r.status == "done" for r in out)
        assert eng.stats["requests_done"] == 5

    def test_run_with_zero_slots_free_at_entry(self):
        eng = _engine()
        eng.submit(Request(prompt=[1, 2], max_new_tokens=4, seed=1))
        eng.submit(Request(prompt=[3, 4], max_new_tokens=4, seed=2))
        eng.step()                            # both slots now occupied
        assert eng._free_slots() == []
        out = eng.run([Request(prompt=[5, 6], max_new_tokens=2, seed=3)])
        assert out[0].status == "done" and len(out[0].tokens) == 2
        assert len(eng.completed) == 2        # the pre-submitted pair


class TestDegradation:
    def test_watchdog_arming_warms_decode_at_init(self):
        """The first decode call traces+compiles (minutes through the
        real tunnel) — arming the watchdog must pre-warm the
        executable at construction so a healthy engine never trips on
        step 0. Fresh model: the compile is attributable."""
        fresh = build_lm(vocab_size=50, dim=16, num_heads=2,
                         num_layers=1, max_len=32)
        fresh.build(jax.random.PRNGKey(1))
        eng = InferenceEngine(fresh, slots=2, prefill_buckets=(8,),
                              step_timeout_s=5.0)
        assert eng.stats["decode_traces"] == 1   # warmed at init
        res = eng.run([Request(prompt=[1, 2], max_new_tokens=3)])
        assert res[0].status == "done"
        assert eng.stats["decode_traces"] == 1   # no step-0 retrace
        assert eng.stats["watchdog_trips"] == 0

    def test_donated_cache_failure_is_not_retried(self):
        """A failure after the dispatch consumed (donated) the cache
        must degrade immediately with the real cause — re-dispatching
        deleted buffers would burn the retry budget on misleading
        buffer errors."""
        from bigdl_tpu.utils import faults

        eng = _engine(step_retries=3, retry_backoff_s=0.0)
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=6, seed=1))
        eng.step()                           # healthy step first
        for leaf in jax.tree_util.tree_leaves(eng.pool):
            leaf.delete()                    # model the donated pool
        faults.set_plan(faults.FaultPlan("serve_err@1"))
        try:
            out = eng.step()
        finally:
            faults.set_plan(None)
        assert eng.degraded is not None
        assert "not retryable" in eng.degraded
        assert eng.stats["retries"] == 0     # budget untouched
        assert [r.status for r in out] == ["failed"]


class TestHealth:
    def test_snapshot_shape_and_latency(self):
        eng = _engine(max_queue=4)
        eng.submit(Request(prompt=[1, 2], max_new_tokens=3, seed=1))
        eng.submit(Request(prompt=[3, 4], max_new_tokens=3, seed=2))
        eng.submit(Request(prompt=[5, 6], max_new_tokens=3, seed=3))
        eng.step()
        h = eng.health()
        assert h["state"] == "ok" and h["degraded_reason"] is None
        assert h["slots_active"] == 2 and h["queue_depth"] == 1
        assert h["queue_buckets"] == {8: 1}
        assert h["decode_p50_ms"] > 0 and h["decode_p95_ms"] > 0
        for key in ("deadline_misses", "shed", "rejected", "poisoned",
                    "retries", "watchdog_trips", "failed", "cancelled"):
            assert h[key] == 0
        eng.run()
        assert eng.health()["requests_done"] == 3

    def test_bucket_histogram(self):
        assert bucket_histogram([3, 9, 17, 2], (8, 16, 32)) == \
            {8: 2, 16: 1, 32: 1}
        assert bucket_histogram([], (8, 16)) == {8: 0, 16: 0}
        with pytest.raises(ValueError, match="exceeds"):
            bucket_histogram([33], (8, 16, 32))


class TestTpuProbe:
    def test_probe_subprocess_returns_platform(self, monkeypatch):
        from bigdl_tpu.utils.tpu_probe import probe_platform

        # the child inherits the env; pin it to cpu so the probe never
        # touches the axon tunnel from CI
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        assert probe_platform(timeout_s=120.0) == "cpu"

    def test_probe_times_out_on_hung_backend(self):
        import threading

        from bigdl_tpu.utils.tpu_probe import probe_platform

        hang = threading.Event()

        def hung_devices():
            hang.wait(10.0)           # the axon-tunnel hang model
            return "never"

        assert probe_platform(timeout_s=0.05,
                              devices_fn=hung_devices) is None
        hang.set()

    def test_probe_swallows_backend_errors(self):
        from bigdl_tpu.utils.tpu_probe import probe_platform

        def broken_devices():
            raise RuntimeError("No ba16c7433 device found")

        assert probe_platform(timeout_s=5.0,
                              devices_fn=broken_devices) is None
