"""ops/losses oracle tests + the ChunkedSoftmaxCE criterion fusion.

The chunked loss is oracled against the materializing
LogSoftMax+ClassNLL pair it replaces (reference: nn/LogSoftMax.scala +
nn/ClassNLLCriterion.scala), forward AND gradients; the fusion protocol
is verified end-to-end through the Optimizer (LM training through the
product surface must never materialize the (B, S, V) tensor — checked
on the jaxpr, not just claimed).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.ops.losses import build_train_loss, softmax_cross_entropy_chunked

KEY = jax.random.PRNGKey(0)


def _materializing_loss(hidden, head, targets):
    logits = (hidden @ head).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(
        logp, targets[..., None], axis=-1))


class TestChunkedSoftmaxCrossEntropy:
    @pytest.mark.parametrize("b,s,e,v,chunk", [
        (2, 64, 16, 50, 16),    # chunk divides S
        (2, 64, 16, 50, 256),   # chunk > S -> single chunk of S
        (1, 384, 8, 33, 256),   # ADVICE r2 #2: falls back to divisor 192
        (3, 96, 8, 17, 32),
    ])
    def test_forward_and_grad_match_materializing(self, b, s, e, v, chunk):
        rng = np.random.RandomState(1)
        hidden = jnp.asarray(rng.randn(b, s, e), jnp.float32)
        head = jnp.asarray(rng.randn(e, v) * 0.3, jnp.float32)
        targets = jnp.asarray(rng.randint(0, v, (b, s)))

        got = softmax_cross_entropy_chunked(hidden, head, targets,
                                            chunk=chunk)
        want = _materializing_loss(hidden, head, targets)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)

        g_got = jax.grad(lambda h, w: softmax_cross_entropy_chunked(
            h, w, targets, chunk=chunk), argnums=(0, 1))(hidden, head)
        g_want = jax.grad(_materializing_loss, argnums=(0, 1))(
            hidden, head, targets)
        for a, b_ in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-5, atol=1e-6)

    def test_prime_sequence_refused(self):
        h = jnp.zeros((1, 383, 4))
        w = jnp.zeros((4, 9))
        t = jnp.zeros((1, 383), jnp.int32)
        with pytest.raises(ValueError, match="no usable chunk"):
            softmax_cross_entropy_chunked(h, w, t)

    def test_grad_under_jit_with_remat(self):
        """value_and_grad under jit (the optimizer's exact usage): the
        chunk body is jax.checkpoint'ed, so the backward retraces it —
        values must still match the materializing oracle."""
        rng = np.random.RandomState(2)
        hidden = jnp.asarray(rng.randn(2, 128, 8), jnp.float32)
        head = jnp.asarray(rng.randn(8, 40) * 0.3, jnp.float32)
        targets = jnp.asarray(rng.randint(0, 40, (2, 128)))

        f = jax.jit(jax.value_and_grad(
            lambda h: softmax_cross_entropy_chunked(h, head, targets,
                                                    chunk=32)))
        loss, g = f(hidden)
        want_l, want_g = jax.value_and_grad(
            lambda h: _materializing_loss(h, head, targets))(hidden)
        np.testing.assert_allclose(float(loss), float(want_l), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g), np.asarray(want_g),
                                   rtol=2e-5, atol=1e-6)


class TestChunkedSoftmaxCECriterion:
    def test_forward_is_mean_nll_2d_and_3d(self):
        rng = np.random.RandomState(3)
        crit = nn.ChunkedSoftmaxCE()
        oracle2 = nn.ClassNLLCriterion()
        logp2 = jnp.asarray(jax.nn.log_softmax(
            jnp.asarray(rng.randn(6, 9), jnp.float32)))
        t2 = jnp.asarray(rng.randint(0, 9, 6))
        np.testing.assert_allclose(float(crit(logp2, t2)),
                                   float(oracle2(logp2, t2)), rtol=1e-6)

        oracle3 = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                              size_average=True)
        logp3 = jnp.asarray(jax.nn.log_softmax(
            jnp.asarray(rng.randn(2, 5, 9), jnp.float32)))
        t3 = jnp.asarray(rng.randint(0, 9, (2, 5)))
        np.testing.assert_allclose(float(crit(logp3, t3)),
                                   float(oracle3(logp3, t3)), rtol=1e-6)

    def test_fused_loss_none_without_hidden_surface(self):
        model = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax()).build(KEY)
        assert nn.ChunkedSoftmaxCE().fused_loss(model) is None
        # build_train_loss falls back to apply+forward and still works
        loss_call = build_train_loss(model, nn.ChunkedSoftmaxCE())
        x = jnp.ones((2, 4))
        y = jnp.zeros((2,), jnp.int32)
        loss, _ = loss_call(model.variables["params"],
                            model.variables["state"], x, y, KEY)
        want = nn.ClassNLLCriterion()(
            model.apply(model.variables, x)[0], y)
        np.testing.assert_allclose(float(loss), float(want), rtol=1e-6)

    def test_fusion_refuses_stateful_model(self):
        """apply_hidden has no state-output channel: a model with real
        state must be refused, not silently trained with frozen stats."""
        m = build_lm(vocab_size=16, dim=16, num_heads=2, num_layers=1,
                     max_len=8)
        fused = nn.ChunkedSoftmaxCE().fused_loss(m)
        toks = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="non-empty state"):
            fused({"params": m.init(KEY)["params"],
                   "state": {"bn": {"mean": jnp.zeros(4)}}},
                  toks, toks, KEY)

    def test_fused_matches_unfused_through_model(self):
        """fused (apply_hidden + chunked) == unfused (apply + forward)
        on the same TransformerLM — value and parameter gradients."""
        m = build_lm(vocab_size=40, dim=32, num_heads=2, num_layers=2,
                     max_len=32)
        variables = m.init(KEY)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 40)
        tgts = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 40)
        crit = nn.ChunkedSoftmaxCE(chunk=8)

        fused = crit.fused_loss(m)
        assert fused is not None

        def fused_l(p):
            return fused({"params": p, "state": {}}, toks, tgts, KEY)[0]

        def unfused_l(p):
            out, _ = m.apply({"params": p, "state": {}}, toks)
            return crit(out, tgts)

        lf, gf = jax.value_and_grad(fused_l)(variables["params"])
        lu, gu = jax.value_and_grad(unfused_l)(variables["params"])
        np.testing.assert_allclose(float(lf), float(lu), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(gf),
                        jax.tree_util.tree_leaves(gu)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=1e-5)

    def test_train_step_jaxpr_never_materializes_bsv(self):
        """THE point of the fusion: the jitted training step's jaxpr
        (all sub-jaxprs included) contains no (B, S, V) intermediate."""
        b, s, v = 4, 64, 512
        m = build_lm(vocab_size=v, dim=32, num_heads=2, num_layers=2,
                     max_len=s)
        variables = m.init(KEY)
        crit = nn.ChunkedSoftmaxCE(chunk=16)
        loss_call = build_train_loss(m, crit)
        toks = jnp.zeros((b, s), jnp.int32)
        tgts = jnp.zeros((b, s), jnp.int32)

        jaxpr = jax.make_jaxpr(
            lambda p: jax.value_and_grad(
                lambda q: loss_call(q, {}, toks, tgts, KEY)[0])(p)
        )(variables["params"])

        def walk(jx, seen):
            for eqn in jx.eqns:
                for var in list(eqn.invars) + list(eqn.outvars):
                    aval = getattr(var, "aval", None)
                    if aval is not None and getattr(aval, "shape", None):
                        seen.add(tuple(aval.shape))
                for p_ in eqn.params.values():
                    inner = getattr(p_, "jaxpr", None)
                    if inner is not None:
                        walk(inner, seen)
                    if isinstance(p_, (list, tuple)):
                        for q_ in p_:
                            inner = getattr(q_, "jaxpr", None)
                            if inner is not None:
                                walk(inner, seen)
            return seen

        shapes = walk(jaxpr.jaxpr, set())
        assert (b, s, v) not in shapes, "fused step materialized (B,S,V)"
        # sanity: the chunked (B, chunk, V) block IS there
        assert any(sh[-1] == v and len(sh) >= 3 and sh[-2] == 16
                   for sh in shapes), shapes

    @pytest.mark.slow
    def test_distri_optimizer_mesh_fused(self):
        """The fused criterion also drives the DP/ZeRO-1 mesh path
        (DistriOptimizer): loss finite and falling over 2 epochs on the
        8-device CPU mesh. Tier-2: fused==unfused is pinned by
        test_fused_matches_unfused_through_model and the mesh step by
        test_distributed — this 11 s integration rerun keeps tier-1
        margin (ISSUE 8 budget satellite)."""
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.text import synthetic_next_token
        from bigdl_tpu.optim import Adam, Loss, Optimizer, Trigger
        from bigdl_tpu.parallel import make_mesh

        assert jax.device_count() >= 8
        samples = synthetic_next_token(64, 16, 16)
        m = build_lm(vocab_size=16, dim=32, num_heads=2, num_layers=1,
                     max_len=16)
        m.build(KEY)
        crit = nn.ChunkedSoftmaxCE(chunk=8)
        trained = (Optimizer(m, DataSet.array(samples), crit,
                             batch_size=16)
                   .set_optim_method(Adam(learningrate=1e-2))
                   .set_end_when(Trigger.max_epoch(6))
                   .set_mesh(make_mesh({"data": 8}))
                   .optimize())
        from bigdl_tpu.optim import Evaluator
        res = Evaluator(trained).test(DataSet.array(samples[:16]),
                                      [Loss(crit)], 16)
        assert res["Loss"].result()[0] < 2.0

    def test_optimizer_trains_lm_through_product_surface(self):
        """Optimizer + ChunkedSoftmaxCE on TransformerLM: loss falls on
        the cyclic-grammar task (the examples/transformer_lm.py setup)."""
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.text import synthetic_next_token
        from bigdl_tpu.optim import Adam, Evaluator, Loss, Optimizer, Trigger

        samples = synthetic_next_token(64, 16, 16)
        m = build_lm(vocab_size=16, dim=32, num_heads=2, num_layers=1,
                     max_len=16)
        m.build(KEY)
        crit = nn.ChunkedSoftmaxCE(chunk=8)

        opt = (Optimizer(m, DataSet.array(samples), crit, batch_size=16)
               .set_optim_method(Adam(learningrate=1e-2))
               .set_end_when(Trigger.max_epoch(8))
               .set_validation(Trigger.every_epoch(),
                               DataSet.array(samples[:16]), [Loss(crit)]))
        trained = opt.optimize()
        res = Evaluator(trained).test(DataSet.array(samples[:16]),
                                      [Loss(crit)], 16)
        final = res["Loss"].result()[0]
        assert final < 1.0, f"LM did not train through Optimizer: {final}"
