"""Test configuration.

Mirrors the reference's test strategy (SURVEY.md §4): the reference tests
distributed code paths on Spark `local[N]` without a cluster; we test
multi-chip code paths on a virtual 8-device CPU mesh via
`--xla_force_host_platform_device_count` — the real sharding/collective
code runs unchanged.

Environment note: this image boots an `axon` PJRT plugin (remote TPU
tunnel) via sitecustomize, and initializing it blocks on the tunnel. Tests
must run CPU-only, so we force the platform to cpu AND drop the axon
factory from the backend registry before any backend is materialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # intentional inline copy of utils/engine.ensure_cpu_platform:
    # this runs before bigdl_tpu is importable (or with conditional
    # platform logic)
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
except Exception:
    pass

jax.config.update("jax_enable_x64", False)
