"""Perf-regression sentinel (ISSUE 11): pure-parse guard over the
COMMITTED BENCH_r0*.json trajectory — the check_tier1_budget.py-style
CI usage. The committed history must gate clean at the recorded
spreads (the documented ~25% host variance never pages), a synthetic
2x slowdown must flag with a nonzero exit, and the --format json
verdict must be machine-readable."""

import importlib.util
import json
import os

import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load():
    path = os.path.join(ROOT, "scripts", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bc():
    return _load()


@pytest.fixture(scope="module")
def history(bc):
    return bc.load_history(os.path.join(ROOT, "BENCH_r*.json"))


# --------------------------------------------------------------- parsing

def test_rows_from_text_skips_noise(bc):
    text = ("WARNING: some log line\n"
            '{"metric": "m_a", "value": 10.0, "unit": "x/s"}\n'
            '{"not_a_metric": 1}\n'
            "{torn json\n"
            '{"metric": "m_b", "value": 2.5, "unit": "x/s", '
            '"step_ms": 4.0, "step_ms_spread": [3.0, 5.0]}\n')
    rows = bc.rows_from_text(text)
    assert set(rows) == {"m_a", "m_b"}
    assert rows["m_b"]["step_ms_spread"] == [3.0, 5.0]


def test_load_rows_list_rejects_nonnumeric_values(bc, tmp_path):
    """A JSON-list candidate applies the same numeric-value admission
    as rows_from_text — garbage rows route to exit 2, not a TypeError
    inside compare()."""
    p = tmp_path / "rows.json"
    p.write_text(json.dumps([
        {"metric": "m_ok", "value": 3.0},
        {"metric": "m_null", "value": None},
        {"metric": "m_missing"},
        "not a row"]))
    assert set(bc.load_rows(str(p))) == {"m_ok"}
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([{"metric": "m_null", "value": None}]))
    assert bc.main(["--fresh", str(bad),
                    "--history",
                    os.path.join(ROOT, "BENCH_r*.json")]) == 2


def test_committed_history_loads(history):
    """Every committed driver artifact parses into metric rows."""
    assert len(history) >= 5
    tags = [tag for tag, _ in history]
    assert tags == sorted(tags, key=lambda t: int(t.split("_r")[1]
                                                  .split(".")[0]))
    assert all(rows for _, rows in history)


def test_spread_frac(bc):
    assert bc.spread_frac({"step_ms_spread": [3.0, 5.0],
                           "step_ms": 4.0}) == pytest.approx(0.25)
    assert bc.spread_frac({"step_ms": 4.0}) is None
    assert bc.spread_frac({"step_ms_spread": [3.0, 5.0]}) is None


# ------------------------------------------------------------ comparison

def test_committed_trajectory_gates_clean(bc, history):
    """THE acceptance pin: the newest committed round against the
    earlier ones flags NO regression at the recorded spreads — the
    r04->r05 BiLSTM dip (-8%, inside its recorded 46%-wide spread)
    must not page."""
    fresh_tag, fresh = history[-1]
    verdict = bc.compare(history[:-1], fresh)
    assert verdict["ok"], verdict["regressions"]
    assert verdict["checked"] >= 5
    bilstm = [r for r in verdict["rows"]
              if r["metric"].startswith("bilstm")]
    if bilstm:     # the noisy row widened its own tolerance
        assert bilstm[0]["threshold_frac"] > 0.25


def test_synthetic_2x_slowdown_flags(bc, history):
    """Halving a stable metric's throughput must flag it (and only
    it) as a regression."""
    fresh_tag, fresh = history[-1]
    target = "inception_v1_bf16_train_images_per_sec_per_chip[tpu]"
    assert target in fresh
    slowed = {m: dict(r) for m, r in fresh.items()}
    slowed[target]["value"] = fresh[target]["value"] / 2.0
    verdict = bc.compare(history[:-1], slowed)
    assert not verdict["ok"]
    assert [r["metric"] for r in verdict["regressions"]] == [target]
    reg = verdict["regressions"][0]
    assert reg["shortfall_frac"] == pytest.approx(0.5, abs=0.02)
    assert reg["threshold_frac"] < reg["shortfall_frac"]


def test_noise_widens_threshold_but_2x_still_flags(bc):
    """A row publishing a wide median-of-5 spread gets a wider
    tolerance — a dip inside it passes, a 2x slowdown still flags."""
    hist = [("r1", {"m": {"metric": "m", "value": 100.0,
                          "step_ms": 10.0, "step_ms_median_of": 5,
                          "step_ms_spread": [8.0, 12.0]}})]
    dip = {"m": {"metric": "m", "value": 70.0, "step_ms": 14.0}}
    v = bc.compare(hist, dip)
    assert v["ok"]                         # -30% < 1.5 * 20% spread
    halved = {"m": {"metric": "m", "value": 50.0, "step_ms": 20.0}}
    v2 = bc.compare(hist, halved)
    assert not v2["ok"]


def test_lmdecode_spec_row_parses_and_gates(bc):
    """ISSUE 15: the sentinel picks the new speculative-decoding row
    up — a bench line shaped like bench_lm_decode_spec's output parses
    into a metric row (extra provenance fields preserved), a
    within-tolerance wobble passes, and a 2x goodput collapse (e.g. a
    broken draft pinning accept_rate to 0) flags exactly that row."""
    spec_metric = ("transformer_lm_43m_decode_spec_goodput"
                   "_tokens_per_sec[cpu]")
    line = json.dumps({
        "metric": spec_metric, "value": 120.0, "unit": "tokens/sec",
        "vs_baseline": None, "target_only_tokens_per_sec": 60.0,
        "speedup_vs_target_only": 2.0, "k": 4, "accept_rate": 0.7,
        "tokens_bit_identical_to_target_only": True})
    rows = bc.rows_from_text("some warmup noise\n" + line + "\n")
    assert spec_metric in rows
    assert rows[spec_metric]["accept_rate"] == 0.7
    hist = [("r1", rows)]
    wobble = {spec_metric: {"metric": spec_metric, "value": 100.0}}
    assert bc.compare(hist, wobble)["ok"]      # -17% < the 25% floor
    collapsed = {spec_metric: {"metric": spec_metric, "value": 60.0}}
    verdict = bc.compare(hist, collapsed)
    assert not verdict["ok"]
    assert [r["metric"] for r in verdict["regressions"]] \
        == [spec_metric]


def test_lmdecode_spill_row_parses_and_gates(bc):
    """ISSUE 16: the sentinel picks the spill-tier row up — a bench
    line shaped like bench_lm_decode_spill's output parses into a
    metric row (tier provenance preserved), a within-tolerance wobble
    passes, and a 2x goodput collapse (e.g. re-admission silently
    falling back to re-prefill) flags exactly that row."""
    spill_metric = ("transformer_lm_43m_decode_spill_goodput"
                    "_tokens_per_sec[cpu]")
    line = json.dumps({
        "metric": spill_metric, "value": 90.0, "unit": "tokens/sec",
        "vs_baseline": None, "cold_cache_tokens_per_sec": 55.0,
        "speedup_vs_cold": 1.64, "spilled_blocks": 84,
        "readmitted_blocks": 30, "host_evictions": 0,
        "host_blocks_in_use": 61,
        "tokens_bit_identical_to_cold": True})
    rows = bc.rows_from_text("some warmup noise\n" + line + "\n")
    assert spill_metric in rows
    assert rows[spill_metric]["readmitted_blocks"] == 30
    hist = [("r1", rows)]
    wobble = {spill_metric: {"metric": spill_metric, "value": 75.0}}
    assert bc.compare(hist, wobble)["ok"]      # -17% < the 25% floor
    collapsed = {spill_metric: {"metric": spill_metric, "value": 45.0}}
    verdict = bc.compare(hist, collapsed)
    assert not verdict["ok"]
    assert [r["metric"] for r in verdict["regressions"]] \
        == [spill_metric]


# ----------------------------------------------------------------- CLI

def test_cli_fresh_latest_exits_zero(bc, capsys):
    assert bc.main(["--fresh-latest",
                    "--history", os.path.join(ROOT, "BENCH_r*.json")]) \
        == 0
    out = capsys.readouterr().out
    assert "OK" in out and "metrics checked" in out


def test_cli_json_verdict_and_regression_exit(bc, tmp_path, capsys):
    """--format json is machine-readable; a candidate file with a 2x
    slowdown exits 1 and names the metric in the verdict."""
    hist = bc.load_history(os.path.join(ROOT, "BENCH_r*.json"))
    _, latest = hist[-1]
    target = "transformer_lm_43m_train_tokens_per_sec_per_chip[tpu]"
    rows = [dict(r) for r in latest.values()]
    for r in rows:
        if r["metric"] == target:
            r["value"] = r["value"] / 2.0
    fresh = tmp_path / "fresh.jsonl"
    fresh.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = bc.main(["--fresh", str(fresh), "--format", "json",
                  "--history", os.path.join(ROOT, "BENCH_r*.json")])
    assert rc == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is False
    assert [r["metric"] for r in verdict["regressions"]] == [target]
    assert verdict["candidate"] == "fresh.jsonl"


def test_cli_usage_errors_exit_two(bc, tmp_path, capsys):
    assert bc.main([]) == 2                        # no candidate
    assert bc.main(["--fresh-latest",
                    "--history",
                    str(tmp_path / "none_*.json")]) == 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("no rows here\n")
    assert bc.main(["--fresh", str(empty),
                    "--history",
                    os.path.join(ROOT, "BENCH_r*.json")]) == 2
    assert bc.main(["--fresh", str(tmp_path / "missing.jsonl")]) == 2
    capsys.readouterr()
