"""TreeLSTM tests — linearized post-order scan over binary trees
(reference: nn/BinaryTreeLSTM + example/treeLSTM, TreeNNAccuracy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.treelstm import BinaryTreeLSTM, encode_from_nested
from bigdl_tpu.optim.validation import TreeNNAccuracy

KEY = jax.random.PRNGKey(0)


def batch_trees(trees, max_nodes):
    encs = [encode_from_nested(t, max_nodes) for t in trees]
    stack = lambda k: np.stack([e[k] for e in encs])
    return (stack("word"), stack("left"), stack("right"),
            stack("is_leaf"), stack("mask")), [e["n_nodes"] for e in encs]


class TestEncoding:
    def test_simple_tree(self):
        # (1, (2, 3)): post-order = 1, 2, 3, (2,3), (1, .)
        enc = encode_from_nested((1, (2, 3)), max_nodes=8)
        assert enc["n_nodes"] == 5
        np.testing.assert_array_equal(enc["word"][:5], [1, 2, 3, 0, 0])
        np.testing.assert_array_equal(enc["is_leaf"][:5], [1, 1, 1, 0, 0])
        assert enc["left"][3] == 1 and enc["right"][3] == 2
        assert enc["left"][4] == 0 and enc["right"][4] == 3

    def test_too_big_raises(self):
        with pytest.raises(ValueError, match="max_nodes"):
            encode_from_nested((1, (2, (3, 4))), max_nodes=3)


class TestBinaryTreeLSTM:
    def test_forward_shapes(self):
        m = BinaryTreeLSTM(vocab_size=20, embed_dim=8, hidden_size=8,
                           class_num=3).build(KEY).evaluate()
        inputs, _ = batch_trees([(1, (2, 3)), ((4, 5), 6)], max_nodes=8)
        out = m.forward(tuple(jnp.asarray(a) for a in inputs))
        assert out.shape == (2, 8, 3)
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0,
                                   rtol=1e-5)

    def test_composition_uses_children(self):
        """Swapping leaves must change the root representation.
        Output is root-first: node 0 IS the root."""
        m = BinaryTreeLSTM(20, 8, 8, 3).build(KEY).evaluate()
        (w, l, r, lf, mk), nn_ = batch_trees([(1, 2), (2, 1)], max_nodes=4)
        out = np.asarray(m.forward((jnp.asarray(w), jnp.asarray(l),
                                    jnp.asarray(r), jnp.asarray(lf),
                                    jnp.asarray(mk))))
        assert not np.allclose(out[0, 0], out[1, 0], atol=1e-6)

    def test_dict_input_matches_tuple(self):
        m = BinaryTreeLSTM(20, 8, 8, 3).build(KEY).evaluate()
        (w, l, r, lf, mk), _ = batch_trees([(1, (2, 3))], max_nodes=8)
        arrays = tuple(jnp.asarray(a) for a in (w, l, r, lf, mk))
        out_tuple = np.asarray(m.forward(arrays))
        out_dict = np.asarray(m.forward({
            "word": arrays[0], "left": arrays[1], "right": arrays[2],
            "is_leaf": arrays[3], "mask": arrays[4]}))
        np.testing.assert_allclose(out_tuple, out_dict, rtol=1e-6)

    def test_learns_toy_sentiment(self):
        """Root label = which of tokens {1,2} appears — learnable."""
        m = BinaryTreeLSTM(10, 16, 16, 2).build(KEY)
        trees = [((1, 3), (3, 3)), ((3, 2), (3, 3)),
                 ((3, 3), (1, 3)), ((3, 3), (3, 2)),
                 ((1, 1), (3, 3)), ((3, 3), (2, 2))]
        labels_root = [0, 1, 0, 1, 0, 1]
        (w, l, r, lf, mk), n_nodes = batch_trees(trees, max_nodes=8)
        inputs = tuple(jnp.asarray(a) for a in (w, l, r, lf, mk))
        y = jnp.asarray(labels_root)

        variables = m.variables

        def loss_fn(params):
            out, _ = m.apply({"params": params, "state": {}}, inputs,
                             training=True)
            root_logp = out[:, 0]  # root-first output convention
            return -jnp.mean(jnp.take_along_axis(root_logp, y[:, None], 1))

        step = jax.jit(jax.value_and_grad(loss_fn))
        params = variables["params"]
        for i in range(300):
            loss, g = step(params)
            params = jax.tree_util.tree_map(lambda p, gr: p - 0.2 * gr,
                                            params, g)
        assert float(loss) < 0.1, f"TreeLSTM failed to fit toy data: {loss}"

    def test_treenn_accuracy_on_root(self):
        out = jnp.asarray([[[0.9, 0.1], [0.2, 0.8]]])  # root = node 0 conv
        tgt = jnp.asarray([[0, 1]])
        r = TreeNNAccuracy().apply(out, tgt)
        assert r.result()[0] == 1.0

    def test_grad_flows_through_tree(self):
        m = BinaryTreeLSTM(10, 8, 8, 2)
        variables = m.init(KEY)
        inputs, _ = batch_trees([((1, 2), (3, 4))], max_nodes=8)
        inputs = tuple(jnp.asarray(a) for a in inputs)

        def loss(params):
            out, _ = m.apply({"params": params, "state": {}}, inputs)
            return jnp.sum(out)

        g = jax.grad(loss)(variables["params"])
        assert float(jnp.abs(g["compose"]["weight"]).sum()) > 0
        assert float(jnp.abs(g["embedding"]).sum()) > 0
