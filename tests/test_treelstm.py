"""TreeLSTM tests — linearized post-order scan over binary trees
(reference: nn/BinaryTreeLSTM + example/treeLSTM, TreeNNAccuracy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.treelstm import BinaryTreeLSTM, encode_from_nested
from bigdl_tpu.optim.validation import TreeNNAccuracy

KEY = jax.random.PRNGKey(0)


def batch_trees(trees, max_nodes):
    encs = [encode_from_nested(t, max_nodes) for t in trees]
    stack = lambda k: np.stack([e[k] for e in encs])
    return (stack("word"), stack("left"), stack("right"),
            stack("is_leaf"), stack("mask")), [e["n_nodes"] for e in encs]


class TestEncoding:
    def test_simple_tree(self):
        # (1, (2, 3)): post-order = 1, 2, 3, (2,3), (1, .)
        enc = encode_from_nested((1, (2, 3)), max_nodes=8)
        assert enc["n_nodes"] == 5
        np.testing.assert_array_equal(enc["word"][:5], [1, 2, 3, 0, 0])
        np.testing.assert_array_equal(enc["is_leaf"][:5], [1, 1, 1, 0, 0])
        assert enc["left"][3] == 1 and enc["right"][3] == 2
        assert enc["left"][4] == 0 and enc["right"][4] == 3

    def test_too_big_raises(self):
        with pytest.raises(ValueError, match="max_nodes"):
            encode_from_nested((1, (2, (3, 4))), max_nodes=3)


class TestBinaryTreeLSTM:
    def test_forward_shapes(self):
        m = BinaryTreeLSTM(vocab_size=20, embed_dim=8, hidden_size=8,
                           class_num=3).build(KEY).evaluate()
        inputs, _ = batch_trees([(1, (2, 3)), ((4, 5), 6)], max_nodes=8)
        out = m.forward(tuple(jnp.asarray(a) for a in inputs))
        assert out.shape == (2, 8, 3)
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0,
                                   rtol=1e-5)

    def test_composition_uses_children(self):
        """Swapping leaves must change the root representation.
        Output is root-first: node 0 IS the root."""
        m = BinaryTreeLSTM(20, 8, 8, 3).build(KEY).evaluate()
        (w, l, r, lf, mk), nn_ = batch_trees([(1, 2), (2, 1)], max_nodes=4)
        out = np.asarray(m.forward((jnp.asarray(w), jnp.asarray(l),
                                    jnp.asarray(r), jnp.asarray(lf),
                                    jnp.asarray(mk))))
        assert not np.allclose(out[0, 0], out[1, 0], atol=1e-6)

    def test_dict_input_matches_tuple(self):
        m = BinaryTreeLSTM(20, 8, 8, 3).build(KEY).evaluate()
        (w, l, r, lf, mk), _ = batch_trees([(1, (2, 3))], max_nodes=8)
        arrays = tuple(jnp.asarray(a) for a in (w, l, r, lf, mk))
        out_tuple = np.asarray(m.forward(arrays))
        out_dict = np.asarray(m.forward({
            "word": arrays[0], "left": arrays[1], "right": arrays[2],
            "is_leaf": arrays[3], "mask": arrays[4]}))
        np.testing.assert_allclose(out_tuple, out_dict, rtol=1e-6)

    def test_learns_toy_sentiment(self):
        """Root label = which of tokens {1,2} appears — learnable."""
        m = BinaryTreeLSTM(10, 16, 16, 2).build(KEY)
        trees = [((1, 3), (3, 3)), ((3, 2), (3, 3)),
                 ((3, 3), (1, 3)), ((3, 3), (3, 2)),
                 ((1, 1), (3, 3)), ((3, 3), (2, 2))]
        labels_root = [0, 1, 0, 1, 0, 1]
        (w, l, r, lf, mk), n_nodes = batch_trees(trees, max_nodes=8)
        inputs = tuple(jnp.asarray(a) for a in (w, l, r, lf, mk))
        y = jnp.asarray(labels_root)

        variables = m.variables

        def loss_fn(params):
            out, _ = m.apply({"params": params, "state": {}}, inputs,
                             training=True)
            root_logp = out[:, 0]  # root-first output convention
            return -jnp.mean(jnp.take_along_axis(root_logp, y[:, None], 1))

        step = jax.jit(jax.value_and_grad(loss_fn))
        params = variables["params"]
        for i in range(300):
            loss, g = step(params)
            params = jax.tree_util.tree_map(lambda p, gr: p - 0.2 * gr,
                                            params, g)
        assert float(loss) < 0.1, f"TreeLSTM failed to fit toy data: {loss}"

    def test_treenn_accuracy_on_root(self):
        out = jnp.asarray([[[0.9, 0.1], [0.2, 0.8]]])  # root = node 0 conv
        tgt = jnp.asarray([[0, 1]])
        r = TreeNNAccuracy().apply(out, tgt)
        assert r.result()[0] == 1.0

    def test_grad_flows_through_tree(self):
        m = BinaryTreeLSTM(10, 8, 8, 2)
        variables = m.init(KEY)
        inputs, _ = batch_trees([((1, 2), (3, 4))], max_nodes=8)
        inputs = tuple(jnp.asarray(a) for a in inputs)

        def loss(params):
            out, _ = m.apply({"params": params, "state": {}}, inputs)
            return jnp.sum(out)

        g = jax.grad(loss)(variables["params"])
        assert float(jnp.abs(g["compose"]["weight"]).sum()) > 0
        assert float(jnp.abs(g["embedding"]).sum()) > 0


# SST-style constituency parses: binary, mostly right-branching with
# left-branching sub-phrases and varying depth — the shapes the
# wavefront schedule must agree with the slot scan on
SST_TREES = [
    ((1, 2), (3, ((4, 5), (6, 7)))),
    (1, (2, (3, (4, (5, 6))))),            # fully right-branching
    (((((1, 2), 3), 4), 5), 6),            # fully left-branching
    ((1, (2, 3)), ((4, 5), (6, (7, 8)))),
    (1, 2),
    ((2, 3), 9),
]


class TestWavefront:
    """Level-batched (wavefront) schedule vs the roots-first serial
    slot scan — must be numerically interchangeable."""

    def _batch(self, max_nodes=16):
        encs = [encode_from_nested(t, max_nodes) for t in SST_TREES]
        stack = lambda k: jnp.asarray(np.stack([e[k] for e in encs]))
        six = tuple(stack(k) for k in ("word", "left", "right",
                                       "is_leaf", "mask", "level"))
        max_lv = max(e["n_levels"] for e in encs)
        return six, max_lv

    def test_encoding_levels(self):
        enc = encode_from_nested((1, (2, 3)), max_nodes=8)
        # post-order: 1, 2, 3, (2,3), root
        np.testing.assert_array_equal(enc["level"][:5], [0, 0, 0, 1, 2])
        assert enc["n_levels"] == 3
        with pytest.raises(ValueError, match="max_levels"):
            encode_from_nested((1, (2, (3, 4))), 8, max_levels=2)

    def test_forward_equivalence(self):
        six, max_lv = self._batch()
        legacy = BinaryTreeLSTM(20, 8, 8, 3).build(KEY).evaluate()
        wave = BinaryTreeLSTM(20, 8, 8, 3, max_levels=max_lv)
        out_legacy = legacy.forward(six[:5])
        out_wave, _ = wave.apply(legacy.variables, six)
        np.testing.assert_allclose(np.asarray(out_wave),
                                   np.asarray(out_legacy),
                                   rtol=1e-5, atol=1e-6)

    def test_grad_equivalence(self):
        six, max_lv = self._batch()
        legacy = BinaryTreeLSTM(20, 8, 8, 3)
        wave = BinaryTreeLSTM(20, 8, 8, 3, max_levels=max_lv)
        v = legacy.init(KEY)

        def loss(params, m, inp):
            out, _ = m.apply({"params": params, "state": {}}, inp)
            return jnp.sum(jnp.sin(out))

        g1 = jax.grad(loss)(v["params"], legacy, six[:5])
        g2 = jax.grad(loss)(v["params"], wave, six)
        flat1 = jax.tree_util.tree_leaves(g1)
        flat2 = jax.tree_util.tree_leaves(g2)
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_five_tuple_falls_back_to_slot_scan(self):
        six, max_lv = self._batch()
        wave = BinaryTreeLSTM(20, 8, 8, 3,
                              max_levels=max_lv).build(KEY).evaluate()
        out5 = wave.forward(six[:5])        # no level → slot scan
        out6 = wave.forward(six)            # wavefront
        np.testing.assert_allclose(np.asarray(out5), np.asarray(out6),
                                   rtol=1e-5, atol=1e-6)

    def test_too_deep_tree_poisons_not_silently_wrong(self):
        """A batch deeper than the model's static max_levels must fail
        LOUDLY (NaN) — never emit confidently-wrong zeros for the
        never-composed nodes."""
        six, max_lv = self._batch()
        shallow = BinaryTreeLSTM(20, 8, 8, 3,
                                 max_levels=max_lv - 2).build(KEY)
        out, _ = shallow.evaluate().apply(shallow.variables, six)
        assert np.isnan(np.asarray(out)).any()

    def test_dict_input_with_level(self):
        six, max_lv = self._batch()
        wave = BinaryTreeLSTM(20, 8, 8, 3,
                              max_levels=max_lv).build(KEY).evaluate()
        keys = ("word", "left", "right", "is_leaf", "mask", "level")
        out_d = wave.forward(dict(zip(keys, six)))
        out_t = wave.forward(six)
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_t),
                                   rtol=1e-6)
