"""Speculative decoding (ISSUE 15): the exactness claims.

The load-bearing bar: a SpeculativeEngine's emitted tokens are the
TARGET-ONLY token stream verbatim — greedy AND seeded sampling —
whatever the draft proposes, because acceptance compares the draft's
proposal against the target's own coupled sample (sample_logits is a
pure function of (logits, fold_in(seed, n)), and a verify row's logits
are bitwise the sequential Q=1 decode logits: positions ride the batch
axis through the same per-row ops, full-table-extent attention
included). Draft quality moves the accept rate, never a token.

Also pinned here: the rollback/block-table truncation invariants (a
rejected suffix is a length/table edit, never a scrub), the compile
contract with the spec pair armed (#buckets per model + draft decode +
ONE verify executable; zero new compiles on wave 2 and for a second
pair over the same models), and the draft-loss fallback (quiesce +
target-only continue, no request terminals from the draft)."""

import jax
import numpy as np
import pytest

from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.serving import (InferenceEngine, Request,
                               SpeculativeEngine)
from bigdl_tpu.utils import faults


_TARGET_LM = None
_DRAFT_LM = None


def _target_lm():
    global _TARGET_LM
    if _TARGET_LM is None:
        _TARGET_LM = build_lm(vocab_size=50, dim=32, num_heads=2,
                              num_layers=2, max_len=64)
        _TARGET_LM.build(jax.random.PRNGKey(0))
    return _TARGET_LM


def _draft_lm():
    global _DRAFT_LM
    if _DRAFT_LM is None:
        _DRAFT_LM = build_lm(vocab_size=50, dim=16, num_heads=2,
                             num_layers=1, max_len=64)
        _DRAFT_LM.build(jax.random.PRNGKey(1))
    return _DRAFT_LM


def _tgt(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_buckets", (8, 16))
    return InferenceEngine(_target_lm(), **kw)


def _drf(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_buckets", (8, 16))
    return InferenceEngine(_draft_lm(), **kw)


def _spec(k=3, draft_kw=None, target_kw=None):
    return SpeculativeEngine(_drf(**(draft_kw or {})),
                             _tgt(**(target_kw or {})), k=k)


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


class TestGreedyIdentity:
    def test_tokens_identical_across_both_buckets(self):
        """Greedy spec tokens == target-only tokens for ragged
        prompts spanning both prefill buckets, with slot eviction and
        reuse in both engines."""
        specs = [dict(prompt=[1, 2, 3], max_new_tokens=10, seed=1),
                 dict(prompt=list(range(1, 12)), max_new_tokens=8,
                      seed=2),                        # bucket 16
                 dict(prompt=[7, 3], max_new_tokens=12, seed=3),
                 dict(prompt=[9, 9, 2, 4, 1, 6, 2, 8, 3], seed=4,
                      max_new_tokens=6),              # bucket 16
                 dict(prompt=[5] * 5, max_new_tokens=9, seed=5)]
        ref = _tgt().run([Request(**s) for s in specs])
        got = _spec(k=3).run([Request(**s) for s in specs])
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        assert [r.finish_reason for r in got] \
            == [r.finish_reason for r in ref]
        assert all(r.status == "done" for r in got)

    def test_warm_and_cold_prefix_cache_identical(self):
        """Spec decode through a WARM radix prefix cache (draft and
        target mirrors both hit) emits the same tokens as the cold
        spec run and as cold target-only — the PR-8 warm==cold bar
        carried onto the speculative path."""
        share = [5, 9, 3, 7, 2, 8, 4, 6]
        A = dict(prompt=share + [11, 12], max_new_tokens=8, seed=7)
        B = dict(prompt=share + [13, 14, 15], max_new_tokens=8, seed=8)
        ref = _tgt().run([Request(**A), Request(**B)])
        eng = _spec(k=3, draft_kw=dict(block_size=4, max_len=32),
                    target_kw=dict(block_size=4, max_len=32))
        cold = eng.run([Request(**A)])[0]          # seeds both trees
        warm = eng.run([Request(**A), Request(**B)])
        assert cold.tokens == ref[0].tokens
        assert [r.tokens for r in warm] == [r.tokens for r in ref]
        # the mirrors really did reuse the draft-side prefix too
        assert eng.draft_engine.stats["prefix_hits"] >= 1
        assert eng.target_engine.stats["prefix_hits"] >= 1

    def test_full_accept_bonus_and_lag_path(self):
        """A same-model draft accepts every proposal: rounds emit k+1
        tokens (bonus included), the draft trails by one position and
        catches up next round — tokens still identical and accept
        rate exactly 1."""
        specs = [dict(prompt=[1, 2, 3], max_new_tokens=12, seed=1),
                 dict(prompt=[4, 5, 6, 7], max_new_tokens=11, seed=2)]
        ref = _tgt().run([Request(**s) for s in specs])
        eng = SpeculativeEngine(_tgt(), _tgt(), k=3)
        got = eng.run([Request(**s) for s in specs])
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        h = eng.health()["speculative"]
        assert h["accept_rate"] == 1.0
        assert h["tokens_per_round"] > 3.0     # k+1 amortization real

    def test_k1_keeps_full_horizon_after_bonus(self):
        """Regression (review): a fully-accepted round leaves the
        draft lagging one position, but the catch-up step must not
        shrink the next round's proposal horizon — at k=1 a `k - lag`
        cap would stall speculation permanently after the first
        bonus."""
        kw = dict(prompt=[1, 2, 3], max_new_tokens=10, seed=1)
        ref = _tgt(slots=1).run([Request(**kw)])[0]
        eng = SpeculativeEngine(_tgt(slots=1), _tgt(slots=1), k=1)
        got = eng.run([Request(**kw)])[0]
        assert got.tokens == ref.tokens
        h = eng.health()["speculative"]
        assert h["accept_rate"] == 1.0
        assert h["tokens_per_round"] == 2.0   # every round k+1 tokens

    def test_emitted_counts_only_tokens_that_left(self):
        """Regression (review): a stop_id landing on the round's first
        sample discards the whole accepted chain — `emitted` must
        count what actually left the engine, not the verify rows."""
        kw = dict(prompt=[1, 2, 3], max_new_tokens=10, seed=9)
        free = _tgt().run([Request(**kw)])[0]
        stop = free.tokens[0]                 # stops before any emit
        eng = SpeculativeEngine(_tgt(), _tgt(), k=3)
        got = eng.run([Request(**kw, stop_ids=(stop,))])[0]
        assert got.tokens == [] and got.finish_reason == "stop_id"
        h = eng.health()["speculative"]
        assert h["emitted"] == 0, h

    def test_stop_id_mid_chain(self):
        """A stop id landing inside an accepted chain truncates
        exactly where target-only stops (the stop token unemitted,
        later accepted tokens discarded)."""
        kw = dict(prompt=[1, 2, 3], max_new_tokens=10, seed=9)
        free = _tgt().run([Request(**kw)])[0]
        stop = free.tokens[4]
        cut = free.tokens.index(stop)
        ref = _tgt().run([Request(**kw, stop_ids=(stop,))])[0]
        got = SpeculativeEngine(_tgt(), _tgt(), k=3).run(
            [Request(**kw, stop_ids=(stop,))])[0]
        assert ref.finish_reason == "stop_id"
        assert got.finish_reason == "stop_id"
        assert got.tokens == ref.tokens == free.tokens[:cut]


class TestSamplingExactness:
    def test_seeded_streams_identical(self):
        """Seeded sampling: spec emits bitwise the target-only sampled
        stream for every seed — the coupled-acceptance construction
        makes the output the target sampler's verbatim, which is
        strictly stronger than distribution-exactness (identical per
        seed ⇒ identical in law)."""
        eng_ref = _tgt()
        eng_spec = _spec(k=3)
        for seed in range(10):
            kw = dict(prompt=[2 + seed % 5, 7, 1], max_new_tokens=8,
                      temperature=1.0, seed=seed)
            ref = eng_ref.run([Request(**kw)])[0]
            got = eng_spec.run([Request(**kw)])[0]
            assert got.tokens == ref.tokens, seed

    def test_filtered_sampling_identical(self):
        """top-k / top-p filters ride the verify rows as per-row
        operands exactly like the decode step's."""
        specs = [dict(prompt=[3, 1, 4], max_new_tokens=9,
                      temperature=0.8, top_k=7, seed=21),
                 dict(prompt=[1, 5, 9, 2], max_new_tokens=9,
                      temperature=1.2, top_p=0.85, seed=22),
                 dict(prompt=[6, 2], max_new_tokens=9, temperature=0.6,
                      top_k=12, top_p=0.7, seed=23)]
        ref = _tgt().run([Request(**s) for s in specs])
        got = _spec(k=2).run([Request(**s) for s in specs])
        assert [r.tokens for r in got] == [r.tokens for r in ref]


class TestRollback:
    def test_table_never_extends_past_clock_between_rounds(self):
        """The rollback invariant: after every speculative round, no
        slot's block table extends beyond the block holding its next
        write position, and the pool's accounting balances — a
        rejected suffix is a table/length edit, not a leak."""
        eng = _spec(k=3, draft_kw=dict(block_size=4, max_len=32),
                    target_kw=dict(block_size=4, max_len=32))
        t = eng.target_engine
        for s in (dict(prompt=[1, 2, 3], max_new_tokens=10, seed=1),
                  dict(prompt=[9, 8, 7, 6, 5], max_new_tokens=10,
                       seed=2)):
            eng.submit(Request(**s))
        rounds = 0
        while not eng.idle:
            eng.step()
            rounds += 1
            assert rounds < 100
            for i, req in enumerate(t._req):
                if req is None:
                    continue
                bi = int(t._pos[i]) // t.block_size
                assert all(t._table[i, j] == 0
                           for j in range(bi + 1, t._table.shape[1])), \
                    (i, bi, t._table[i])
            st = t._pool_mgr.stats()
            assert st["free"] + st["active"] + st["cached"] \
                == st["total"]
        # everything released at drain (prefix blocks may stay cached)
        assert all(r is None for r in t._req)
        assert t._pool_mgr.stats()["active"] == 0

    def test_rollback_slot_frees_lookahead_blocks(self):
        """Direct hook check: grow a slot's table past its clock, then
        rollback_slot detaches exactly the beyond-clock blocks and
        returns them to the pool."""
        eng = _tgt(block_size=4, max_len=32)
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=4))
        eng._admit()
        free0 = eng._pool_mgr.free_count
        assert not eng._ensure_blocks(horizons=[9, 0])   # pos 2 + 9
        grown = [int(b) for b in eng._table[0] if b]
        assert len(grown) >= 3                 # blocks 0..2 covered
        freed = eng.rollback_slot(0)
        assert freed == len(grown) - 1         # only the clock's stays
        # net vs post-admission: the lookahead block went back AND the
        # beyond-clock prefill pad block was detached too
        assert eng._pool_mgr.free_count == free0 + 1
        assert [int(b) for b in eng._table[0] if b] == grown[:1]
        # the engine still decodes to the same tokens as untouched
        ref = _tgt().run([Request(prompt=[1, 2, 3], max_new_tokens=4)])
        out = eng.run()
        assert out[0].tokens == ref[0].tokens


class TestCompileContract:
    def test_spec_pair_compiles_bounded_then_nothing(self):
        """Wave 1 over a fresh spec pair compiles exactly: one prefill
        per (model, bucket) used + the draft decode executable + the
        ONE verify executable. Wave 2 — new requests, mid-stream
        arrivals, slot reuse — compiles NOTHING; a second engine pair
        over the same models compiles NOTHING."""
        from bigdl_tpu.serving.engine import _TRACES

        d_lm = build_lm(vocab_size=50, dim=16, num_heads=2,
                        num_layers=1, max_len=64)
        d_lm.build(jax.random.PRNGKey(3))
        t_lm = build_lm(vocab_size=50, dim=32, num_heads=2,
                        num_layers=2, max_len=64)
        t_lm.build(jax.random.PRNGKey(4))

        def pair():
            return SpeculativeEngine(
                InferenceEngine(d_lm, slots=2, prefill_buckets=(8, 16)),
                InferenceEngine(t_lm, slots=2, prefill_buckets=(8, 16)),
                k=3)

        eng = pair()
        t0 = dict(_TRACES)
        rng = np.random.RandomState(0)
        wave = [Request(prompt=list(rng.randint(1, 50, n)),
                        max_new_tokens=int(rng.randint(3, 8)),
                        temperature=float(n % 2) * 0.8, seed=int(n))
                for n in (3, 10, 6, 12)]
        eng.run(wave)
        # both buckets on both models; draft B=2 decode + verify B=8
        assert _TRACES["prefill"] - t0["prefill"] == 4
        assert _TRACES["decode"] - t0["decode"] == 2
        t1 = dict(_TRACES)
        wave2 = [Request(prompt=list(rng.randint(1, 50, n)),
                         max_new_tokens=3, seed=int(n))
                 for n in (5, 11, 7)]
        eng.run(wave2)
        assert dict(_TRACES) == t1, "wave 2 must compile nothing"
        pair().run([Request(prompt=[1, 2, 3], max_new_tokens=4)])
        assert dict(_TRACES) == t1, \
            "a second pair over the same models must compile nothing"


class TestFallbackAndFaults:
    def test_draft_watchdog_trip_falls_back_bit_identical(self):
        """serve_slow against the draft's armed watchdog quiesces the
        draft (engine_degraded, NO request terminals from it) and the
        wrapper finishes every request target-only with tokens
        bit-identical to an undisturbed target-only run."""
        specs = [dict(prompt=[i + 1, i + 2, i + 3], max_new_tokens=6,
                      temperature=0.8, seed=30 + i) for i in range(4)]
        ref = _tgt().run([Request(**s) for s in specs])
        eng = _spec(k=3, draft_kw=dict(step_timeout_s=0.05))
        faults.set_plan(faults.FaultPlan("serve_slow@2"))
        try:
            got = eng.run([Request(**s) for s in specs])
        finally:
            faults.set_plan(None)
        assert eng.fallback is not None
        assert "watchdog" in eng.fallback
        assert eng.draft_engine.degraded is not None
        assert eng.draft_engine.stats["watchdog_trips"] == 1
        # zero lost, zero failed — the fallback is invisible
        assert all(r.status == "done" for r in got)
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        assert eng.stats["fallbacks"] == 1
        # quiesce never emitted terminals for the shadow mirrors
        assert eng.draft_engine.stats["failed"] == 0
        assert eng.draft_engine.completed == {}

    def test_draft_pool_exhaustion_falls_back_without_terminals(self):
        """Regression (review): draft pool pressure during lookahead
        growth must fall back — never finish a shadow mirror
        'pool_exhausted' (that would emit a request_terminal from the
        draft for a request still living in the target, and a second
        terminal later from the target)."""
        from bigdl_tpu import obs

        specs = [dict(prompt=[1, 2, 3], max_new_tokens=32, seed=1),
                 dict(prompt=[4, 5, 6], max_new_tokens=32, seed=2)]
        ref = _tgt().run([Request(**s) for s in specs])
        # 4 usable draft blocks: 2 admissions + 2 first crossings fit,
        # the position-32 crossing exhausts the pool mid-burst
        draft = _drf(pool_blocks=5)
        eng = SpeculativeEngine(draft, _tgt(), k=3)
        log = obs.set_event_log(obs.EventLog())
        try:
            got = eng.run([Request(**s) for s in specs])
            draft_terms = [e for e in log.events("request_terminal")
                           if e["engine"] == draft.obs_name]
        finally:
            obs.set_event_log(None)
        assert eng.fallback is not None and "pool" in eng.fallback
        assert all(r.status == "done" for r in got)
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        assert draft_terms == []               # zero phantom terminals
        assert draft.stats["requests_done"] == 0
        assert draft.completed == {}

    def test_poison_isolation_under_speculation(self):
        """A serve_nan row during verify evicts only its own request
        (status poisoned); the co-batched request's tokens stay
        bit-identical to running alone."""
        A = dict(prompt=[1, 2, 3], max_new_tokens=6, temperature=0.8,
                 seed=5)
        B = dict(prompt=[4, 5, 6, 7], max_new_tokens=6,
                 temperature=0.9, seed=9)
        alone_b = _tgt().run([Request(**B)])[0]
        eng = _spec(k=2)
        faults.set_plan(faults.FaultPlan("serve_nan@1"))
        try:
            got_a, got_b = eng.run([Request(**A), Request(**B)])
        finally:
            faults.set_plan(None)
        assert got_a.status == "poisoned"
        assert got_b.status == "done"
        assert got_b.tokens == alone_b.tokens

    def test_draft_absorbs_inline_faults_first(self):
        """The draft chain consults the fault plan before the verify
        each round, so an inline serve_err lands on the draft: the
        wrapper falls back (no retry burn, no outage) and the request
        still finishes done, target-only."""
        from bigdl_tpu.serving import EngineDegraded

        eng = _spec(k=2)
        faults.set_plan(faults.FaultPlan("serve_err@1"))
        try:
            got = eng.run([Request(prompt=[1, 2, 3],
                                   max_new_tokens=8, seed=1)])
        finally:
            faults.set_plan(None)
        assert got[0].status == "done"
        assert eng.fallback is not None and "failed" in eng.fallback
        with pytest.raises(EngineDegraded):
            eng.draft_engine.submit(Request(prompt=[1]))

    def test_verify_failure_degrades_target(self):
        """A failure in the VERIFY dispatch is an outage, not a
        fallback: with no retry budget the target degrades and the
        request fails keeping its partial tokens — the router's
        failover contract then applies above. Armed mid-run so the
        fault stepno is one the draft's (always-leading) counter has
        already passed."""
        eng = _spec(k=2)
        rid = eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=12,
                                 seed=1))
        first = eng.step()                     # round 1, clean
        assert not first
        t = eng.target_engine
        faults.set_plan(faults.FaultPlan(
            f"serve_err@{t.stats['decode_steps']}"))
        try:
            out = []
            while not eng.idle and t.degraded is None:
                out.extend(eng.step())
        finally:
            faults.set_plan(None)
        assert eng.degraded is not None
        assert eng.fallback is None            # the draft was healthy
        res = next(r for r in out if r.id == rid)
        assert res.status == "failed"
        assert len(res.tokens) >= 1            # round-1 tokens kept
        # the draft mirrors were released, with no terminal events
        assert eng.draft_engine.completed == {}
        assert all(r is None for r in eng.draft_engine._req)


class TestCrossLayout:
    def test_tp_target_unsharded_draft_identical(self):
        """Fleet story (ISSUE 15/10): a tensor-parallel TARGET behind
        an unsharded draft — the wrapper is layout-blind, and because
        tp decode is bitwise tp=1 decode (tp_shard_gather), the spec
        stream is still the unsharded target-only stream verbatim."""
        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices (tests/conftest.py arms "
                        "the 8-device CPU mesh)")
        from bigdl_tpu.parallel import make_mesh

        mesh = make_mesh({"model": 2}, devices=jax.devices()[:2])
        specs = [dict(prompt=[1, 2, 3], max_new_tokens=8, seed=1),
                 dict(prompt=[4, 5, 6, 7], max_new_tokens=8,
                      temperature=0.8, seed=2)]
        ref = _tgt().run([Request(**s) for s in specs])
        eng = SpeculativeEngine(
            _drf(), _tgt(tp_mesh=mesh), k=3)
        got = eng.run([Request(**s) for s in specs])
        assert [r.tokens for r in got] == [r.tokens for r in ref]
        assert eng.tp == 2 and eng.draft_engine.tp == 1


class TestSurfaceAndGuards:
    def test_constructor_guards(self):
        with pytest.raises(ValueError, match="k must be"):
            SpeculativeEngine(_drf(), _tgt(), k=0)
        with pytest.raises(ValueError, match="distinct"):
            t = _tgt()
            SpeculativeEngine(t, t)
        with pytest.raises(ValueError, match="slots"):
            SpeculativeEngine(_drf(slots=3), _tgt(slots=2))
        with pytest.raises(ValueError, match="buckets"):
            SpeculativeEngine(_drf(prefill_buckets=(8,)), _tgt())
        big = build_lm(vocab_size=60, dim=16, num_heads=2,
                       num_layers=1, max_len=64)
        big.build(jax.random.PRNGKey(9))
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeEngine(
                InferenceEngine(big, slots=2, prefill_buckets=(8, 16)),
                _tgt())

    def test_health_and_counters(self):
        from bigdl_tpu import obs

        obs.set_registry(obs.MetricsRegistry())
        try:
            eng = _spec(k=3)
            eng.run([Request(prompt=[1, 2, 3], max_new_tokens=8,
                             seed=1)])
            h = eng.health()
            sp = h["speculative"]
            assert sp["k"] == 3 and sp["fallback"] is None
            assert sp["rounds"] >= 1
            assert sp["proposed"] == sp["accepted"] + sp["wasted"]
            assert sp["emitted"] == 8
            assert sp["accept_rate"] is not None
            assert sp["draft"]["state"] == "ok"
            snap = obs.get_registry().snapshot()["metrics"]
            acc = snap["serving_spec_accepted_tokens_total"]["series"]
            was = snap["serving_spec_wasted_draft_total"]["series"]
            assert sum(s["value"] for s in acc) == sp["accepted"]
            assert sum(s["value"] for s in was) == sp["wasted"]
        finally:
            obs.set_registry(None)

    def test_spec_events_registered_and_emitted(self):
        from bigdl_tpu import obs
        from bigdl_tpu.obs.events import EVENT_KINDS, validate_record

        assert "spec_verify" in EVENT_KINDS
        assert "spec_fallback" in EVENT_KINDS
        log = obs.set_event_log(obs.EventLog())
        try:
            eng = _spec(k=2)
            eng.run([Request(prompt=[1, 2, 3], max_new_tokens=6,
                             seed=2)])
            evs = log.events("spec_verify")
            assert evs and all(not validate_record(e) for e in evs)
            assert sum(e["emitted"] for e in evs) == 6
        finally:
            obs.set_event_log(None)
