"""TensorFlow interop tests (reference: utils/tf/TensorflowLoaderSpec /
TensorflowSaverSpec — SURVEY.md §4 "Interop").

Real TensorFlow (available in the image) is the oracle: TF builds and
runs a frozen graph, our loader imports the same bytes via the bundled
wire-compatible proto; outputs must match. The saver round-trips both
through our own loader and through real TF.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
tf = pytest.importorskip("tensorflow")

from bigdl_tpu import nn
from bigdl_tpu.utils import tf as tf_interop

KEY = jax.random.PRNGKey(0)


def _freeze(graph, outputs, path):
    """Serialize a TF graph (constants only) to a frozen .pb file."""
    gd = graph.as_graph_def()
    with open(path, "wb") as f:
        f.write(gd.SerializeToString())


def _tf_run(graph, feeds, fetch):
    with tf.compat.v1.Session(graph=graph) as sess:
        return sess.run(fetch, feeds)


def test_load_mlp_matches_tf(tmp_path):
    rng = np.random.default_rng(0)
    w1 = rng.standard_normal((10, 16)).astype(np.float32)
    b1 = rng.standard_normal((16,)).astype(np.float32)
    w2 = rng.standard_normal((16, 4)).astype(np.float32)
    b2 = rng.standard_normal((4,)).astype(np.float32)

    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 10], name="input")
        h = tf.nn.relu(tf.nn.bias_add(tf.matmul(x, w1), b1), name="h")
        y = tf.nn.softmax(tf.nn.bias_add(tf.matmul(h, w2), b2), name="prob")
    path = tmp_path / "mlp.pb"
    _freeze(g, ["prob"], str(path))

    model, variables = tf_interop.load(str(path))
    xs = rng.standard_normal((3, 10)).astype(np.float32)
    want = _tf_run(g, {"input:0": xs}, "prob:0")
    got, _ = model.apply(variables, jnp.asarray(xs), training=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_load_cnn_matches_tf(tmp_path):
    rng = np.random.default_rng(1)
    wc = rng.standard_normal((3, 3, 2, 5)).astype(np.float32) * 0.3
    bc = rng.standard_normal((5,)).astype(np.float32)
    scale = rng.uniform(0.5, 1.5, 5).astype(np.float32)
    offset = rng.standard_normal((5,)).astype(np.float32)
    mean = rng.standard_normal((5,)).astype(np.float32)
    var = rng.uniform(0.5, 2.0, 5).astype(np.float32)
    wf = rng.standard_normal((5 * 4 * 4, 7)).astype(np.float32) * 0.2

    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 8, 8, 2],
                                     name="input")
        h = tf.nn.conv2d(x, wc, strides=[1, 1, 1, 1], padding="SAME")
        h = tf.nn.bias_add(h, bc)
        h = tf.compat.v1.nn.fused_batch_norm(
            h, scale, offset, mean, var, epsilon=1e-3, is_training=False)[0]
        h = tf.nn.relu(h)
        h = tf.nn.max_pool2d(h, 2, 2, "VALID")
        h = tf.reshape(h, [-1, 5 * 4 * 4])
        y = tf.matmul(h, wf, name="logits")
    path = tmp_path / "cnn.pb"
    _freeze(g, ["logits"], str(path))

    model, variables = tf_interop.load(str(path))
    xs = rng.standard_normal((2, 8, 8, 2)).astype(np.float32)
    want = _tf_run(g, {"input:0": xs}, "logits:0")
    got, _ = model.apply(variables, jnp.asarray(xs), training=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_load_depthwise_and_avgpool_matches_tf(tmp_path):
    rng = np.random.default_rng(2)
    wd = rng.standard_normal((3, 3, 4, 2)).astype(np.float32) * 0.4

    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 6, 6, 4],
                                     name="input")
        h = tf.nn.depthwise_conv2d(x, wd, strides=[1, 1, 1, 1],
                                   padding="SAME")
        y = tf.nn.avg_pool2d(h, 2, 2, "SAME", name="out")
    path = tmp_path / "dw.pb"
    _freeze(g, ["out"], str(path))

    model, variables = tf_interop.load(str(path))
    xs = rng.standard_normal((2, 6, 6, 4)).astype(np.float32)
    want = _tf_run(g, {"input:0": xs}, "out:0")
    got, _ = model.apply(variables, jnp.asarray(xs), training=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_load_branches_concat_mean(tmp_path):
    rng = np.random.default_rng(3)
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 4, 4, 3],
                                     name="input")
        a = tf.nn.relu(x)
        b = tf.nn.tanh(x)
        c = tf.concat([a, b], axis=3)
        y = tf.reduce_mean(c, axis=[1, 2], name="gap")
    path = tmp_path / "branch.pb"
    _freeze(g, ["gap"], str(path))

    model, variables = tf_interop.load(str(path))
    xs = rng.standard_normal((2, 4, 4, 3)).astype(np.float32)
    want = _tf_run(g, {"input:0": xs}, "gap:0")
    got, _ = model.apply(variables, jnp.asarray(xs), training=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def _lenet_like():
    return nn.Sequential(
        nn.SpatialConvolution(1, 4, 5, 5).set_name("c1"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([4 * 12 * 12]),
        nn.Linear(4 * 12 * 12, 10).set_name("fc"),
        nn.LogSoftMax(),
    )


def test_save_roundtrip_own_loader(tmp_path):
    m = _lenet_like()
    variables = m.init(KEY)
    path = tmp_path / "m.pb"
    tf_interop.save(m, variables, str(path), (1, 28, 28, 1))

    m2, v2 = tf_interop.load(str(path))
    xs = np.random.default_rng(4).standard_normal(
        (2, 28, 28, 1)).astype(np.float32)
    want, _ = m.apply(variables, jnp.asarray(xs), training=False)
    got, _ = m2.apply(v2, jnp.asarray(xs), training=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_save_loads_in_real_tensorflow(tmp_path):
    m = _lenet_like()
    variables = m.init(KEY)
    path = tmp_path / "m.pb"
    tf_interop.save(m, variables, str(path), (1, 28, 28, 1))

    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(path.read_bytes())
    g = tf.Graph()
    with g.as_default():
        tf.import_graph_def(gd, name="")
    xs = np.random.default_rng(5).standard_normal(
        (2, 28, 28, 1)).astype(np.float32)
    want, _ = m.apply(variables, jnp.asarray(xs), training=False)
    got = _tf_run(g, {"input:0": xs}, "output:0")
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4, atol=1e-4)


def test_graph_model_with_branches_roundtrip(tmp_path):
    x = nn.Input()
    h = nn.SpatialConvolution(2, 3, 3, 3, 1, 1, -1, -1).set_name("c")(x)
    a = nn.ReLU()(h)
    b = nn.Tanh()(h)
    j = nn.CAddTable()(a, b)
    y = nn.SoftMax()(nn.Reshape([3 * 16]).set_name("r")(j))
    m = nn.Graph(x, y)
    variables = m.init(KEY)
    path = tmp_path / "g.pb"
    tf_interop.save(m, variables, str(path), (1, 4, 4, 2))

    m2, v2 = tf_interop.load(str(path))
    xs = np.random.default_rng(6).standard_normal(
        (2, 4, 4, 2)).astype(np.float32)
    want, _ = m.apply(variables, jnp.asarray(xs), training=False)
    got, _ = m2.apply(v2, jnp.asarray(xs), training=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_imported_model_is_trainable(tmp_path):
    """Imported TF graphs are native models: jax.grad flows into the
    imported weights (replaces the reference's BigDLSessionImpl)."""
    rng = np.random.default_rng(7)
    w = rng.standard_normal((6, 3)).astype(np.float32)
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 6], name="input")
        y = tf.nn.log_softmax(tf.matmul(x, w), name="out")
    path = tmp_path / "t.pb"
    _freeze(g, ["out"], str(path))
    model, variables = tf_interop.load(str(path))

    xs = jnp.asarray(rng.standard_normal((8, 6)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 3, 8), jnp.int32)
    crit = nn.ClassNLLCriterion()

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params, "state": variables["state"]}, xs,
            training=False)
        return crit(out, ys)

    grads = jax.grad(loss_fn)(variables["params"])
    gnorm = sum(float(jnp.abs(g).sum())
                for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


class TestLayoutGuards:
    def test_nchw_rejected(self):
        """NCHW frozen graphs must refuse to import rather than convert
        silently with wrong results (ADVICE r1)."""
        import pytest

        from bigdl_tpu.utils.tf.loader import _require_nhwc

        class _Attr:
            def __init__(self, s):
                self.s = s

        class _Node:
            name = "conv1"
            attr = {"data_format": _Attr(b"NCHW")}

        with pytest.raises(NotImplementedError, match="NHWC"):
            _require_nhwc(_Node())

        class _NodeOK:
            name = "conv2"
            attr = {"data_format": _Attr(b"NHWC")}

        _require_nhwc(_NodeOK())  # no raise

        class _NodeNoAttr:
            name = "conv3"
            attr = {}

        _require_nhwc(_NodeNoAttr())  # defaults are fine


def test_load_real_mobilenet_frozen_graph(tmp_path):
    """A REAL public classic topology end-to-end (VERDICT r4 item 8):
    MobileNetV1 (alpha=0.25, 96x96) built by the oracle TF itself,
    frozen to constants — 565 nodes of Conv2D/DepthwiseConv2dNative/
    decomposed-BN (Mul/Sub/Rsqrt)/Relu6/Pad/Mean/Softmax — loaded by
    our wire-compatible loader, with numeric parity vs TF execution
    AND gradients flowing into the imported weights (fine-tune path)."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    keras_model = tf.keras.applications.MobileNet(
        weights=None, alpha=0.25, input_shape=(96, 96, 3))
    conc = tf.function(keras_model).get_concrete_function(
        tf.TensorSpec((1, 96, 96, 3), tf.float32))
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    path = tmp_path / "mobilenet_v1_025.pb"
    path.write_bytes(gd.SerializeToString())

    model, variables = tf_interop.load(str(path))

    rng = np.random.default_rng(11)
    xs = rng.standard_normal((2, 96, 96, 3)).astype(np.float32)
    want = np.asarray(frozen(tf.constant(xs[:1]))[0])
    got, _ = model.apply(variables, jnp.asarray(xs[:1]), training=False)
    got = np.asarray(got).reshape(want.shape)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    # fine-tune: grads flow into every imported conv/dense weight
    ys = jnp.asarray(rng.integers(0, 1000, 2), jnp.int32)

    def loss_fn(params):
        out, _ = model.apply(
            {"params": params, "state": variables["state"]},
            jnp.asarray(xs), training=False)
        logp = jnp.log(jnp.clip(out.reshape(2, -1), 1e-9, 1.0))
        return nn.ClassNLLCriterion()(logp, ys)

    grads = jax.grad(loss_fn)(variables["params"])
    leaves = jax.tree_util.tree_leaves(grads)
    assert len(leaves) > 20  # dozens of imported weight tensors
    gnorm = sum(float(jnp.abs(g).sum()) for g in leaves)
    assert np.isfinite(gnorm) and gnorm > 0
