"""Optim method / schedule / trigger tests
(reference: optim/SGDSpec, AdamSpec, TriggerSpec)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.optim import (
    SGD, Adam, Adagrad, Adamax, RMSprop, AdaDelta, Ftrl,
    Default, Step, MultiStep, Poly, Warmup, SequentialSchedule, Plateau,
    Trigger, Top1Accuracy, Top5Accuracy, ValidationResult,
)

KEY = jax.random.PRNGKey(0)


def rosenbrock_like_quadratic(params):
    # f(w) = sum((w - 3)^2); minimum at w = 3
    return jnp.sum((params["w"] - 3.0) ** 2)


def converges(method, iters=600, tol=1e-2):
    params = {"w": jnp.zeros(4)}
    slots = method.init_slots(params)
    grad_fn = jax.jit(jax.grad(rosenbrock_like_quadratic))
    state = {"epoch": 1, "neval": 0}
    for i in range(iters):
        g = grad_fn(params)
        lr = method.current_rate(state)
        params, slots = method.update(g, params, slots,
                                      jnp.asarray(lr), jnp.asarray(i))
        state["neval"] += 1
    return float(jnp.max(jnp.abs(params["w"] - 3.0))) < tol


class TestMethodsConverge:
    def test_sgd(self):
        assert converges(SGD(learningrate=0.1))

    def test_sgd_momentum_nesterov(self):
        assert converges(SGD(learningrate=0.05, momentum=0.9, dampening=0.0,
                             nesterov=True))

    @pytest.mark.slow
    def test_adam(self):
        # 20+ s toy-convergence run; Adam's update math is pinned
        # exactly by TestSGDvsTorch::test_adam_trajectory_matches_torch
        # (per-step oracle) — tier-2 keeps the redundant slow check
        assert converges(Adam(learningrate=0.1))

    def test_adagrad(self):
        assert converges(Adagrad(learningrate=1.0))

    @pytest.mark.slow
    def test_adamax(self):
        # ~40 s toy-convergence run; Adamax's update math is pinned
        # exactly by test_adamax_trajectory_matches_torch (per-step
        # oracle below) — tier-2 keeps the redundant slow check
        assert converges(Adamax(learningrate=0.5))

    def test_adamax_trajectory_matches_torch(self):
        torch = pytest.importorskip("torch")
        w0 = np.asarray([1.0, -2.0, 0.5], np.float32)
        grads_seq = [np.asarray([0.5, -0.25, 1.5], np.float32) * (i + 1)
                     for i in range(6)]
        method = Adamax(learningrate=0.05, beta1=0.9, beta2=0.999,
                        epsilon=1e-8)
        params = {"w": jnp.asarray(w0)}
        slots = method.init_slots(params)
        for i, g in enumerate(grads_seq):
            params, slots = method.update({"w": jnp.asarray(g)}, params,
                                          slots, jnp.asarray(0.05),
                                          jnp.asarray(i))
        tw = torch.tensor(w0.copy(), requires_grad=True)
        opt = torch.optim.Adamax([tw], lr=0.05, betas=(0.9, 0.999),
                                 eps=1e-8)
        for g in grads_seq:
            opt.zero_grad()
            tw.grad = torch.tensor(g)
            opt.step()
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   tw.detach().numpy(), rtol=1e-5)

    def test_rmsprop(self):
        assert converges(RMSprop(learningrate=0.1))

    def test_adadelta_matches_torch(self):
        torch = pytest.importorskip("torch")
        w0 = np.asarray([1.0, -2.0], np.float32)
        grads_seq = [np.asarray([0.5, -0.25], np.float32) * (i + 1)
                     for i in range(6)]
        method = AdaDelta(decayrate=0.9, epsilon=1e-6)
        params = {"w": jnp.asarray(w0)}
        slots = method.init_slots(params)
        for i, g in enumerate(grads_seq):
            params, slots = method.update({"w": jnp.asarray(g)}, params, slots,
                                          jnp.asarray(1.0), jnp.asarray(i))
        tw = torch.tensor(w0.copy(), requires_grad=True)
        opt = torch.optim.Adadelta([tw], lr=1.0, rho=0.9, eps=1e-6)
        for g in grads_seq:
            opt.zero_grad()
            tw.grad = torch.tensor(g)
            opt.step()
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   tw.detach().numpy(), rtol=1e-5)

    def test_ftrl(self):
        assert converges(Ftrl(learningrate=1.0))


class TestSGDvsTorch:
    def test_momentum_trajectory_matches_torch(self):
        torch = pytest.importorskip("torch")
        w0 = np.asarray([1.0, -2.0, 0.5], np.float32)
        grads_seq = [np.asarray([0.1, -0.2, 0.3], np.float32) * (i + 1)
                     for i in range(5)]

        method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0,
                     weightdecay=0.01)
        params = {"w": jnp.asarray(w0)}
        slots = method.init_slots(params)
        for i, g in enumerate(grads_seq):
            params, slots = method.update({"w": jnp.asarray(g)}, params, slots,
                                          jnp.asarray(0.1), jnp.asarray(i))

        tw = torch.tensor(w0.copy(), requires_grad=True)
        opt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=0.01)
        for g in grads_seq:
            opt.zero_grad()
            tw.grad = torch.tensor(g)
            opt.step()
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   tw.detach().numpy(), rtol=1e-5)

    def test_adam_trajectory_matches_torch(self):
        torch = pytest.importorskip("torch")
        w0 = np.asarray([1.0, -1.0], np.float32)
        grads_seq = [np.asarray([0.5, -0.3], np.float32)] * 4
        method = Adam(learningrate=0.01)
        params = {"w": jnp.asarray(w0)}
        slots = method.init_slots(params)
        for i, g in enumerate(grads_seq):
            params, slots = method.update({"w": jnp.asarray(g)}, params, slots,
                                          jnp.asarray(0.01), jnp.asarray(i))
        tw = torch.tensor(w0.copy(), requires_grad=True)
        opt = torch.optim.Adam([tw], lr=0.01)
        for g in grads_seq:
            opt.zero_grad()
            tw.grad = torch.tensor(g)
            opt.step()
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   tw.detach().numpy(), rtol=1e-5, atol=1e-6)


class TestSchedules:
    def _state(self, neval, epoch=1):
        return {"neval": neval, "epoch": epoch}

    def test_default_decay(self):
        m = SGD(learningrate=1.0, learningrate_decay=0.1)
        assert m.current_rate(self._state(0)) == 1.0
        np.testing.assert_allclose(m.current_rate(self._state(10)), 0.5)

    def test_step(self):
        m = SGD(learningrate=1.0, learningrate_schedule=Step(10, 0.5))
        assert m.current_rate(self._state(9)) == 1.0
        assert m.current_rate(self._state(10)) == 0.5
        assert m.current_rate(self._state(25)) == 0.25

    def test_multistep(self):
        m = SGD(learningrate=1.0, learningrate_schedule=MultiStep([5, 8], 0.1))
        assert m.current_rate(self._state(4)) == 1.0
        np.testing.assert_allclose(m.current_rate(self._state(6)), 0.1)
        np.testing.assert_allclose(m.current_rate(self._state(9)), 0.01)

    def test_poly(self):
        m = SGD(learningrate=1.0, learningrate_schedule=Poly(2.0, 100))
        np.testing.assert_allclose(m.current_rate(self._state(50)), 0.25)

    def test_warmup_sequential(self):
        seq = SequentialSchedule().add(Warmup(5), 5).add(Default(), 1000)
        m = SGD(learningrate=1.0, learningrate_schedule=seq)
        np.testing.assert_allclose(m.current_rate(self._state(0)), 0.2)
        np.testing.assert_allclose(m.current_rate(self._state(4)), 1.0)
        np.testing.assert_allclose(m.current_rate(self._state(100)), 1.0)

    def test_plateau(self):
        p = Plateau(factor=0.5, patience=2, mode="max")
        m = SGD(learningrate=1.0, learningrate_schedule=p)
        for score in [0.5, 0.5, 0.5]:
            p.on_metric(score)
        np.testing.assert_allclose(m.current_rate(self._state(0)), 0.5)


class TestTriggers:
    def test_max_epoch(self):
        t = Trigger.max_epoch(3)
        assert not t({"epoch": 3, "neval": 100})
        assert t({"epoch": 4, "neval": 100})

    def test_every_epoch_fires_on_transition(self):
        t = Trigger.every_epoch()
        assert not t({"epoch": 1, "neval": 5})
        assert t({"epoch": 2, "neval": 10})
        assert not t({"epoch": 2, "neval": 11})
        assert t({"epoch": 3, "neval": 20})

    def test_several_iteration(self):
        t = Trigger.several_iteration(5)
        assert not t({"epoch": 1, "neval": 4})
        assert t({"epoch": 1, "neval": 5})

    def test_combinators(self):
        t = Trigger.and_(Trigger.max_epoch(2), Trigger.max_iteration(10))
        assert not t({"epoch": 3, "neval": 5})
        assert t({"epoch": 3, "neval": 10})


class TestValidationMethods:
    def test_top1(self):
        out = jnp.asarray([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
        tgt = jnp.asarray([1, 0, 0])
        r = Top1Accuracy().apply(out, tgt)
        np.testing.assert_allclose(r.result()[0], 2.0 / 3.0)

    def test_top5(self):
        out = jnp.eye(8)[:3] * 0.1 + jnp.arange(8) * 0.01
        tgt = jnp.asarray([7, 6, 5])
        r = Top5Accuracy().apply(out, tgt)
        assert r.result()[0] == 1.0

    def test_masked_padding(self):
        out = jnp.asarray([[0.9, 0.1], [0.9, 0.1], [0.9, 0.1], [0.9, 0.1]])
        tgt = jnp.asarray([0, 0, 1, 1])
        r = Top1Accuracy().apply(out, tgt, real_size=2)
        assert r.result() == (1.0, 2)

    def test_result_merge(self):
        a = ValidationResult(3, 4)
        b = ValidationResult(1, 4)
        assert (a + b).result() == (0.5, 8)


class TestGradientAccumulation:
    def test_matches_large_batch_sgd(self):
        """4 micro-batches of 8 with accumulation == one batch of 32."""
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Optimizer

        rng = np.random.RandomState(0)
        xs = rng.rand(32, 4).astype(np.float32)
        ys = rng.randint(0, 2, 32).astype(np.int32)

        def train(batch_size, accum):
            model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
            ds = DataSet.array(
                [Sample(x, int(y)) for x, y in zip(xs, ys)], seed=7)
            opt = (Optimizer(model, ds, nn.ClassNLLCriterion(),
                             batch_size=batch_size, seed=3)
                   .set_optim_method(SGD(learningrate=0.5))
                   .set_end_when(Trigger.max_iteration(32 // batch_size)))
            if accum > 1:
                opt.set_gradient_accumulation(accum)
            m = opt.optimize()
            return [np.asarray(p) for _, p in m.parameters()]

        # same epoch of data either way; shuffle order is seed-fixed, and
        # mean-reduced criterion + grad averaging make the updates equal
        big = train(32, 1)
        small = train(8, 4)
        for a, b in zip(big, small):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_validates_n(self):
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Optimizer

        model = nn.Sequential(nn.Linear(2, 2))
        ds = DataSet.array([Sample(np.zeros(2, np.float32), 0)])
        with pytest.raises(ValueError):
            Optimizer(model, ds, nn.ClassNLLCriterion(),
                      batch_size=1).set_gradient_accumulation(0)

    def test_adam_stepno_counts_updates_not_microbatches(self):
        """Bias correction must see update t, not micro-batch index."""
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Optimizer

        rng = np.random.RandomState(0)
        xs = rng.rand(32, 4).astype(np.float32)
        ys = rng.randint(0, 2, 32).astype(np.int32)

        def train(batch_size, accum):
            model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
            ds = DataSet.array(
                [Sample(x, int(y)) for x, y in zip(xs, ys)], seed=7)
            opt = (Optimizer(model, ds, nn.ClassNLLCriterion(),
                             batch_size=batch_size, seed=3)
                   .set_optim_method(Adam(learningrate=0.05))
                   .set_end_when(Trigger.max_iteration(32 // batch_size)))
            if accum > 1:
                opt.set_gradient_accumulation(accum)
            m = opt.optimize()
            return [np.asarray(p) for _, p in m.parameters()]

        big = train(32, 1)
        small = train(8, 4)
        for a, b in zip(big, small):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_mesh_plus_accumulation_supported(self):
        """Mesh + accumulation dispatches to DistriOptimizer and trains
        (equivalence with one large-batch DP step is covered in
        tests/test_distributed.py::TestMeshGradAccumulation)."""
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Optimizer
        from bigdl_tpu.parallel import make_mesh

        rng = np.random.RandomState(0)
        samples = [Sample(rng.rand(2).astype(np.float32), int(y))
                   for y in rng.randint(0, 2, 32)]
        model = nn.Sequential(nn.Linear(2, 2), nn.LogSoftMax())
        model.build(jax.random.PRNGKey(0))
        before = [np.asarray(p).copy() for _, p in model.parameters()]
        m = (Optimizer(model, DataSet.array(samples), nn.ClassNLLCriterion(),
                       batch_size=8)
             .set_gradient_accumulation(2)
             .set_mesh(make_mesh({"data": 8}))
             .set_end_when(Trigger.max_iteration(4))
             .optimize())
        after = [np.asarray(p) for _, p in m.parameters()]
        assert any(not np.allclose(a, b) for a, b in zip(before, after))


class TestMAE:
    def test_mae_values(self):
        from bigdl_tpu.optim import MAE

        out = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        tgt = jnp.asarray([[1.5, 2.0], [2.0, 4.0]])
        s, c = MAE().stats(out, tgt)
        assert abs(float(s) / float(c) - 0.375) < 1e-6

    def test_mae_respects_real_size(self):
        from bigdl_tpu.optim import MAE

        out = jnp.asarray([[2.0], [100.0]])
        tgt = jnp.asarray([[1.0], [0.0]])
        s, c = MAE().stats(out, tgt, real_size=1)
        assert float(c) == 1.0 and abs(float(s) - 1.0) < 1e-6


class TestGradAccumTailFlush:
    def test_partial_tail_is_flushed_at_end(self):
        """End trigger firing mid-accumulation-cycle must not discard the
        pending micro-batch gradients (ADVICE r1): 6 micro-batches with
        accum=4 = one full update + a flushed partial of 2, so the result
        differs from stopping at the 4-micro-batch update boundary."""
        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, Sample
        from bigdl_tpu.optim import Optimizer

        rng = np.random.RandomState(3)
        xs = rng.rand(48, 4).astype(np.float32)
        ys = rng.randint(0, 2, 48).astype(np.int32)

        def train(iters):
            model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
            model.build(jax.random.PRNGKey(11))
            ds = DataSet.array(
                [Sample(x, int(y)) for x, y in zip(xs, ys)], seed=7)
            m = (Optimizer(model, ds, nn.ClassNLLCriterion(),
                           batch_size=8, seed=3)
                 .set_optim_method(SGD(learningrate=0.5))
                 .set_gradient_accumulation(4)
                 .set_end_when(Trigger.max_iteration(iters))
                 .optimize())
            return [np.asarray(p) for _, p in m.parameters()]

        at_boundary = train(4)
        with_tail = train(6)
        assert any(not np.allclose(a, b)
                   for a, b in zip(at_boundary, with_tail)), \
            "partial accumulator was silently discarded at loop exit"
