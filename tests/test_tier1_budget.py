"""scripts/check_tier1_budget.py — pure text parsing + threshold
logic, so this runs in milliseconds (the actual budget check against a
real run is a standalone invocation; see CLAUDE.md)."""

import importlib.util
import os

_SYNTHETIC = """\
============================= slowest durations ==============================
120.50s call     tests/test_models.py::test_resnet
  0.30s setup    tests/test_models.py::test_resnet
 45.25s call     tests/test_serving.py::TestEngine::test_matches_run_alone
  0.05s teardown tests/test_serving.py::TestEngine::test_matches_run_alone
not a duration line
12 passed in 166.2s
"""


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "check_tier1_budget.py")
    spec = importlib.util.spec_from_file_location("check_tier1_budget",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

def test_parse_and_projection():
    m = _load()
    entries = m.parse_durations(_SYNTHETIC)
    assert len(entries) == 4
    assert entries[0] == (120.5, "call", "tests/test_models.py::test_resnet")
    assert m.projected_runtime_s(entries, overhead_s=40.0) == \
        40.0 + 120.5 + 0.3 + 45.25 + 0.05
    top = m.slowest_tests(entries, top=1)
    assert top == [(120.8, "tests/test_models.py::test_resnet")]


def test_main_verdicts(tmp_path, capsys):
    m = _load()
    log = tmp_path / "t1.log"
    log.write_text(_SYNTHETIC)
    assert m.main(["--log", str(log), "--budget", "500"]) == 0
    assert m.main(["--log", str(log), "--budget", "100"]) == 1
    out = capsys.readouterr().out
    assert "OVER BUDGET" in out and "test_resnet" in out
    log.write_text("no durations here\n")
    assert m.main(["--log", str(log)]) == 2
    assert m.main(["--log", str(tmp_path / "missing.log")]) == 2
