"""scripts/check_tier1_budget.py — pure text parsing + threshold
logic, so this runs in milliseconds (the actual budget check against a
real run is a standalone invocation; see CLAUDE.md)."""

import importlib.util
import os

_SYNTHETIC = """\
============================= slowest durations ==============================
120.50s call     tests/test_models.py::test_resnet
  0.30s setup    tests/test_models.py::test_resnet
 45.25s call     tests/test_serving.py::TestEngine::test_matches_run_alone
  0.05s teardown tests/test_serving.py::TestEngine::test_matches_run_alone
not a duration line
12 passed in 166.2s
"""


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "check_tier1_budget.py")
    spec = importlib.util.spec_from_file_location("check_tier1_budget",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

def test_parse_and_projection():
    m = _load()
    entries = m.parse_durations(_SYNTHETIC)
    assert len(entries) == 4
    assert entries[0] == (120.5, "call", "tests/test_models.py::test_resnet")
    assert m.projected_runtime_s(entries, overhead_s=40.0) == \
        40.0 + 120.5 + 0.3 + 45.25 + 0.05
    top = m.slowest_tests(entries, top=1)
    assert top == [(120.8, "tests/test_models.py::test_resnet")]


def test_main_verdicts(tmp_path, capsys):
    m = _load()
    log = tmp_path / "t1.log"
    log.write_text(_SYNTHETIC)
    assert m.main(["--log", str(log), "--budget", "500"]) == 0
    assert m.main(["--log", str(log), "--budget", "100"]) == 1
    out = capsys.readouterr().out
    assert "OVER BUDGET" in out and "test_resnet" in out
    log.write_text("no durations here\n")
    assert m.main(["--log", str(log)]) == 2
    assert m.main(["--log", str(tmp_path / "missing.log")]) == 2


def _scaled_log(factor):
    """_SYNTHETIC with every duration multiplied by `factor`."""
    out = []
    for line in _SYNTHETIC.splitlines():
        e = _load().parse_durations(line)
        if e:
            secs, phase, test = e[0]
            out.append(f"{secs * factor:.2f}s {phase}     {test}")
        else:
            out.append(line)
    return "\n".join(out) + "\n"


def test_telemetry_delta(tmp_path, capsys):
    """ISSUE 5 satellite: the budget guard also fails when the
    telemetry-on suite adds >max-delta-pct over the BIGDL_OBS=off
    baseline durations."""
    m = _load()
    on, off = tmp_path / "on.log", tmp_path / "off.log"
    off.write_text(_SYNTHETIC)
    # +1% — within the 2% default limit
    on.write_text(_scaled_log(1.01))
    assert m.main(["--log", str(on), "--baseline-log", str(off),
                   "--budget", "500"]) == 0
    # +5% — over the limit (runtime budget itself still fine)
    on.write_text(_scaled_log(1.05))
    assert m.main(["--log", str(on), "--baseline-log", str(off),
                   "--budget", "500"]) == 1
    out = capsys.readouterr().out
    assert "OVER LIMIT" in out
    # a tighter explicit limit flips the verdict the other way too
    on.write_text(_scaled_log(1.01))
    assert m.main(["--log", str(on), "--baseline-log", str(off),
                   "--budget", "500", "--max-delta-pct", "0.5"]) == 1
    # unreadable/empty baseline is a usage error, not a pass
    assert m.main(["--log", str(on), "--baseline-log",
                   str(tmp_path / "missing.log"),
                   "--budget", "500"]) == 2
    off.write_text("nothing recorded\n")
    assert m.main(["--log", str(on), "--baseline-log", str(off),
                   "--budget", "500"]) == 2
    # pure function: delta math
    a = m.parse_durations(_SYNTHETIC)
    assert m.telemetry_delta_pct(a, a) == 0.0
