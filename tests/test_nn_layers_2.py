"""Layer-catalog tranche 2: volumetric conv/pool, upsampling, extended
activations, misc utility layers, similarity layers, margin criterions —
torch-CPU as numeric oracle (reference: the corresponding nn/*Spec.scala
files, SURVEY.md §4)."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from bigdl_tpu import nn

KEY = jax.random.PRNGKey(0)


def sv(m):
    return m.init(KEY)


class TestVolumetric:
    def test_conv3d_vs_torch(self):
        m = nn.VolumetricConvolution(3, 5, 2, 3, 3, 2, 1, 1, 0, 1, 1)
        v = sv(m)
        x = np.random.RandomState(0).randn(2, 6, 7, 8, 3).astype(np.float32)
        y, _ = m.apply(v, jnp.asarray(x))
        w = np.asarray(v["params"]["weight"])  # (T,H,W,I,O)
        conv = torch.nn.Conv3d(3, 5, (2, 3, 3), stride=(2, 1, 1),
                               padding=(0, 1, 1))
        conv.weight.data = torch.tensor(w.transpose(4, 3, 0, 1, 2))
        conv.bias.data = torch.tensor(np.asarray(v["params"]["bias"]))
        # torch: NCDHW
        ref = conv(torch.tensor(x.transpose(0, 4, 1, 2, 3)))
        ref = ref.detach().numpy().transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=2e-4)

    def test_maxpool3d_vs_torch(self):
        m = nn.VolumetricMaxPooling(2, 2, 2)
        x = np.random.RandomState(1).randn(1, 4, 6, 6, 2).astype(np.float32)
        y, _ = m.apply({"params": {}, "state": {}}, jnp.asarray(x))
        ref = torch.nn.functional.max_pool3d(
            torch.tensor(x.transpose(0, 4, 1, 2, 3)), 2)
        ref = ref.numpy().transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-6)

    def test_avgpool3d(self):
        m = nn.VolumetricAveragePooling(2, 2, 2)
        x = np.random.RandomState(2).randn(1, 4, 4, 4, 3).astype(np.float32)
        y, _ = m.apply({"params": {}, "state": {}}, jnp.asarray(x))
        ref = torch.nn.functional.avg_pool3d(
            torch.tensor(x.transpose(0, 4, 1, 2, 3)), 2)
        ref = ref.numpy().transpose(0, 2, 3, 4, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-6)


class TestUpsampling:
    def test_nearest_vs_torch(self):
        m = nn.SpatialUpSamplingNearest(3)
        x = np.random.RandomState(0).randn(2, 4, 5, 3).astype(np.float32)
        y, _ = m.apply({"params": {}, "state": {}}, jnp.asarray(x))
        ref = torch.nn.functional.interpolate(
            torch.tensor(x.transpose(0, 3, 1, 2)), scale_factor=3,
            mode="nearest")
        ref = ref.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-6)

    @pytest.mark.parametrize("align", [True, False])
    def test_bilinear_vs_torch(self, align):
        m = nn.SpatialUpSamplingBilinear(2, align_corners=align)
        x = np.random.RandomState(1).randn(1, 5, 4, 2).astype(np.float32)
        y, _ = m.apply({"params": {}, "state": {}}, jnp.asarray(x))
        ref = torch.nn.functional.interpolate(
            torch.tensor(x.transpose(0, 3, 1, 2)), scale_factor=2,
            mode="bilinear", align_corners=align)
        ref = ref.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)


class TestActivations2:
    def _x(self):
        return np.random.RandomState(0).randn(3, 7).astype(np.float32) * 3

    def test_hard_sigmoid_vs_torch(self):
        x = self._x()
        y, _ = nn.HardSigmoid().apply({"params": {}, "state": {}},
                                      jnp.asarray(x))
        # torch hardsigmoid uses slope 1/6; reference BigDL uses 0.2 (keras)
        ref = np.clip(0.2 * x + 0.5, 0, 1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-6)

    def test_swish_vs_torch(self):
        x = self._x()
        y, _ = nn.Swish().apply({"params": {}, "state": {}}, jnp.asarray(x))
        ref = torch.nn.functional.silu(torch.tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-6)

    def test_mish_vs_torch(self):
        x = self._x()
        y, _ = nn.Mish().apply({"params": {}, "state": {}}, jnp.asarray(x))
        ref = torch.nn.functional.mish(torch.tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)

    def test_rrelu_eval_matches_torch(self):
        x = self._x()
        m = nn.RReLU()
        y, _ = m.apply({"params": {}, "state": {}}, jnp.asarray(x),
                       training=False)
        ref = torch.nn.functional.rrelu(torch.tensor(x),
                                        training=False).numpy()
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-6)

    def test_rrelu_training_needs_rng(self):
        m = nn.RReLU()
        with pytest.raises(ValueError):
            m.apply({"params": {}, "state": {}}, jnp.ones((2, 2)),
                    training=True)
        y, _ = m.apply({"params": {}, "state": {}}, -jnp.ones((64,)),
                       training=True, rng=KEY)
        vals = -np.asarray(y)
        assert (vals >= 1 / 8 - 1e-6).all() and (vals <= 1 / 3 + 1e-6).all()
        assert np.unique(np.round(vals, 6)).size > 1  # actually random

    def test_srelu_identity_inside_thresholds(self):
        m = nn.SReLU((5,))
        v = sv(m)
        x = jnp.asarray(np.linspace(0.1, 0.9, 5), jnp.float32)[None]
        y, _ = m.apply(v, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-6)
        # outside: kinked
        x2 = jnp.asarray([[-1.0, 2.0, 0.5, 3.0, -2.0]], jnp.float32)
        y2, _ = m.apply(v, x2)
        np.testing.assert_allclose(
            np.asarray(y2)[0, [0, 4]], [-0.2, -0.4], atol=1e-6)


class TestMiscLayers:
    def test_add_mul_constant(self):
        x = jnp.ones((2, 3))
        y, _ = nn.AddConstant(2.5).apply({"params": {}, "state": {}}, x)
        np.testing.assert_allclose(np.asarray(y), 3.5)
        y, _ = nn.MulConstant(-2.0).apply({"params": {}, "state": {}}, x)
        np.testing.assert_allclose(np.asarray(y), -2.0)

    def test_replicate(self):
        x = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
        y, _ = nn.Replicate(4, dim=2).apply({"params": {}, "state": {}}, x)
        assert y.shape == (2, 4, 3)
        np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(x))
        np.testing.assert_allclose(np.asarray(y[:, 3]), np.asarray(x))

    def test_masking(self):
        x = jnp.asarray([[[1.0, 2.0], [0.0, 0.0], [0.0, 3.0]]])
        y, _ = nn.Masking(0.0).apply({"params": {}, "state": {}}, x)
        np.testing.assert_allclose(np.asarray(y[0, 1]), [0.0, 0.0])
        np.testing.assert_allclose(np.asarray(y[0, 2]), [0.0, 3.0])

    def test_gradient_reversal(self):
        m = nn.GradientReversal(2.0)

        def f(x):
            y, _ = m.apply({"params": {}, "state": {}}, x)
            return jnp.sum(y ** 2)

        x = jnp.asarray([1.0, -2.0])
        g = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(g), [-4.0, 8.0], atol=1e-6)
        y, _ = m.apply({"params": {}, "state": {}}, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))


class TestSimilarity:
    def test_cosine_rows_are_cosines(self):
        m = nn.Cosine(6, 4)
        v = sv(m)
        x = np.random.RandomState(0).randn(3, 6).astype(np.float32)
        y, _ = m.apply(v, jnp.asarray(x))
        w = np.asarray(v["params"]["weight"])
        ref = (x / np.linalg.norm(x, axis=1, keepdims=True)) @ \
            (w / np.linalg.norm(w, axis=1, keepdims=True)).T
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)

    def test_euclidean_distances(self):
        m = nn.Euclidean(5, 3)
        v = sv(m)
        x = np.random.RandomState(1).randn(2, 5).astype(np.float32)
        y, _ = m.apply(v, jnp.asarray(x))
        w = np.asarray(v["params"]["weight"])  # (in, out)
        ref = np.stack([np.linalg.norm(x - w[:, j], axis=1)
                        for j in range(3)], axis=1)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)


class TestCriterions2:
    def test_multi_margin_vs_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 6).astype(np.float32)
        t = rng.randint(0, 6, 4)
        for p in (1, 2):
            c = nn.MultiMarginCriterion(p=p)
            got = float(c(jnp.asarray(x), jnp.asarray(t)))
            ref = torch.nn.functional.multi_margin_loss(
                torch.tensor(x), torch.tensor(t), p=p).item()
            assert abs(got - ref) < 1e-5

    def test_margin_ranking_vs_torch(self):
        rng = np.random.RandomState(1)
        x1 = rng.randn(8).astype(np.float32)
        x2 = rng.randn(8).astype(np.float32)
        y = rng.choice([-1.0, 1.0], 8).astype(np.float32)
        c = nn.MarginRankingCriterion(margin=0.5)
        got = float(c((jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y)))
        ref = torch.nn.functional.margin_ranking_loss(
            torch.tensor(x1), torch.tensor(x2), torch.tensor(y),
            margin=0.5).item()
        assert abs(got - ref) < 1e-6

    def test_cosine_proximity(self):
        rng = np.random.RandomState(2)
        x = rng.randn(4, 5).astype(np.float32)
        c = nn.CosineProximityCriterion()
        got = float(c(jnp.asarray(x), jnp.asarray(x)))
        assert abs(got + 1.0) < 1e-5  # identical vectors → -1


class TestGradsFlow:
    @pytest.mark.parametrize("builder", [
        lambda: nn.VolumetricConvolution(2, 3, 2, 2, 2),
        lambda: nn.Cosine(4, 2),
        lambda: nn.Euclidean(4, 2),
        lambda: nn.SReLU((4,)),
    ])
    def test_param_grads_nonzero(self, builder):
        m = builder()
        v = m.init(KEY)
        shape = {"VolumetricConvolution": (1, 3, 4, 4, 2)}.get(
            type(m).__name__, (2, 4))
        x = jnp.asarray(np.random.RandomState(0).randn(*shape),
                        jnp.float32)

        def loss(p):
            y, _ = m.apply({"params": p, "state": {}}, x)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(v["params"])
        total = sum(float(jnp.abs(l).sum())
                    for l in jax.tree_util.tree_leaves(g))
        assert total > 0
