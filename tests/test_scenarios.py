"""Scenario compiler (ISSUE 20): declarative workload shapes compile
to the loadgen trace format deterministically — one seeded stream,
validated specs, phases/chaos provenance, and the append-only chaos
contract (adding a flood never perturbs the base traffic's draws)."""

import json

import pytest

from bigdl_tpu.serving.scenarios import (BUILTIN_SCENARIOS,
                                         compile_scenario,
                                         list_scenarios,
                                         load_scenario)


def _arrival_key(a):
    return (a.t, json.dumps(a.spec, sort_keys=True), a.session, a.turn)


# ------------------------------------------------------------- builtins

def test_builtins_compile_to_wellformed_traces():
    """Every built-in compiles (scaled down — the acceptance scenario
    is 1e5 requests) to a sorted, fully-typed trace with phases and
    chaos timelines the replay loop can fire in order."""
    assert list_scenarios() == sorted(BUILTIN_SCENARIOS)
    for name in list_scenarios():
        trace = compile_scenario(name, scale=0.01)
        assert trace["name"] == name
        ts = [a.t for a in trace["arrivals"]]
        assert ts == sorted(ts) and len(ts) > 0
        for a in trace["arrivals"]:
            assert isinstance(a.spec["prompt"], list)
            assert a.spec["max_new_tokens"] >= 1
            assert 0 <= a.spec["seed"] < 2 ** 31
        pts = [p["t"] for p in trace["phases"]]
        assert pts == sorted(pts) and len(pts) >= 1
        cts = [c["t"] for c in trace["chaos"]]
        assert cts == sorted(cts)
        declared = {t["name"] for t in trace["tenants"]}
        for a in trace["arrivals"]:
            if "tenant" in a.spec:
                assert a.spec["tenant"] in declared


def test_compile_is_deterministic():
    """Two compiles of one spec are identical lists — the seeded
    single-stream contract the 1e5-request byte-identity rides."""
    for name in ("chaos_smoke", "flash_crowd", "agentic_sessions"):
        t1 = compile_scenario(name, scale=0.5)
        t2 = compile_scenario(name, scale=0.5)
        assert [_arrival_key(a) for a in t1["arrivals"]] \
            == [_arrival_key(a) for a in t2["arrivals"]]
        assert t1["phases"] == t2["phases"]
        assert t1["chaos"] == t2["chaos"]
        assert t1["sessions"] == t2["sessions"]


def test_scale_shrinks_every_shape_and_flood():
    full = compile_scenario("chaos_smoke")
    tiny = compile_scenario("chaos_smoke", scale=0.25)
    assert len(tiny["arrivals"]) < len(full["arrivals"])
    # 96-request steady + 48-request flood at quarter scale
    assert len(tiny["arrivals"]) == 24 + 12
    with pytest.raises(ValueError, match="scale"):
        compile_scenario("chaos_smoke", scale=0.0)


def test_flood_appends_without_perturbing_base_draws():
    """Chaos floods draw AFTER the shapes: the base traffic of a
    spec-with-flood is the spec-without-flood's traffic verbatim, so
    A/B-ing a chaos schedule changes only the injected arrivals."""
    spec = load_scenario("chaos_smoke")
    base_spec = json.loads(json.dumps(spec))
    base_spec["chaos"] = [c for c in base_spec["chaos"]
                          if c["action"] != "tenant_flood"]
    with_flood = compile_scenario(spec)
    without = compile_scenario(base_spec)
    base_keys = [_arrival_key(a) for a in without["arrivals"]]
    flood_keys = [_arrival_key(a) for a in with_flood["arrivals"]]
    assert len(flood_keys) == len(base_keys) + 48
    for k in base_keys:                  # every base arrival survives
        assert k in flood_keys
    extra = list(flood_keys)
    for k in base_keys:
        extra.remove(k)
    assert all(json.loads(k[1])["tenant"] == "tenant1" for k in extra)


def test_sessions_shape_builds_continuations():
    trace = compile_scenario("agentic_sessions", scale=0.5)
    sess = trace["sessions"]
    assert sess["count"] >= 1 and sess["turns"] >= 2
    assert set(sess["continuations"]) == set(range(sess["count"]))
    for blocks in sess["continuations"].values():
        assert len(blocks) == sess["turns"] - 1
    heads = [a for a in trace["arrivals"] if a.session is not None]
    assert len(heads) == sess["count"]
    assert all(a.turn == 0 for a in heads)


def test_diurnal_phases_partition_the_day():
    trace = compile_scenario("diurnal_noisy", scale=0.01)
    labels = [p["name"] for p in trace["phases"]]
    assert labels == ["diurnal:trough", "diurnal:ramp",
                      "diurnal:peak", "diurnal:decay"]
    n_flood = next(c for c in trace["chaos"]
                   if c["action"] == "tenant_flood")
    # phase counts partition the diurnal arrivals (floods excluded)
    assert sum(p["arrivals"] for p in trace["phases"]) \
        == len(trace["arrivals"]) - 20      # 2000-request flood @1%
    assert n_flood["target"] == "tenant1"
    # the curve maximum sits at the ramp/peak boundary: the middle
    # half of the day must far outweigh the trough/decay quarters
    by_name = {p["name"]: p["arrivals"] for p in trace["phases"]}
    assert by_name["diurnal:ramp"] + by_name["diurnal:peak"] \
        > 2 * (by_name["diurnal:trough"] + by_name["diurnal:decay"])


# ----------------------------------------------------------- validation

def test_spec_validation():
    with pytest.raises(ValueError, match="unknown scenario"):
        load_scenario("nope_not_a_scenario")
    with pytest.raises(ValueError, match="shapes"):
        compile_scenario({"seed": 0})
    with pytest.raises(ValueError, match="shape kind"):
        compile_scenario({"shapes": [{"kind": "sawtooth", "n": 4}]})
    with pytest.raises(ValueError, match="undeclared"):
        compile_scenario({"shapes": [
            {"kind": "steady", "n": 4, "rate": 1.0,
             "tenant_mix": {"ghost": 1.0}}]})
    with pytest.raises(ValueError, match="chaos action"):
        compile_scenario({"shapes": [{"kind": "steady", "n": 4}],
                          "chaos": [{"t": 1.0, "action": "meteor"}]})
    with pytest.raises(ValueError, match="tenant_flood"):
        compile_scenario({"shapes": [{"kind": "steady", "n": 4}],
                          "chaos": [{"t": 1.0,
                                     "action": "tenant_flood"}]})
    with pytest.raises(ValueError, match="regions"):
        compile_scenario({"shapes": [{"kind": "regional_wave"}]})
    with pytest.raises(ValueError, match="target"):
        compile_scenario({"shapes": [{"kind": "steady", "n": 4}],
                          "chaos": [{"t": 1.0,
                                     "action": "watchdog_trip"}]})
    with pytest.raises(ValueError, match="one sessions shape"):
        compile_scenario({"shapes": [
            {"kind": "sessions", "count": 2},
            {"kind": "sessions", "count": 2}]})
    with pytest.raises(ValueError, match="name"):
        compile_scenario({"tenants": [{"weight": 1.0}],
                          "shapes": [{"kind": "steady", "n": 4}]})


def test_json_spec_roundtrip(tmp_path):
    """A spec file compiles exactly like its dict — the
    `loadgen.py --scenario path.json` input path."""
    spec = load_scenario("chaos_smoke")
    p = tmp_path / "scenario.json"
    p.write_text(json.dumps(spec))
    t1 = compile_scenario(str(p))
    t2 = compile_scenario(spec)
    assert [_arrival_key(a) for a in t1["arrivals"]] \
        == [_arrival_key(a) for a in t2["arrivals"]]
    assert t1["chaos"] == t2["chaos"]
