"""Scaling-harness plumbing CI (VERDICT r3 item 4): the pod-scaling
script must run end-to-end on the virtual mesh so pod time, when it
exists, is spent measuring rather than debugging."""

import importlib.util
import os

import jax


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "scaling_bench.py")
    spec = importlib.util.spec_from_file_location("scaling_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_measure_mesh_contract():
    sb = _load()
    assert jax.device_count() >= 8
    for n in (1, 8):
        row = sb.measure_mesh(n, "mlp", per_chip_batch=8, iters=1,
                              ici_gbps=400.0)
        assert row["devices"] == n
        assert row["global_batch"] == 8 * n
        assert row["step_ms"] > 0
        assert row["collective_ms"] > 0
        assert row["wire_mb"] > 0
        if n == 1:
            assert row["ici_ring_bound_ms"] == 0.0
        else:
            assert row["ici_ring_bound_ms"] > 0


def test_measure_zero2_contract(tmp_path):
    """ISSUE 9: the zero2 row runs the sharded step + REAL async
    sharded checkpoint path end-to-end and reports the
    checkpoint-overlap provenance fields (the 5% acceptance is judged
    on a quiet host from the CLI run, not asserted under CI jitter)."""
    sb = _load()
    row = sb.measure_zero2(8, "mlp", per_chip_batch=8, iters=2,
                           ckpt_every=1, windows=1,
                           workdir=str(tmp_path))
    assert row["devices"] == 8 and row["zero"] == 2
    ov = row["ckpt_overlap"]
    for k in ("nosave_step_ms", "async_step_ms", "sync_step_ms",
              "async_overhead_frac", "sync_overhead_frac",
              "async_within_5pct"):
        assert k in ov
    assert ov["async_step_ms"] > 0 and ov["sync_step_ms"] > 0
    assert row["provenance"]["sharded_ckpt"] is True
    # the async window really published manifest-last sharded dirs
    import os
    pub = [d for d in os.listdir(os.path.join(str(tmp_path), "async0"))
           if d.startswith("checkpoint-")]
    assert pub and all(os.path.exists(os.path.join(
        str(tmp_path), "async0", d, "MANIFEST.json")) for d in pub)
