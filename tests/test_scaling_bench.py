"""Scaling-harness plumbing CI (VERDICT r3 item 4): the pod-scaling
script must run end-to-end on the virtual mesh so pod time, when it
exists, is spent measuring rather than debugging."""

import importlib.util
import os

import jax


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "scaling_bench.py")
    spec = importlib.util.spec_from_file_location("scaling_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_measure_mesh_contract():
    sb = _load()
    assert jax.device_count() >= 8
    for n in (1, 8):
        row = sb.measure_mesh(n, "mlp", per_chip_batch=8, iters=1,
                              ici_gbps=400.0)
        assert row["devices"] == n
        assert row["global_batch"] == 8 * n
        assert row["step_ms"] > 0
        assert row["collective_ms"] > 0
        assert row["wire_mb"] > 0
        if n == 1:
            assert row["ici_ring_bound_ms"] == 0.0
        else:
            assert row["ici_ring_bound_ms"] > 0
