"""One-launch Pallas paged-decode kernel (ISSUE 17): interpret-mode
parity against the `ops/kv_cache.paged_attention` oracle — fp32
BITWISE (the load-bearing contract: the kernel must be a drop-in under
every bitwise pin built on the full-extent reduction discipline), bf16
to tolerance — across block-table shapes (ragged last blocks, shuffled
chains, reserved scratch block 0, single-cell and engine-like
launches), the tile-divisibility fail-fast, the env-knob snapshot
round-trip, and the engine-level wiring (attn_impl="interpret" engine
bitwise == the xla engine, sharing its prefill executable)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.ops.kv_cache import paged_attention
from bigdl_tpu.ops.paged_decode import paged_decode_attention, resolve_tiles
from bigdl_tpu.utils import envknobs


def _case(b, h, nb, bs, d, dtype=jnp.float32, seed=0, pos=None,
          poison=False):
    """A pool + shuffled disjoint block chains + ragged row clocks.
    Block 0 is reserved scratch and never appears in the table (the
    engine contract); poison=True fills it with NaN to prove the
    kernel never reads it and masked keys launder correctly."""
    rng = np.random.RandomState(seed)
    pool_n = b * nb + 1
    k_pool = rng.randn(pool_n, h, bs, d).astype(np.float32)
    v_pool = rng.randn(pool_n, h, bs, d).astype(np.float32)
    if poison:
        k_pool[0] = np.nan
        v_pool[0] = np.nan
    ids = rng.permutation(np.arange(1, pool_n))[:b * nb]
    table = jnp.asarray(ids.reshape(b, nb), jnp.int32)
    if pos is None:
        pos = rng.randint(0, nb * bs, size=b)
    pos = jnp.asarray(pos, jnp.int32)
    q = jnp.asarray(rng.randn(b, h, 1, d), dtype)
    return (q, jnp.asarray(k_pool, dtype), jnp.asarray(v_pool, dtype),
            table, pos)


CONFIGS = [
    # (b, h, nb, bs, d, block_tile, head_tile)
    (1, 1, 1, 4, 8, 1, 1),       # single cell
    (2, 2, 4, 4, 8, 1, 1),
    (3, 4, 4, 4, 16, 1, 1),      # odd batch
    (1, 4, 4, 4, 8, 1, 2),
    (2, 2, 4, 4, 8, 2, 1),       # multi-block tiles
    (2, 2, 4, 4, 8, 4, 2),       # full-table tile
    (4, 8, 8, 16, 64, 8, 4),     # engine-like 43M shape
    (2, 1, 4, 4, 8, 1, 1),       # H=1, B>1 (dup-batch edge)
]


class TestInterpretParity:
    @pytest.mark.parametrize("b,h,nb,bs,d,bt,ht", CONFIGS)
    def test_fp32_bitwise(self, b, h, nb, bs, d, bt, ht):
        args = _case(b, h, nb, bs, d)
        ref = paged_attention(*args)
        out = paged_decode_attention(*args, impl="interpret",
                                     block_tile=bt, head_tile=ht)
        assert out.dtype == ref.dtype
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_fp32_bitwise_ragged_clocks(self):
        # clocks mid-block, at a block boundary, and at 0: the
        # valid-extent masking must agree with the oracle exactly
        args = _case(4, 2, 4, 4, 8, pos=[0, 3, 4, 15])
        ref = paged_attention(*args)
        out = paged_decode_attention(*args, impl="interpret")
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_poisoned_scratch_block_never_read(self):
        # block 0 (reserved scratch) and every masked key row are NaN
        # in spirit: output must stay finite and bitwise the oracle's
        args = _case(2, 2, 4, 4, 8, poison=True, pos=[5, 9])
        ref = paged_attention(*args)
        out = paged_decode_attention(*args, impl="interpret")
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_bf16_tolerance(self):
        # bf16 pools: both paths cast to fp32 at the same point (VMEM
        # load here, post-gather there), so values match — pinned to
        # tolerance, not bits (module docstring)
        args = _case(2, 4, 4, 4, 16, dtype=jnp.bfloat16)
        ref = paged_attention(*args)
        out = paged_decode_attention(*args, impl="interpret")
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=2e-2, rtol=2e-2)

    def test_custom_sm_scale(self):
        args = _case(2, 2, 4, 4, 8)
        ref = paged_attention(*args, 0.25)
        out = paged_decode_attention(*args, 0.25, impl="interpret")
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_under_jit(self):
        args = _case(2, 2, 4, 4, 8)
        ref = paged_attention(*args)
        out = jax.jit(lambda *a: paged_decode_attention(
            *a, impl="interpret"))(*args)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestDispatchAndTiles:
    def test_xla_impl_is_the_oracle(self):
        args = _case(2, 2, 4, 4, 8)
        np.testing.assert_array_equal(
            np.asarray(paged_decode_attention(*args, impl="xla")),
            np.asarray(paged_attention(*args)))

    def test_rejects_multi_row_q(self):
        q, kp, vp, tbl, pos = _case(2, 2, 4, 4, 8)
        q2 = jnp.concatenate([q, q], axis=2)
        with pytest.raises(ValueError, match="one row"):
            paged_decode_attention(q2, kp, vp, tbl, pos,
                                   impl="interpret")

    def test_rejects_unknown_impl(self):
        args = _case(1, 1, 1, 4, 8)
        with pytest.raises(ValueError, match="impl"):
            paged_decode_attention(*args, impl="mosaic")

    def test_tile_divisibility_fail_fast(self):
        with pytest.raises(ValueError, match="block_tile"):
            resolve_tiles(4, 2, block_tile=3)
        with pytest.raises(ValueError, match="head_tile"):
            resolve_tiles(4, 2, head_tile=4)
        with pytest.raises(ValueError, match="block_tile"):
            resolve_tiles(4, 2, block_tile=0)
        assert resolve_tiles(4, 2) == (1, 1)
        assert resolve_tiles(8, 4, block_tile=2, head_tile=4) == (2, 4)

    def test_env_knob_snapshot(self):
        # BIGDL_PAGED_DECODE_TILES is an import snapshot: mutate env +
        # refresh() (the sweep discipline), explicit args still win
        old = os.environ.get("BIGDL_PAGED_DECODE_TILES")
        os.environ["BIGDL_PAGED_DECODE_TILES"] = "2x2"
        try:
            envknobs.refresh()
            assert envknobs.PAGED_DECODE_TILES == (2, 2)
            assert resolve_tiles(4, 2) == (2, 2)
            assert resolve_tiles(4, 2, block_tile=4, head_tile=1) \
                == (4, 1)
            args = _case(2, 2, 4, 4, 8)
            np.testing.assert_array_equal(
                np.asarray(paged_decode_attention(*args,
                                                  impl="interpret")),
                np.asarray(paged_attention(*args)))
        finally:
            if old is None:
                os.environ.pop("BIGDL_PAGED_DECODE_TILES", None)
            else:
                os.environ["BIGDL_PAGED_DECODE_TILES"] = old
            envknobs.refresh()
        assert envknobs.PAGED_DECODE_TILES is None


class TestEngineWiring:
    def test_interpret_engine_bitwise_and_shares_prefill(self):
        from bigdl_tpu.models.transformer import build_lm
        from bigdl_tpu.serving import InferenceEngine, Request
        from bigdl_tpu.serving.engine import _TRACES

        model = build_lm(vocab_size=61, dim=32, num_heads=2,
                         num_layers=2, max_len=32)
        variables = model.init(jax.random.PRNGKey(0))

        def run(attn_impl):
            eng = InferenceEngine(model, variables, slots=2, max_len=32,
                                  prefill_buckets=(8,), block_size=4,
                                  attn_impl=attn_impl)
            res = eng.run([Request(id=i, prompt=[3 + i, 7, 11 + i],
                                   max_new_tokens=5) for i in range(3)])
            return eng, {r.id: r.tokens for r in res}

        _, toks_xla = run("xla")
        before = dict(_TRACES)
        eng, toks_int = run("interpret")
        # the kernel path is decode-only: one NEW decode executable
        # for the new static attn_impl, ZERO new prefill compiles
        assert _TRACES["prefill"] == before["prefill"]
        assert _TRACES["decode"] == before["decode"] + 1
        assert toks_int == toks_xla  # fp32 kernel == oracle, bitwise
        assert eng.health()["attn_impl"] == "interpret"
        # second interpret engine over the same model: zero new traces
        before2 = dict(_TRACES)
        _, toks_int2 = run("interpret")
        assert dict(_TRACES) == before2
        assert toks_int2 == toks_xla
