"""ZeRO-2 sharded update + async sharded checkpoints (ISSUE 9).

Parity contract: `all_gather` of the disjoint per-device weight shards
reconstructs the exact concatenation the ZeRO-1 step holds replicated,
so sharding the master fp32 residency must not change a single bit of
the update — pinned BITWISE here for both the plain step and the
grad-accum pair (the `zero2` dryrun leg in __graft_entry__.py asserts
the same invariant from the driver contract side).

Resume contract: train 2N steps uninterrupted == train N + kill +
fresh-process resume + N, bit-for-bit, through the sharded async
checkpoint (fast tier-1 sibling of scripts/fault_drill.py's
preempt_resume leg — which additionally drives the injected fault
plan and asserts from telemetry events).

Checkpoint format contract (serialization/checkpoint.py): per-shard
units + MANIFEST.json published LAST — a dir without a MANIFEST is
torn and never a `latest()` candidate; a damaged published shard
fails its crc32 and `load()` falls back newest-valid; a failed async
save surfaces at the next `save()`/`wait()`, never silently.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.parallel import (
    FlatParamSpec, make_dp_accum_steps, make_dp_train_step, make_mesh,
)
from bigdl_tpu.serialization.checkpoint import (
    Checkpoint, CheckpointCorruptError, shard_unit_name,
)
from bigdl_tpu.utils import faults

KEY = jax.random.PRNGKey(0)


@pytest.fixture(scope="module")
def mesh8():
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    return make_mesh({"data": 8})


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


def _setup(mesh, grad_dtype):
    from jax.sharding import NamedSharding, PartitionSpec as P

    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
    model.build(KEY)
    crit = nn.CrossEntropyCriterion()
    from bigdl_tpu.optim import SGD

    method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0)
    spec = FlatParamSpec(model.variables["params"], 8)
    bx = jax.random.normal(jax.random.PRNGKey(1), (32, 6))
    by = jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 4)

    def inputs(w_spec):
        flat_w = jax.device_put(
            spec.flatten(model.variables["params"]),
            NamedSharding(mesh, w_spec))
        slots = jax.tree_util.tree_map(
            lambda s: jax.device_put(s, NamedSharding(mesh, P("data"))),
            method.init_slots(jnp.zeros((spec.padded,), jnp.float32)))
        return flat_w, slots

    return model, crit, method, spec, bx, by, inputs


class TestZero2StepParity:
    @pytest.mark.parametrize("grad_dtype", [None, "bfloat16"])
    def test_step_bitwise_vs_zero1(self, mesh8, grad_dtype):
        """The ZeRO-2 step's updated params and loss are bit-identical
        to the ZeRO-1 step's on the same inputs — fp32 master path and
        bf16-gradient-wire path both."""
        from jax.sharding import PartitionSpec as P

        model, crit, method, spec, bx, by, inputs = _setup(mesh8,
                                                           grad_dtype)
        args = (model.variables["state"], bx, by,
                jnp.asarray(0.1, jnp.float32), jnp.asarray(0, jnp.int32),
                KEY)

        step1 = make_dp_train_step(model, crit, method, mesh8, spec,
                                   grad_dtype=grad_dtype)
        w, s = inputs(P())
        ref_w, ref_slots, _, ref_loss = step1(w, s, *args)

        step2 = make_dp_train_step(model, crit, method, mesh8, spec,
                                   grad_dtype=grad_dtype, zero=2)
        w2, s2 = inputs(P("data"))
        assert all(sh.data.shape == (spec.shard_size,)
                   for sh in w2.addressable_shards)
        new_w, new_slots, _, loss = step2(w2, s2, *args)
        # output stays sharded: ZeRO-2 persists 1/n residency
        assert all(sh.data.shape == (spec.shard_size,)
                   for sh in new_w.addressable_shards)

        np.testing.assert_array_equal(np.asarray(loss),
                                      np.asarray(ref_loss))
        np.testing.assert_array_equal(np.asarray(new_w),
                                      np.asarray(ref_w))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            new_slots, ref_slots)

    def test_accum_pair_bitwise_vs_zero1(self, mesh8):
        """Two micro-steps + apply under zero=2 match zero=1 bitwise —
        the accumulator path all_gathers the sharded weights the same
        way the plain step does."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        model, crit, method, spec, bx, by, inputs = _setup(mesh8, None)
        mod_state = model.variables["state"]

        def run(zero):
            micro_fn, apply_fn = make_dp_accum_steps(
                model, crit, method, mesh8, spec, grad_dtype=None,
                zero=zero)
            w, s = inputs(P("data") if zero == 2 else P())
            g_acc = jax.device_put(jnp.zeros((spec.padded,), jnp.float32),
                                   NamedSharding(mesh8, P("data")))
            state = mod_state
            for i in range(2):
                g_acc, state, _ = micro_fn(w, g_acc, state, bx, by,
                                           jax.random.fold_in(KEY, i))
            w, s, g_acc = apply_fn(w, s, g_acc, jnp.asarray(0.1),
                                   jnp.asarray(0), jnp.asarray(2.0))
            return np.asarray(w), s

        ref_w, ref_s = run(1)
        got_w, got_s = run(2)
        np.testing.assert_array_equal(got_w, ref_w)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            got_s, ref_s)

    def test_zero_knob_validation(self, mesh8):
        model, crit, method, spec, *_ = _setup(mesh8, None)
        with pytest.raises(ValueError, match="zero must be 1 or 2"):
            make_dp_train_step(model, crit, method, mesh8, spec, zero=3)
        with pytest.raises(ValueError, match="zero must be 1 or 2"):
            make_dp_accum_steps(model, crit, method, mesh8, spec, zero=0)
        with pytest.raises(ValueError, match="zero must be 1 or 2"):
            Optimizer(nn.Linear(2, 2).build(KEY), DataSet.array(
                [Sample(np.zeros(2, np.float32), 0)]),
                nn.ClassNLLCriterion(), batch_size=1).set_mesh(
                    mesh8, zero=3)


# ---------------------------------------------------------------- e2e

def _train(workdir, end_iter, *, ckpt_iter=None, resume=False,
           tag="run", zero=2, sharded=True, async_save=True):
    """Tiny ZeRO-2 mesh run with sharded async checkpoints; returns
    the trained flat parameter vector (same dataset/model/seeds every
    call — runs differ only in interruption/resume)."""
    rng = np.random.RandomState(11)
    samples = [Sample(rng.rand(6).astype(np.float32),
                      int(rng.randint(0, 4))) for _ in range(64)]
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4),
                          nn.LogSoftMax()).build(jax.random.PRNGKey(3))
    opt = (Optimizer(model, DataSet.array(samples),
                     nn.ClassNLLCriterion(), batch_size=8)
           .set_optim_method(Adam(learningrate=1e-2))
           .set_end_when(Trigger.max_iteration(end_iter))
           .set_mesh(make_mesh({"data": 8}), zero=zero))
    if ckpt_iter is not None:
        opt.set_checkpoint(os.path.join(workdir, tag),
                           Trigger.several_iteration(ckpt_iter),
                           sharded=sharded, async_save=async_save)
    if resume:
        opt.resume_from_checkpoint()
    trained = opt.optimize()
    return np.concatenate([np.ravel(np.asarray(a, np.float32))
                           for _, a in trained.parameters()]), opt


class TestElasticResume:
    def test_resume_bit_identity(self, tmp_path):
        """train 2N uninterrupted == train N (sharded async ckpt at N)
        + fresh-process resume + N, bit-for-bit (acceptance criterion:
        resume-after-kill indistinguishable from never having died)."""
        ref, _ = _train(str(tmp_path), 8, tag="ref")
        _train(str(tmp_path), 4, ckpt_iter=4, tag="kill")
        got, opt = _train(str(tmp_path), 8, ckpt_iter=4, resume=True,
                          tag="kill")
        assert opt.checkpoint._last_loaded.endswith("checkpoint-4")
        np.testing.assert_array_equal(got, ref)

    def test_sharded_needs_mesh(self, tmp_path):
        """A local (mesh-less) run cannot WRITE sharded checkpoints —
        there is no ZeRO flat state to shard."""
        rng = np.random.RandomState(0)
        samples = [Sample(rng.rand(6).astype(np.float32), 0)
                   for _ in range(8)]
        opt = (Optimizer(nn.Sequential(nn.Linear(6, 4),
                                       nn.LogSoftMax()).build(KEY),
                         DataSet.array(samples), nn.ClassNLLCriterion(),
                         batch_size=8)
               .set_optim_method(Adam(learningrate=1e-2))
               .set_end_when(Trigger.max_iteration(2))
               .set_checkpoint(str(tmp_path / "c"),
                               Trigger.several_iteration(1),
                               sharded=True))
        with pytest.raises(ValueError, match="need a mesh"):
            opt.optimize()


# --------------------------------------------- sharded checkpoint format

def _toy_shards(nshards=4, shard_size=3):
    full = {"m": np.arange(nshards * shard_size, dtype=np.float32),
            "v": np.arange(nshards * shard_size, dtype=np.float32) * 2}
    shards = {i: {k: v[i * shard_size:(i + 1) * shard_size]
                  for k, v in full.items()} for i in range(nshards)}
    return full, shards


def _model_tree():
    return {"params": {"w": np.ones((2, 2), np.float32)}, "state": {}}


META = {"layout": "zero2_flat", "num_shards": 4, "total": 12,
        "padded": 12}


class TestShardedCheckpointFormat:
    def test_roundtrip_concatenates_shards(self, tmp_path):
        ck = Checkpoint(str(tmp_path))
        full, shards = _toy_shards()
        ck.save_sharded(3, _model_tree(), shards, nshards=4,
                        train_state={"neval": 3}, optim_meta=META)
        d = os.path.join(str(tmp_path), "checkpoint-3")
        assert os.path.exists(os.path.join(d, "MANIFEST.json"))
        assert os.path.exists(os.path.join(
            d, shard_unit_name(0, 4) + ".npz"))
        vars_, optim, ts, meta = ck.load(with_optim_meta=True)
        np.testing.assert_array_equal(np.asarray(optim["m"]), full["m"])
        np.testing.assert_array_equal(np.asarray(optim["v"]), full["v"])
        assert ts["neval"] == 3
        assert meta["layout"] == "zero2_flat" and meta["padded"] == 12

    def test_torn_dir_never_a_candidate(self, tmp_path):
        """A writer death mid-save strands only the .inprogress
        staging dir (never surfaced by latest()); load() uses the
        older complete checkpoint. A later clean re-save of the SAME
        step adopts the leftover staging and publishes fine."""
        ck = Checkpoint(str(tmp_path))
        full, shards = _toy_shards()
        ck.save_sharded(2, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        # torn save at step 4: sync dispatch raises mid-write
        faults.set_plan(faults.FaultPlan("ckpt_async_torn@4"))
        with pytest.raises(faults.FaultInjected):
            ck.save_sharded(4, _model_tree(), shards, nshards=4,
                            optim_meta=META)
        torn = os.path.join(str(tmp_path), "checkpoint-4")
        assert not os.path.isdir(torn), "torn save must never publish"
        assert os.path.isdir(torn + ".inprogress")
        assert ck.latest().endswith("checkpoint-2")
        # recovery re-saves step 4 over the stale staging
        faults.set_plan(None)
        ck.save_sharded(4, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        assert ck.latest().endswith("checkpoint-4")
        assert not os.path.isdir(torn + ".inprogress")

    def test_resave_crash_keeps_previous_same_step_checkpoint(
            self, tmp_path):
        """Re-saving an existing COMPLETE checkpoint-N must not
        destroy it before the replacement publishes: a writer death
        mid-re-save leaves the original intact and loadable."""
        ck = Checkpoint(str(tmp_path))
        full, shards = _toy_shards()
        ck.save_sharded(4, _model_tree(), shards, nshards=4,
                        train_state={"neval": 4}, optim_meta=META)
        faults.set_plan(faults.FaultPlan("ckpt_async_torn@4"))
        with pytest.raises(faults.FaultInjected):
            ck.save_sharded(4, _model_tree(), shards, nshards=4,
                            optim_meta=META)
        faults.set_plan(None)
        # the previous complete checkpoint-4 survived the torn re-save
        vars_, optim, ts = ck.load()
        assert ck._last_loaded.endswith("checkpoint-4")
        assert ts["neval"] == 4
        np.testing.assert_array_equal(np.asarray(optim["m"]), full["m"])

    def test_damaged_published_shard_falls_back(self, tmp_path):
        """Bit rot on one PUBLISHED shard: per-shard crc32 catches it,
        load() skips the dir (recording it) and falls back."""
        ck = Checkpoint(str(tmp_path))
        _, shards = _toy_shards()
        ck.save_sharded(2, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        ck.save_sharded(4, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        npz = os.path.join(str(tmp_path), "checkpoint-4",
                           shard_unit_name(2, 4) + ".npz")
        faults.corrupt_file(npz)
        vars_, optim, ts = ck.load()
        assert ck._last_loaded.endswith("checkpoint-2")
        assert any(d.endswith("checkpoint-4")
                   for d in ck.corrupt_skipped)
        # a fully-damaged history must still raise, not loop
        faults.corrupt_file(os.path.join(
            str(tmp_path), "checkpoint-2", shard_unit_name(1, 4) + ".npz"))
        ck2 = Checkpoint(str(tmp_path))
        with pytest.raises(CheckpointCorruptError):
            ck2.load()

    def test_damaged_manifest_falls_back(self, tmp_path):
        """A MANIFEST.json that still parses as JSON but lost its
        fields (partial overwrite) must fall back like an unreadable
        one — not escape load() as a KeyError."""
        import json as _json

        ck = Checkpoint(str(tmp_path))
        _, shards = _toy_shards()
        ck.save_sharded(2, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        ck.save_sharded(4, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        mpath = os.path.join(str(tmp_path), "checkpoint-4",
                             Checkpoint.MANIFEST)
        with open(mpath, "w") as f:
            _json.dump({"step": 4}, f)  # valid JSON, no nshards
        vars_, optim, ts = ck.load()
        assert ck._last_loaded.endswith("checkpoint-2")
        assert any(d.endswith("checkpoint-4")
                   for d in ck.corrupt_skipped)

    def test_async_error_surfaces_at_wait(self, tmp_path):
        """A writer death on the background thread surfaces at wait()
        (and at the next save), never silently."""
        ck = Checkpoint(str(tmp_path), async_save=True)
        _, shards = _toy_shards()
        faults.set_plan(faults.FaultPlan("ckpt_async_torn@4"))
        ck.save_sharded(4, _model_tree(), shards, nshards=4,
                        optim_meta=META)  # returns immediately
        with pytest.raises(faults.FaultInjected):
            ck.wait()
        # the error is consumed once; the saver is reusable after
        ck.save_sharded(6, _model_tree(), shards, nshards=4,
                        optim_meta=META)
        ck.wait()
        assert ck.latest().endswith("checkpoint-6")

    def test_async_full_format_roundtrip(self, tmp_path):
        """async_save also covers the unsharded format: the snapshot
        is taken synchronously, the write lands by wait()."""
        ck = Checkpoint(str(tmp_path), async_save=True)
        ck.save(5, _model_tree(), {"m": np.arange(4, dtype=np.float32)},
                train_state={"neval": 5})
        ck.wait()
        vars_, optim, ts = ck.load()
        assert ts["neval"] == 5
        np.testing.assert_array_equal(np.asarray(optim["m"]),
                                      np.arange(4, dtype=np.float32))
