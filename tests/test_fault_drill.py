"""Fault drill as tier-1 CI (ISSUE 1 satellite): every test run
exercises injected NaN-skip, step-exception retry, and
corrupt-checkpoint fallback on the CPU mesh — the recovery paths the
reference only ever exercised when a node actually died (SURVEY.md
§5.3). Drill legs live in scripts/fault_drill.py (also a standalone
driver); unit tests for the injection registry (utils/faults) and the
anomaly guard (utils/anomaly) ride along."""

import importlib.util
import os

import numpy as np
import pytest

from bigdl_tpu.utils import anomaly, faults


_DRILL = None


def _load_drill():
    # cached: serving legs share one tiny LM whose jitted steps must
    # compile once per process, not once per test (module reload would
    # rebuild the model object and retrace everything)
    global _DRILL
    if _DRILL is None:
        path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                            "fault_drill.py")
        spec = importlib.util.spec_from_file_location("fault_drill", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _DRILL = mod
    return _DRILL


@pytest.fixture(autouse=True)
def _clean_plan():
    """No injection plan may leak between tests (process-global)."""
    faults.set_plan(None)
    yield
    faults.set_plan(None)


# ------------------------------------------------------------ drill legs

@pytest.mark.parametrize("leg", ["nan_skip", "nan_skip_mesh", "rollback",
                                 "step_retry", "data_retry", "ckpt_torn",
                                 "ckpt_fallback"])
def test_drill_leg(tmp_path, leg):
    fd = _load_drill()
    result = fd.LEGS[leg](str(tmp_path))
    assert result["ok"], result


@pytest.mark.parametrize("leg", ["preempt_resume", "ckpt_async_torn",
                                 "torn_shard", "worldsize_resume"])
def test_elastic_drill_leg(tmp_path, leg):
    """ISSUE 9: the preemption-tolerant training plane drills — ZeRO-2
    sharded updates with async sharded checkpoints survive worker
    kills, torn background saves, shard bit-rot, and world-size
    changes, bit-deterministically, on every tier-1 pass."""
    fd = _load_drill()
    result = fd.LEGS[leg](str(tmp_path))
    assert result["ok"], result


@pytest.mark.parametrize("leg", ["serve_poison", "serve_overload",
                                 "serve_deadline", "serve_retry",
                                 "serve_watchdog", "serve_prefix",
                                 "serve_spill", "serve_spec",
                                 "spec_adapt",
                                 "fleet_failover",
                                 "fleet_affinity_failover", "fleet_drain",
                                 "fleet_autoscale",
                                 "fleet_tp_failover",
                                 "fleet_journey", "slo_alert",
                                 "tenant_noisy", "scenario_chaos"])
def test_serving_drill_leg(tmp_path, leg):
    """ISSUE 4 + ISSUE 7 + ISSUE 10 + ISSUE 11 + ISSUE 14: the
    serving-plane reliability drills (poisoned co-batch, overload
    shed, deadline expiry, retry-then-succeed, watchdog trip), the
    fleet drills (failover bit-identity — including across sharding
    layouts, drain, SLO autoscaling), the observability drill (request
    journeys across handoff/failover with byte-identical
    flight-recorder bundles), the live-SLO drill (burn-rate alert
    fires and resolves deterministically with a byte-identical
    slo_burn bundle) and the ISSUE 18 speculation-flywheel drill
    (planted accept collapse suspends speculation with tokens bitwise
    target-only; a distilled hot-swapped draft resumes it) and the
    ISSUE 19 noisy-neighbor drill (a co-resident flood is throttled by
    its own token bucket while the quiet tenant's tokens stay bitwise
    identical to a quiet-only run) and the ISSUE 20 scenario-chaos
    drill (a compiled chaos scenario — watchdog trip + tenant flood —
    replayed twice through the calibrated simulator with report AND
    flight-recorder bundle bytes identical) run bit-deterministically
    on every tier-1 pass.
    Legs must actually DRILL here: the CPU-mesh conftest gives them 8
    devices, so the device-count skip escape is asserted shut."""
    fd = _load_drill()
    result = fd.SERVING_LEGS[leg](str(tmp_path))
    assert result["ok"], result
    assert "skipped" not in result, result


# ------------------------------------------------------------- FaultPlan

def test_plan_parse_and_one_shot():
    plan = faults.FaultPlan("nan@4,step@7,ckpt_corrupt@6x2")
    assert plan
    assert plan.fires("nan", 4)
    assert not plan.fires("nan", 4), "one-shot by default"
    assert not plan.fires("nan", 5)
    assert plan.fires("ckpt_corrupt", 6) and plan.fires("ckpt_corrupt", 6)
    assert not plan.fires("ckpt_corrupt", 6), "xN budget exhausted"
    assert ("step", 7) not in plan.fired
    with pytest.raises(faults.FaultInjected):
        plan.maybe_raise("step", 7)
    assert plan.fired == [("nan", 4), ("ckpt_corrupt", 6),
                          ("ckpt_corrupt", 6), ("step", 7)]


def test_plan_rejects_bad_specs():
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan("frobnicate@3")
    with pytest.raises(ValueError, match="expected 'kind@step"):
        faults.FaultPlan("nan@")
    assert not faults.FaultPlan("")


def test_plan_from_env(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "data@2")
    faults.set_plan(None)  # drop the cached plan; re-read lazily
    assert faults.get_plan().fires("data", 2)


def test_poison_minibatch_floats_only():
    from bigdl_tpu.dataset.sample import MiniBatch

    mb = MiniBatch((np.ones((2, 3), np.float32),
                    np.arange(2, dtype=np.int32)),
                   np.zeros(2, np.int64))
    out = faults.poison_minibatch(mb)
    assert np.isnan(out.input[0]).all()
    np.testing.assert_array_equal(out.input[1], mb.input[1])
    np.testing.assert_array_equal(out.target, mb.target)
    # an all-integer batch can't be poisoned — must fail loudly, not
    # log 'fault injected' and pass vacuously
    with pytest.raises(ValueError, match="no floating-point"):
        faults.poison_minibatch(
            MiniBatch(np.arange(6, dtype=np.int32).reshape(2, 3),
                      np.zeros(2, np.int64)))


def test_corrupt_file_modes(tmp_path):
    p = tmp_path / "blob"
    p.write_bytes(b"a" * 300)
    faults.corrupt_file(str(p), "truncate")
    assert p.stat().st_size == 150
    p.write_bytes(b"a" * 300)
    faults.corrupt_file(str(p), "garble")
    data = p.read_bytes()
    assert len(data) == 300 and b"\xff" * 100 in data
    with pytest.raises(ValueError):
        faults.corrupt_file(str(p), "shred")


# ---------------------------------------------------------- AnomalyGuard

def test_guard_rejects_bad_config():
    with pytest.raises(ValueError):
        anomaly.AnomalyGuard(policy="explode")
    with pytest.raises(ValueError):
        anomaly.AnomalyGuard(max_consecutive=0)
    with pytest.raises(ValueError):
        anomaly.AnomalyGuard(spike_factor=0.5)


def test_guard_halt_raises_immediately():
    g = anomaly.AnomalyGuard(policy="halt")
    assert g.observe(True, 1.0, 0) == "ok"
    with pytest.raises(anomaly.AnomalyError):
        g.observe(False, float("nan"), 1)


def test_guard_consecutive_budget():
    g = anomaly.AnomalyGuard(policy="skip_step", max_consecutive=2)
    assert g.observe(False, float("inf"), 0) == "skipped"
    assert g.observe(False, float("inf"), 1) == "skipped"
    with pytest.raises(anomaly.AnomalyError, match="consecutive"):
        g.observe(False, float("inf"), 2)
    g2 = anomaly.AnomalyGuard(policy="skip_step", max_consecutive=2)
    g2.observe(False, float("inf"), 0)
    g2.observe(True, 1.0, 1)  # a healthy step resets the budget
    assert g2.consecutive == 0
    assert g2.observe(False, float("inf"), 2) == "skipped"
    assert g2.skipped == 2


def test_guard_rollback_replay_budget():
    """A data-inherent anomaly re-fires on the SAME step after every
    rollback (the replayed steps in between are healthy and reset the
    consecutive counter) — the replay streak must hit a budget instead
    of rollback-looping forever."""
    g = anomaly.AnomalyGuard(policy="rollback", max_consecutive=2)
    assert g.observe(False, float("nan"), 5) == "rollback"
    g.observe(True, 1.0, 3)  # replay from the checkpoint...
    g.observe(True, 1.0, 4)
    assert g.observe(False, float("nan"), 5) == "rollback"  # re-fires
    g.observe(True, 1.0, 3)
    g.observe(True, 1.0, 4)
    with pytest.raises(anomaly.AnomalyError, match="replays"):
        g.observe(False, float("nan"), 5)
    # progress past the anomalous step resets the streak
    g2 = anomaly.AnomalyGuard(policy="rollback", max_consecutive=1)
    assert g2.observe(False, float("nan"), 5) == "rollback"
    g2.observe(True, 1.0, 5)  # replay got past it this time
    assert g2.observe(False, float("nan"), 9) == "rollback"
    assert g2.rollbacks == 2


def test_guard_spike_threshold_arms_after_warmup():
    import math

    g = anomaly.AnomalyGuard(spike_factor=10.0, ema_decay=0.5,
                             warmup_steps=3)
    assert g.threshold() == math.inf
    for i in range(3):
        g.observe(True, 1.0, i)
    assert g.threshold() == pytest.approx(10.0)
    # EMA tracks healthy norms; anomalies must NOT move it
    g.observe(True, 3.0, 3)
    assert g.threshold() == pytest.approx(10.0 * 2.0)
    ema_before = g._ema
    g.observe(False, 1e9, 4)
    assert g._ema == ema_before


def test_guard_jit_predicate_and_norm():
    import jax.numpy as jnp

    nan, inf = float("nan"), float("inf")
    assert bool(anomaly.health_ok(jnp.float32(1.0), jnp.float32(2.0),
                                  jnp.float32(inf)))
    assert not bool(anomaly.health_ok(jnp.float32(nan), jnp.float32(2.0),
                                      jnp.float32(inf)))
    assert not bool(anomaly.health_ok(jnp.float32(1.0), jnp.float32(nan),
                                      jnp.float32(inf)))
    assert not bool(anomaly.health_ok(jnp.float32(1.0), jnp.float32(5.0),
                                      jnp.float32(4.0)))
    tree = {"a": jnp.ones((2, 2)), "b": jnp.full((3,), 2.0)}
    assert float(anomaly.global_norm(tree)) == pytest.approx(4.0)
