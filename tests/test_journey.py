"""Request journeys + incident flight recorder (ISSUE 11): the
journey builder over synthetic and real event streams, Perfetto
export (pure parse), the flight recorder's trigger/dump/determinism
contracts, and the obs_report journeys/incidents/per-layout sections.

The heavier end-to-end pins live in scripts/fault_drill.py
(fleet_journey: handoff + cross-layout failover journeys, byte-
identical bundles across runs) — these tests cover the units and the
single-engine integration."""

import importlib.util
import json
import os

import numpy as np
import pytest

from bigdl_tpu import obs
from bigdl_tpu.obs.flightrecorder import FlightRecorder, default_trigger
from bigdl_tpu.obs.journey import (build_journeys, journeys_json,
                                   summarize_journeys, to_perfetto)


@pytest.fixture(autouse=True)
def _fresh_obs():
    prev = obs.set_enabled(True)
    obs.reset_all()
    yield
    obs.reset_all()
    obs.set_enabled(prev)


# ------------------------------------------------------ journey builder

def _ev(kind, trace, hop, ts, **f):
    return {"schema": 1, "ts": ts, "seq": 0, "kind": kind,
            "trace": trace, "hop": hop, **f}


def test_build_journeys_failover_shape():
    """A failover journey: submit@e0, transitional failed terminal,
    re-submit@e1, done — one journey, two hops, the failed terminal
    superseded, dwell attributed per hop."""
    evs = [
        _ev("request_submit", "r0/0", 0, 1.0, engine="e0", tp=2,
            role="both", request=0),
        _ev("request_terminal", "r0/0", 0, 3.0, engine="e0",
            status="failed", reason="failed", tokens=1, request=0),
        _ev("request_submit", "r0/0", 1, 3.5, engine="e1", tp=1,
            role="both", request=0),
        _ev("router_failover", "r0/0", 1, 3.5, source="e0",
            target="e1", request=0),
        _ev("request_terminal", "r0/0", 1, 6.0, engine="e1",
            status="done", reason="max_tokens", tokens=5, request=0,
            ttft_s=0.5, latency_s=5.0),
    ]
    (j,) = build_journeys(evs)
    assert j["trace"] == "r0/0" and j["request"] == 0
    assert j["complete"] and j["lost_hops"] == []
    assert j["superseded_terminals"] == 1
    assert j["status"] == "done" and j["tokens"] == 5
    assert j["engines"] == ["e0", "e1"]
    assert j["layouts"] == [2, 1]
    assert j["cross_engine"] and j["cross_layout"]
    h0, h1 = j["hops"]
    assert h0["via"] == "request_submit" and h0["dwell_s"] == 2.5
    assert h1["dwell_s"] == 2.5           # 3.5 -> terminal at 6.0
    assert h0["events"]["request_terminal"] == 1
    assert h1["events"]["router_failover"] == 1


def test_build_journeys_handoff_and_lost_hops():
    """Disagg-prefill journey (submit@prefill -> handoff_import@
    decode) plus a broken trace whose hop 1 never seated."""
    evs = [
        _ev("request_submit", "t/a", 0, 0.0, engine="pf0", tp=1,
            role="prefill", request=1),
        _ev("handoff_export", "t/a", 0, 1.0, engine="pf0", request=1),
        _ev("handoff_import", "t/a", 1, 2.0, engine="e0", tp=2,
            role="both", request=1, source="pf0"),
        _ev("request_terminal", "t/a", 1, 4.0, engine="e0",
            status="done", reason="stop_id", tokens=3, request=1),
        # trace t/b: a non-terminal annotation on hop 1 with no seat
        # (and no settlement) — a genuinely LOST hop
        _ev("request_submit", "t/b", 0, 0.0, engine="e1", tp=1,
            role="both", request=2),
        _ev("prefix_hit", "t/b", 1, 4.0, engine="e1", request=2,
            matched_tokens=4, blocks=1),
        _ev("request_terminal", "t/b", 0, 5.0, engine="e1",
            status="done", reason="max_tokens", tokens=2, request=2),
        # trace t/c: shed ON ARRIVAL at the receiving engine after a
        # move (hop 1 terminal, never seated) — terminal-only, NOT lost
        _ev("request_submit", "t/c", 0, 0.0, engine="e0", tp=1,
            role="both", request=3),
        _ev("request_terminal", "t/c", 1, 2.0, engine="e1",
            status="shed", reason="shed", tokens=0, request=3),
    ]
    ja, jb, jc = build_journeys(evs)
    assert ja["hops"][0]["role"] == "prefill"
    assert ja["hops"][1]["via"] == "handoff_import"
    assert ja["complete"] and not ja["lost_hops"]
    assert ja["hops"][0]["events"]["handoff_export"] == 1
    assert jb["lost_hops"] == [1] and not jb["complete"]
    assert jc["lost_hops"] == [] and jc["complete"]
    assert jc["status"] == "shed"
    s = summarize_journeys([ja, jb, jc])
    assert s["count"] == 3 and s["complete"] == 2
    assert s["lost_hops"] == 1 and s["max_hops"] == 2


def test_rejected_bounce_is_attempt_not_lost_hop():
    """A rebalance/failover move that bounces off a full queue emits
    request_rejected at the PRE-incremented hop before the router
    undoes the increment — that phantom hop is a rejected ATTEMPT,
    never a lost hop (the request settled fine where it was)."""
    evs = [
        _ev("request_submit", "t/r", 0, 0.0, engine="e0", tp=1,
            role="both", request=5),
        _ev("request_rejected", "t/r", 1, 1.0, engine="e1", request=5,
            queue_depth=2),
        _ev("request_terminal", "t/r", 0, 3.0, engine="e0",
            status="done", reason="max_tokens", tokens=3, request=5),
    ]
    (j,) = build_journeys(evs)
    assert j["complete"] and j["lost_hops"] == []
    assert j["rejected_attempts"] == 1
    assert len(j["hops"]) == 1 and j["hops"][0]["engine"] == "e0"
    assert j["status"] == "done"


def test_journeys_json_and_perfetto_parse():
    evs = [
        _ev("request_submit", "t/x", 0, 1.0, engine="e0", tp=1,
            role="both", request=9),
        _ev("request_terminal", "t/x", 0, 2.0, engine="e0",
            status="done", reason="max_tokens", tokens=4, request=9),
    ]
    js = build_journeys(evs)
    # canonical rendering is stable and parseable
    assert json.loads(journeys_json(js)) == json.loads(
        journeys_json(build_journeys(evs)))
    doc = json.loads(json.dumps(to_perfetto(js)))
    names = [e["name"] for e in doc["traceEvents"]]
    assert "thread_name" in names                 # track metadata
    x = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(x) == 1 and x[0]["ts"] == 1.0e6 and x[0]["dur"] == 1.0e6
    assert any(e["ph"] == "i" for e in doc["traceEvents"])
    # events without a trace produce no journeys
    assert build_journeys([{"kind": "train_step", "ts": 0.0}]) == []


# ------------------------------------------------------ flight recorder

def test_default_trigger_set():
    assert default_trigger({"kind": "engine_degraded"}) \
        == "engine_degraded"
    assert default_trigger({"kind": "request_terminal",
                            "status": "poisoned"}) == "poisoned"
    assert default_trigger({"kind": "request_terminal", "status": "done",
                            "reason": "pool_exhausted"}) \
        == "pool_exhausted"
    assert default_trigger({"kind": "request_terminal",
                            "status": "done"}) is None
    assert default_trigger({"kind": "preempted"}) == "preempted"
    assert default_trigger({"kind": "fault_injected",
                            "fault": "preempt"}) == "preempted"
    assert default_trigger({"kind": "checkpoint_corrupt_skipped"}) \
        == "checkpoint_corrupt"
    assert default_trigger({"kind": "train_step"}) is None


def _drive(outdir, clk):
    """One synthetic incident run under an injected clock; returns the
    recorder (bundles written into `outdir`)."""
    obs.reset_all(clock=lambda: clk["t"])
    rec = FlightRecorder(outdir, clock=lambda: clk["t"])
    rec.register_health_source("e0", lambda: {"state": "degraded",
                                              "watchdog_trips": 1})
    rec.install()
    obs.emit_event("request_submit", plane="serving", engine="e0",
                   request=0, trace="r/0", hop=0, tp=1, role="both")
    clk["t"] += 1.0
    obs.emit_event("engine_degraded", plane="serving", engine="e0",
                   reason="watchdog trip at decode step 2: budget")
    clk["t"] += 1.0
    obs.emit_event("request_terminal", plane="serving", engine="e0",
                   request=0, trace="r/0", hop=0, status="failed",
                   reason="failed", tokens=0)
    rec.close()
    return rec


def test_flight_recorder_dump_and_determinism(tmp_path):
    """A trigger event dumps a full bundle (manifest/events/components/
    health/registry/journeys) whose event tail names the failing step;
    two identical runs under the injected clock produce byte-identical
    bundle files; the dump indexes itself via an incident_dump event."""
    runs = []
    for tag in ("a", "b"):
        outdir = str(tmp_path / tag)
        rec = _drive(outdir, {"t": 10.0})
        assert rec.bundles == ["incident-000-engine_degraded"]
        assert rec.triggers_seen == 1
        bundle = os.path.join(outdir, rec.bundles[0])
        files = sorted(os.listdir(bundle))
        assert files == ["components.json", "events.jsonl",
                         "health.json", "journeys.json",
                         "manifest.json", "registry.json"]
        with open(os.path.join(bundle, "manifest.json")) as f:
            man = json.load(f)
        assert man["incident"] == "engine_degraded"
        assert man["component"] == "e0"
        assert man["trigger"]["kind"] == "engine_degraded"
        assert "decode step 2" in man["trigger"]["reason"]
        with open(os.path.join(bundle, "events.jsonl")) as f:
            tail = [json.loads(ln) for ln in f]
        assert any(e["kind"] == "engine_degraded"
                   and "decode step 2" in e["reason"] for e in tail)
        with open(os.path.join(bundle, "health.json")) as f:
            assert json.load(f)["e0"]["watchdog_trips"] == 1
        with open(os.path.join(bundle, "journeys.json")) as f:
            (j,) = json.load(f)
        assert j["trace"] == "r/0" and j["engines"] == ["e0"]
        # the dump indexed itself in the event record
        dumps = obs.get_event_log().events("incident_dump")
        assert len(dumps) == 1
        assert dumps[0]["bundle"] == rec.bundles[0]
        assert dumps[0]["trigger_kind"] == "engine_degraded"
        runs.append({
            f: open(os.path.join(bundle, f), "rb").read()
            for f in files})
    assert runs[0] == runs[1]                  # byte-identical bundles


def test_flight_recorder_off_switch_and_budget(tmp_path):
    """BIGDL_OBS=off kills the recorder (no rings, no dumps); the
    bundle budget caps dumps but keeps counting triggers."""
    rec = FlightRecorder(str(tmp_path / "off")).install()
    obs.set_enabled(False)
    obs.get_event_log().emit("engine_degraded", engine="e0", reason="x")
    # emit_event (the gated path) wouldn't even reach the log; a direct
    # log.emit DOES reach the listener, which must early-out on the
    # kill switch itself
    assert rec.bundles == [] and rec.triggers_seen == 0
    obs.set_enabled(True)
    rec.close()

    rec2 = FlightRecorder(str(tmp_path / "cap"), max_bundles=1).install()
    for i in range(3):
        obs.emit_event("engine_degraded", engine=f"e{i}", reason="r")
    rec2.close()
    assert len(rec2.bundles) == 1 and rec2.triggers_seen == 3
    # a failing health source never blocks the dump
    rec3 = FlightRecorder(str(tmp_path / "err")).install()
    rec3.register_health_source("bad", lambda: 1 / 0)
    obs.emit_event("engine_degraded", engine="e9", reason="r")
    rec3.close()
    bundle = os.path.join(str(tmp_path / "err"), rec3.bundles[0])
    with open(os.path.join(bundle, "health.json")) as f:
        assert "error" in json.load(f)["bad"]


def test_listener_api_and_removal():
    log = obs.get_event_log()
    seen = []
    log.add_listener(seen.append)
    obs.emit_event("tick", i=0)
    log.remove_listener(seen.append)
    obs.emit_event("tick", i=1)
    assert [e["i"] for e in seen] == [0]
    log.remove_listener(seen.append)           # idempotent
    # a raising listener never breaks emit
    def boom(rec):
        raise RuntimeError("x")
    log.add_listener(boom)
    assert obs.emit_event("tick", i=2)["i"] == 2
    log.remove_listener(boom)


# --------------------------------------------- engine integration (CPU)

def _tiny_lm():
    import jax

    from bigdl_tpu.models.transformer import build_lm

    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=1,
                 max_len=64)
    m.build(jax.random.PRNGKey(0))
    return m


def test_engine_journeys_and_poison_bundle(tmp_path):
    """A bare engine (no router) stamps its own trace context; the
    journey builder reconstructs one single-hop journey per request;
    a poisoned request trips the flight recorder; and the whole new
    layer stays inside the compile contract — #buckets+1 traces with
    journeys + recorder armed, zero on wave 2."""
    from bigdl_tpu.serving import InferenceEngine, Request
    from bigdl_tpu.utils import faults

    m = _tiny_lm()
    rec = FlightRecorder(str(tmp_path)).install()
    eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                          obs_label="solo")
    rec.register_health_source("solo", eng.health)
    rng = np.random.RandomState(0)
    reqs = [Request(prompt=list(rng.randint(1, 50, n)),
                    max_new_tokens=3) for n in (3, 10, 6, 12)]
    res = eng.run(reqs)
    assert all(r.status == "done" for r in res)
    assert eng.stats["prefill_traces"] == 2       # both buckets
    assert eng.stats["decode_traces"] == 1        # ONE executable
    # wave 2 under the armed recorder: nothing new compiles
    faults.set_plan(faults.FaultPlan("serve_nan@" +
                                     str(eng.stats["decode_steps"])))
    try:
        res2 = eng.run([Request(prompt=[1, 2, 3], max_new_tokens=4)])
    finally:
        faults.set_plan(None)
    assert eng.stats["prefill_traces"] == 2
    assert eng.stats["decode_traces"] == 1
    assert res2[0].status == "poisoned"
    rec.close()
    # every request reconstructs to ONE complete single-hop journey
    journeys = build_journeys(obs.get_event_log().events())
    assert len(journeys) == 5
    assert all(j["complete"] and not j["lost_hops"] for j in journeys)
    assert all(len(j["hops"]) == 1
               and j["hops"][0]["engine"] == "solo"
               and j["hops"][0]["tp"] == 1 for j in journeys)
    assert {j["status"] for j in journeys} == {"done", "poisoned"}
    # the poisoned terminal tripped a bundle naming the request
    assert rec.bundles and "poisoned" in rec.bundles[0]
    with open(os.path.join(str(tmp_path), rec.bundles[0],
                           "manifest.json")) as f:
        man = json.load(f)
    assert man["trigger"]["status"] == "poisoned"
    assert man["component"] == "solo"


# ------------------------------------------------------------ obs_report

def _load_report():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "obs_report.py")
    spec = importlib.util.spec_from_file_location("obs_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_journeys_incidents_and_layout(tmp_path, capsys):
    """The new report sections: per-engine SLO carries tp/role with a
    per-layout rollup, the journeys section tables per-request hops,
    incidents digests the flight-recorder dumps, and --perfetto writes
    a loadable journey trace."""
    path = tmp_path / "run.jsonl"
    obs.set_event_log(obs.EventLog(path=str(path), clock=lambda: 1.0))
    obs.emit_event("request_submit", plane="serving", engine="e0",
                   request=0, prompt_len=3, priority=0, tp=2,
                   role="both", trace="r0/0", hop=0)
    obs.emit_event("request_terminal", plane="serving", engine="e0",
                   request=0, status="failed", reason="failed",
                   tokens=1, ttft_s=None, latency_s=1.0, tp=2,
                   role="both", trace="r0/0", hop=0)
    obs.emit_event("request_submit", plane="serving", engine="e1",
                   request=0, prompt_len=3, priority=0, tp=1,
                   role="both", trace="r0/0", hop=1)
    obs.emit_event("request_terminal", plane="serving", engine="e1",
                   request=0, status="done", reason="max_tokens",
                   tokens=5, ttft_s=0.5, latency_s=2.0, tp=1,
                   role="both", trace="r0/0", hop=1)
    obs.emit_event("incident_dump", incident="engine_degraded",
                   bundle="incident-000-engine_degraded",
                   component="e0", trigger_kind="engine_degraded",
                   events_in_tail=4)
    obs.get_event_log().close()

    rep = _load_report()
    events = obs.read_jsonl(str(path))
    s = rep.summarize(events)
    assert s["slo"]["per_engine"]["e0"]["tp"] == 2
    assert s["slo"]["per_engine"]["e1"]["role"] == "both"
    assert set(s["slo"]["per_layout"]) == {"tp=1", "tp=2"}
    assert s["slo"]["per_layout"]["tp=1"]["done"] == 1
    j = s["journeys"]
    assert j["summary"]["count"] == 1
    assert j["summary"]["cross_engine"] == 1
    assert j["summary"]["cross_layout"] == 1
    assert j["summary"]["superseded_terminals"] == 1
    assert j["table"][0]["hops"][0]["engine"] == "e0"
    assert j["table"][0]["status"] == "done"
    inc = s["incidents"]
    assert inc["count"] == 1
    assert inc["by_incident"] == {"engine_degraded": 1}
    assert inc["bundles"][0]["component"] == "e0"
    # render + perfetto export through the CLI
    out_trace = str(tmp_path / "journeys.json")
    assert rep.main([str(path), "--perfetto", out_trace]) == 0
    txt = capsys.readouterr().out
    assert "request journeys:" in txt
    assert "incidents (flight recorder):" in txt
    assert "tp=2" in txt
    with open(out_trace) as f:
        doc = json.load(f)
    assert any(e.get("cat") == "journey" for e in doc["traceEvents"])
