"""Calibrated fleet simulator (ISSUE 20): BENCH-artifact calibration
(committed rows only, provenance attached), the modeled-cost algebra,
InferenceEngine surface parity + determinism, the degrade() chaos
hook, and THE honesty gate — the sim-vs-real divergence test that
keeps the cost model within a bench_compare-style tolerance of a real
tiny fleet on the identical trace."""

import importlib.util
import json
import os
import sys

import pytest

from bigdl_tpu import obs
from bigdl_tpu.serving.engine import Request
from bigdl_tpu.serving.sim import CostModel, SimulatedEngine


@pytest.fixture(autouse=True)
def _fresh_obs():
    prev = obs.set_enabled(True)
    obs.reset_all()
    yield
    obs.reset_all()
    obs.set_enabled(prev)


def _loadgen():
    mod = sys.modules.get("bigdl_loadgen")  # one shared module object
    if mod is not None:
        return mod
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "loadgen.py")
    spec = importlib.util.spec_from_file_location("bigdl_loadgen", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bigdl_loadgen"] = mod
    spec.loader.exec_module(mod)
    return mod


def _bench_artifact(path, tail_rows):
    path.write_text(json.dumps(
        {"tail": "\n".join(json.dumps(r) if isinstance(r, dict)
                           else str(r) for r in tail_rows)}))
    return str(path)


# ----------------------------------------------------------- cost model

def test_calibration_reads_committed_rows_only(tmp_path):
    """Row admission is the bench_compare rule: a dict with a string
    "metric" and numeric "value" on one tail line — garbage lines,
    wrong-shaped rows, and unparseable artifacts are ignored, never
    fatal. The anchor is the MEDIAN lm-throughput row; the recorded
    cross-round spread becomes the divergence tolerance's floor."""
    m = CostModel.CALIBRATION_METRIC + "[tpu]"
    p1 = _bench_artifact(tmp_path / "BENCH_r01.json", [
        {"metric": m, "value": 100.0},
        {"metric": "unrelated_row", "value": 1.0},
        "not json at all {",
        {"metric": 123, "value": 4.0},          # non-string metric
        {"metric": "no_value_row"},
    ])
    p2 = _bench_artifact(tmp_path / "BENCH_r02.json", [
        {"metric": m, "value": 120.0},
        {"metric": CostModel.INT8_METRIC + "[tpu]", "value": 900.0,
         "int8_vs_bf16_speedup": 2.0},
    ])
    p3 = str(tmp_path / "BENCH_r03.json")
    with open(p3, "w") as f:
        f.write("{torn json")                    # unparseable artifact
    cm = CostModel.from_bench_artifacts([p1, p2, p3])
    med = 110.0                                  # median of 100, 120
    fwd = med * CostModel.TRAIN_FWD_FACTOR
    assert cm.base_prefill_ms == pytest.approx(1e3 / fwd)
    assert cm.base_decode_ms == pytest.approx(
        1e3 / (fwd * CostModel.DECODE_EFFICIENCY))
    assert cm.int8_speedup == 2.0
    assert cm.spread_frac == pytest.approx((120 - 100) / 2 / med)
    prov = cm.provenance()
    assert len(prov["sources"]) == 3             # 2 lm rows + int8
    assert prov["factors"]["train_fwd_factor"] == 3.0
    with pytest.raises(ValueError, match="no committed calibration"):
        CostModel.from_bench_artifacts([p3])


def test_calibration_from_repo_artifacts():
    """The default glob finds the repo's committed BENCH_r0*.json —
    the simulator must never invent latencies from thin air."""
    cm = CostModel.from_bench_artifacts()
    assert cm.base_decode_ms > 0 and cm.base_prefill_ms > 0
    assert all(s["artifact"].startswith("BENCH_r0")
               for s in cm.sources)
    assert len(cm.sources) >= 1


def test_cost_algebra():
    cm = CostModel(base_decode_ms=1.0, base_prefill_ms=0.1,
                   int8_speedup=2.0, sources=[], spread_frac=0.1)
    # context growth: cost doubles at the reference bucket
    assert cm.decode_ms(bucket=int(cm.CONTEXT_REF)) \
        == pytest.approx(2 * cm.decode_ms(bucket=0))
    # tp divides compute; int8 divides by the committed speedup
    assert cm.decode_ms(bucket=128, tp=4) \
        == pytest.approx(cm.decode_ms(bucket=128) / 4)
    assert cm.decode_ms(bucket=128, layout_family="int8/bfloat16") \
        == pytest.approx(cm.decode_ms(bucket=128) / 2.0)
    # speculative accept a → (1+a) tokens per target-priced round
    assert cm.decode_ms(bucket=128, spec_accept=0.5) \
        == pytest.approx(cm.decode_ms(bucket=128) / 1.5)
    assert cm.decode_ms(bucket=128, spec_accept=9.0) \
        == pytest.approx(cm.decode_ms(bucket=128) / 2.0)  # clamped
    # prefill is linear in prompt length
    assert cm.prefill_ms(32) == pytest.approx(2 * cm.prefill_ms(16))
    with pytest.raises(ValueError, match="positive"):
        CostModel(base_decode_ms=0.0, base_prefill_ms=0.1,
                  int8_speedup=1.0, sources=[], spread_frac=0.0)


# ------------------------------------------------------------ the engine

def _sim_engine(clk, **kw):
    cm = kw.pop("cost_model", None) or CostModel(
        base_decode_ms=1.0, base_prefill_ms=0.1, int8_speedup=1.0,
        sources=[], spread_frac=0.1)
    kw.setdefault("slots", 2)
    kw.setdefault("pacing", "per_step")
    return SimulatedEngine(cm, clock=lambda: clk["t"], **kw)


def _drive(eng, reqs, clk, step_dt=0.25, max_rounds=500):
    got = {}
    ids = [eng.submit(r) for r in reqs]
    rounds = 0
    while len(got) < len(ids):
        rounds += 1
        assert rounds < max_rounds, "sim engine stalled"
        clk["t"] = round(clk["t"] + step_dt, 9)
        for res in eng.step():
            got[res.id] = res
    return [got[i] for i in ids]


def test_engine_surface_and_validation():
    clk = {"t": 0.0}
    with pytest.raises(ValueError, match="clock"):
        SimulatedEngine(CostModel(base_decode_ms=1.0,
                                  base_prefill_ms=0.1,
                                  int8_speedup=1.0, sources=[],
                                  spread_frac=0.0), clock=None)
    with pytest.raises(ValueError, match="pacing"):
        _sim_engine(clk, pacing="warp")
    eng = _sim_engine(clk, obs_label="simT")
    h = eng.health()
    assert h["state"] == "ok" and h["attn_impl"] == "simulated"
    assert h["slots"] == 2 and h["queue_depth"] == 0
    assert eng.obs_name == "simT"
    # one sim_calibration provenance event per engine construction
    cal = [e for e in obs.get_event_log().events()
           if e["kind"] == "sim_calibration"
           and e["engine"] == "simT"]
    assert len(cal) == 1 and cal[0]["decode_ms_per_token"] > 0


def test_deterministic_tokens_across_replays():
    """Two engines over one model, same trace: identical statuses,
    identical token streams — no RNG object anywhere in the sim."""
    reqs = [dict(prompt=[1 + i, 2 + i, 3 + i], max_new_tokens=4,
                 temperature=0.8, seed=31 + i) for i in range(6)]
    runs = []
    for _ in range(2):
        clk = {"t": 0.0}
        eng = _sim_engine(clk)
        runs.append(_drive(eng, [Request(**r) for r in reqs], clk))
    assert [r.status for r in runs[0]] == ["done"] * 6
    assert [list(r.tokens) for r in runs[0]] \
        == [list(r.tokens) for r in runs[1]]
    assert all(len(r.tokens) == 4 for r in runs[0])
    assert all(r.ttft_s is not None and r.latency_s is not None
               for r in runs[0])


def test_overload_policy_and_degrade_chaos_hook():
    clk = {"t": 0.0}
    eng = _sim_engine(clk, slots=1, max_queue=2,
                      overload_policy="reject", obs_label="simO")
    for i in range(2):
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=3,
                           seed=i))
    from bigdl_tpu.serving.engine import OverloadError
    with pytest.raises(OverloadError):
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=3, seed=9))
    # the chaos hook: every queued/in-flight request parks as 'failed'
    # in completed (the router failover harvest) + one engine_degraded
    failed = eng.degrade("chaos_watchdog")
    assert eng.degraded == "chaos_watchdog"
    assert len(failed) == 2
    assert {r.status for r in eng.completed.values()} == {"failed"}
    ev = [e for e in obs.get_event_log().events()
          if e["kind"] == "engine_degraded" and e["engine"] == "simO"]
    assert len(ev) == 1 and ev[0]["reason"] == "chaos_watchdog"
    from bigdl_tpu.serving.engine import EngineDegraded
    with pytest.raises(EngineDegraded):
        eng.submit(Request(prompt=[1], max_new_tokens=1, seed=0))


# -------------------------------------------------- sim-vs-real honesty

def test_divergence_vs_real_fleet():
    """THE calibration honesty gate: the identical 24-request trace
    through a REAL tiny fleet and a simulated one (per_step pacing —
    structural parity mode). Terminal counts and goodput tokens must
    agree EXACTLY (scheduling structure is modeled, not approximated);
    virtual latency/makespan must agree within a bench_compare-style
    tolerance — max(0.25, 1.5x the calibration rows' recorded
    cross-round spread). If the cost constants drift from what the
    control plane actually does, this is the test that fails."""
    lg = _loadgen()
    reports = {}
    for mode in ("real", "sim"):
        trace = lg.make_trace(24, seed=3, arrival="poisson", rate=6.0)
        if mode == "real":
            router, asc, clk = lg.build_fleet(1, slots=4)
        else:
            router, asc, clk = lg.build_sim_fleet(1, slots=4,
                                                  pacing="per_step")
        reports[mode] = lg.replay(router, trace, clock=clk)
    real, sim = reports["real"], reports["sim"]
    assert sim["by_status"] == real["by_status"] == {"done": 24}
    assert sim["goodput_tokens"] == real["goodput_tokens"]
    tol = max(0.25, 1.5 * CostModel.from_bench_artifacts().spread_frac)
    for key in ("latency_p50_s", "latency_p99_s", "ttft_p50_s",
                "makespan_s"):
        rv, sv = real[key], sim[key]
        assert rv is not None and sv is not None, key
        rel = abs(sv - rv) / max(abs(rv), 1e-9)
        assert rel <= tol, (key, rv, sv, rel, tol)


@pytest.mark.slow
def test_scenario_scale_replay_is_deterministic():
    """Duplicate coverage of the scenario_chaos drill at 10x its
    size (slow tier): a ~1.4k-request chaos_smoke day, two full
    replays through the simulated fleet, report JSON byte-identical."""
    lg = _loadgen()
    from bigdl_tpu.serving import TenantSpec
    from bigdl_tpu.serving.scenarios import compile_scenario

    digests = []
    for _ in range(2):
        trace = compile_scenario("chaos_smoke", scale=10.0)
        fc = trace["fleet"]
        router, asc, clk = lg.build_sim_fleet(
            fc["engines"], slots=fc["slots"],
            max_queue=fc["max_queue"],
            overload_policy=fc["overload_policy"], pacing=fc["pacing"],
            tenant_specs=[TenantSpec(**kw) for kw in trace["tenants"]])
        report = lg.replay(router, trace, clock=clk)
        digests.append(json.dumps(report, sort_keys=True))
    assert digests[0] == digests[1]
    rep = json.loads(digests[0])
    assert rep["requests"] == 960 + 480
    assert rep["scenario"]["fired"]["chaos"] == 2
