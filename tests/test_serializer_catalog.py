"""Reflection-driven serialization spec over the FULL layer catalog.

Reference parity: utils/serializer/SerializerSpec.scala — the reference
auto-enumerates every layer class via reflection and requires each to
round-trip through the serializer (SURVEY.md §4 "Serialization
round-trip"). Here: every concrete Module/Criterion defined under
`bigdl_tpu.nn` is discovered by reflection; each must either have a
canonical construction in CANON below (and then round-trip through
module_serializer with bit-identical forward outputs) or appear in
SKIP with a reason. A class in neither place FAILS the discovery test —
adding a layer forces adding its spec.
"""

import importlib
import inspect
import pkgutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Criterion, Module
from bigdl_tpu.nn.recurrent import Cell
from bigdl_tpu.serialization import load_module, save_module
from bigdl_tpu.serialization.module_serializer import (
    module_to_spec, spec_to_module,
)
from bigdl_tpu.utils.table import T

# ------------------------------------------------------------- discovery

BASES = {"Module", "Criterion", "Container", "Cell", "Graph"}


def discover():
    """All concrete Module/Criterion classes defined under bigdl_tpu.nn."""
    import bigdl_tpu.nn as nnpkg

    found = {}
    for info in pkgutil.iter_modules(nnpkg.__path__):
        mod = importlib.import_module(f"bigdl_tpu.nn.{info.name}")
        for name, obj in vars(mod).items():
            if (inspect.isclass(obj) and obj.__module__ == mod.__name__
                    and not name.startswith("_")
                    and issubclass(obj, (Module, Criterion))
                    and name not in BASES):
                found[name] = obj
    return found


# ---------------------------------------------------------------- inputs

_r = np.random.default_rng(7)
x2 = jnp.asarray(_r.normal(size=(4, 8)), jnp.float32)
x2b = jnp.asarray(_r.normal(size=(4, 8)), jnp.float32)
xpos = jnp.abs(x2) + 0.1
xprob = jax.nn.sigmoid(x2)
img = jnp.asarray(_r.normal(size=(2, 8, 8, 3)), jnp.float32)
seq = jnp.asarray(_r.normal(size=(2, 5, 6)), jnp.float32)
vol = jnp.asarray(_r.normal(size=(2, 4, 8, 8, 3)), jnp.float32)
ids = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
y4 = jnp.asarray([0, 2, 1, 3], jnp.int32)

from bigdl_tpu.nn.sparse import encode_sparse

_sp_idx, _sp_val = encode_sparse(
    [([1, 4], [1.0, 2.0]), ([0, 2, 7], [0.5, 1.5, -1.0])])
sparse_in = (jnp.asarray(_sp_idx), jnp.asarray(_sp_val))

# ------------------------------------------------------- canonical specs
# name -> (builder, inputs tuple)

CANON = {
    # activations
    "Abs": (lambda: nn.Abs(), (x2,)),
    "Clamp": (lambda: nn.Clamp(-1.0, 1.0), (x2,)),
    "ELU": (lambda: nn.ELU(0.9), (x2,)),
    "Exp": (lambda: nn.Exp(), (x2,)),
    "GELU": (lambda: nn.GELU(), (x2,)),
    "HardSigmoid": (lambda: nn.HardSigmoid(), (x2,)),
    "HardTanh": (lambda: nn.HardTanh(-2.0, 2.0), (x2,)),
    "LeakyReLU": (lambda: nn.LeakyReLU(0.1), (x2,)),
    "Log": (lambda: nn.Log(), (xpos,)),
    "LogSoftMax": (lambda: nn.LogSoftMax(), (x2,)),
    "Mish": (lambda: nn.Mish(), (x2,)),
    "PReLU": (lambda: nn.PReLU(8), (x2,)),
    "Power": (lambda: nn.Power(2.0, 1.5, 0.5), (xpos,)),
    "ReLU": (lambda: nn.ReLU(), (x2,)),
    "ReLU6": (lambda: nn.ReLU6(), (x2,)),
    "RReLU": (lambda: nn.RReLU(), (x2,)),
    "SReLU": (lambda: nn.SReLU((8,)), (x2,)),
    "Sigmoid": (lambda: nn.Sigmoid(), (x2,)),
    "SoftMax": (lambda: nn.SoftMax(), (x2,)),
    "SoftPlus": (lambda: nn.SoftPlus(), (x2,)),
    "SoftSign": (lambda: nn.SoftSign(), (x2,)),
    "Sqrt": (lambda: nn.Sqrt(), (xpos,)),
    "Square": (lambda: nn.Square(), (x2,)),
    "Swish": (lambda: nn.Swish(), (x2,)),
    "Tanh": (lambda: nn.Tanh(), (x2,)),
    # linear-family
    "Linear": (lambda: nn.Linear(8, 3), (x2,)),
    "Bilinear": (lambda: nn.Bilinear(8, 8, 3), ((x2, x2b),)),
    "CAdd": (lambda: nn.CAdd((8,)), (x2,)),
    "CMul": (lambda: nn.CMul((8,)), (x2,)),
    "Cosine": (lambda: nn.Cosine(8, 3), (x2,)),
    "Euclidean": (lambda: nn.Euclidean(8, 3), (x2,)),
    # reshape / structural
    "AddConstant": (lambda: nn.AddConstant(1.5), (x2,)),
    "Contiguous": (lambda: nn.Contiguous(), (x2,)),
    "Echo": (lambda: nn.Echo(), (x2,)),
    "GradientReversal": (lambda: nn.GradientReversal(0.5), (x2,)),
    "Identity": (lambda: nn.Identity(), (x2,)),
    "Masking": (lambda: nn.Masking(0.0), (seq,)),
    "MulConstant": (lambda: nn.MulConstant(2.0), (x2,)),
    "Narrow": (lambda: nn.Narrow(2, 2, 4), (x2,)),
    "Padding": (lambda: nn.Padding(2, 2, 2), (x2,)),
    "Replicate": (lambda: nn.Replicate(3), (x2,)),
    "Reshape": (lambda: nn.Reshape([2, 4]), (x2,)),
    "Select": (lambda: nn.Select(2, 3), (x2,)),
    "Squeeze": (lambda: nn.Squeeze(), (jnp.reshape(x2, (4, 1, 8)),)),
    "Unsqueeze": (lambda: nn.Unsqueeze(2), (x2,)),
    "Transpose": (lambda: nn.Transpose([(2, 3)]), (seq,)),
    "View": (lambda: nn.View(2, 4), (x2,)),
    "SpatialZeroPadding": (lambda: nn.SpatialZeroPadding(1, 1, 1, 1),
                           (img,)),
    "SpaceToDepth": (lambda: nn.SpaceToDepth(2), (img,)),
    # table ops
    "CAddTable": (lambda: nn.CAddTable(), ((x2, x2b),)),
    "CSubTable": (lambda: nn.CSubTable(), ((x2, x2b),)),
    "CMulTable": (lambda: nn.CMulTable(), ((x2, x2b),)),
    "CDivTable": (lambda: nn.CDivTable(), ((x2, xpos),)),
    "CMaxTable": (lambda: nn.CMaxTable(), ((x2, x2b),)),
    "CMinTable": (lambda: nn.CMinTable(), ((x2, x2b),)),
    "JoinTable": (lambda: nn.JoinTable(1, n_input_dims=1), ((x2, x2b),)),
    "SplitTable": (lambda: nn.SplitTable(2), (x2,)),
    "SelectTable": (lambda: nn.SelectTable(1), ((x2, x2b),)),
    "FlattenTable": (lambda: nn.FlattenTable(), (T(x2, T(x2b)),)),
    "DotProduct": (lambda: nn.DotProduct(), ((x2, x2b),)),
    "CosineDistance": (lambda: nn.CosineDistance(), ((x2, x2b),)),
    "MM": (lambda: nn.MM(), ((jnp.reshape(x2, (2, 4, 4)),
                              jnp.reshape(x2b, (2, 4, 4))),)),
    "MV": (lambda: nn.MV(), ((jnp.reshape(x2, (2, 4, 4)),
                              jnp.reshape(x2b[:2, :4], (2, 4))),)),
    "Max": (lambda: nn.Max(1, n_input_dims=1), (x2,)),
    "Mean": (lambda: nn.Mean(1, n_input_dims=1), (x2,)),
    "Min": (lambda: nn.Min(1, n_input_dims=1), (x2,)),
    "Sum": (lambda: nn.Sum(1, n_input_dims=1), (x2,)),
    # containers
    "Sequential": (lambda: nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                         nn.Linear(16, 3)), (x2,)),
    "Concat": (lambda: nn.Concat(2, nn.Linear(8, 3), nn.Linear(8, 5)),
               (x2,)),
    "ConcatTable": (lambda: nn.ConcatTable(nn.Linear(8, 3), nn.ReLU()),
                    (x2,)),
    "ParallelTable": (lambda: nn.ParallelTable(nn.Linear(8, 3),
                                               nn.Linear(8, 5)),
                      ((x2, x2b),)),
    "MapTable": (lambda: nn.MapTable(nn.Linear(8, 3)), ((x2, x2b),)),
    "Bottle": (lambda: nn.Bottle(nn.Linear(6, 4)), (seq,)),
    # conv / pool / vision
    "SpatialConvolution": (lambda: nn.SpatialConvolution(3, 4, 3, 3, 1, 1,
                                                         1, 1), (img,)),
    "SpatialDilatedConvolution": (
        lambda: nn.SpatialDilatedConvolution(3, 4, 3, 3, 1, 1, 2, 2,
                                             dilation_w=2, dilation_h=2),
        (img,)),
    "SpatialFullConvolution": (
        lambda: nn.SpatialFullConvolution(3, 4, 3, 3, 2, 2), (img,)),
    "SpatialShareConvolution": (
        lambda: nn.SpatialShareConvolution(3, 4, 3, 3, 1, 1, 1, 1), (img,)),
    "TemporalConvolution": (lambda: nn.TemporalConvolution(6, 4, 2), (seq,)),
    "TemporalMaxPooling": (lambda: nn.TemporalMaxPooling(2), (seq,)),
    "SpatialMaxPooling": (lambda: nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
                          (img,)),
    "SpatialAveragePooling": (lambda: nn.SpatialAveragePooling(2, 2, 2, 2),
                              (img,)),
    "SpatialUpSamplingBilinear": (lambda: nn.SpatialUpSamplingBilinear(2),
                                  (img,)),
    "SpatialUpSamplingNearest": (lambda: nn.SpatialUpSamplingNearest(2),
                                 (img,)),
    "VolumetricConvolution": (
        lambda: nn.VolumetricConvolution(3, 4, 2, 2, 2), (vol,)),
    "VolumetricMaxPooling": (lambda: nn.VolumetricMaxPooling(2, 2, 2),
                             (vol,)),
    "VolumetricAveragePooling": (lambda: nn.VolumetricAveragePooling(2, 2, 2),
                                 (vol,)),
    # normalization
    "BatchNormalization": (lambda: nn.BatchNormalization(8), (x2,)),
    "SpatialBatchNormalization": (lambda: nn.SpatialBatchNormalization(3),
                                  (img,)),
    "SpatialCrossMapLRN": (lambda: nn.SpatialCrossMapLRN(5, 1e-4, 0.75),
                           (img,)),
    "LayerNorm": (lambda: nn.LayerNorm(8), (x2,)),
    "RMSNorm": (lambda: nn.RMSNorm(8), (x2,)),
    "Normalize": (lambda: nn.Normalize(2.0), (x2,)),
    # dropout family (eval mode → deterministic)
    "Dropout": (lambda: nn.Dropout(0.5), (x2,)),
    "GaussianDropout": (lambda: nn.GaussianDropout(0.3), (x2,)),
    "GaussianNoise": (lambda: nn.GaussianNoise(0.1), (x2,)),
    "SpatialDropout2D": (lambda: nn.SpatialDropout2D(0.4), (img,)),
    # embedding / sparse / quantized
    "LookupTable": (lambda: nn.LookupTable(10, 6), (ids,)),
    "LookupTableSparse": (lambda: nn.LookupTableSparse(16, 4),
                          (sparse_in,)),
    "SparseLinear": (lambda: nn.SparseLinear(16, 4), (sparse_in,)),
    "QuantizedLinear": (lambda: nn.QuantizedLinear(8, 3), (x2,)),
    "QuantizedSpatialConvolution": (
        lambda: nn.QuantizedSpatialConvolution(
            nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)), (img,)),
    # recurrent (cells covered via Recurrent wrapper)
    "Recurrent": (lambda: nn.Recurrent(nn.LSTM(6, 7)), (seq,)),
    "RnnCell": (lambda: nn.Recurrent(nn.RnnCell(6, 7)), (seq,)),
    "LSTM": (lambda: nn.Recurrent(nn.LSTM(6, 7)), (seq,)),
    "LSTMPeephole": (lambda: nn.Recurrent(nn.LSTMPeephole(6, 7)), (seq,)),
    "GRU": (lambda: nn.Recurrent(nn.GRU(6, 7)), (seq,)),
    "ConvLSTMPeephole": (
        lambda: nn.Recurrent(nn.ConvLSTMPeephole(3, 4, 3)),
        (jnp.asarray(_r.normal(size=(2, 3, 6, 6, 3)), jnp.float32),)),
    "BiRecurrent": (lambda: nn.BiRecurrent(nn.LSTM(6, 7)), (seq,)),
    "TimeDistributed": (lambda: nn.TimeDistributed(nn.Linear(6, 2)),
                        (seq,)),
    # attention
    "MultiHeadAttention": (lambda: nn.MultiHeadAttention(8, 2),
                           (jnp.asarray(_r.normal(size=(2, 5, 8)),
                                        jnp.float32),)),
}

# criterions: name -> (builder, (input, target))
CANON_CRIT = {
    "AbsCriterion": (lambda: nn.AbsCriterion(), (x2, x2b)),
    "BCECriterion": (lambda: nn.BCECriterion(),
                     (xprob, (x2b > 0).astype(jnp.float32))),
    "ClassNLLCriterion": (lambda: nn.ClassNLLCriterion(),
                          (jax.nn.log_softmax(x2, axis=-1), y4)),
    "ClassSimplexCriterion": (lambda: nn.ClassSimplexCriterion(8),
                              (x2, y4)),
    "CosineEmbeddingCriterion": (lambda: nn.CosineEmbeddingCriterion(),
                                 ((x2, x2b),
                                  jnp.asarray([1., -1., 1., -1.]))),
    "CosineProximityCriterion": (lambda: nn.CosineProximityCriterion(),
                                 (x2, x2b)),
    "CrossEntropyCriterion": (lambda: nn.CrossEntropyCriterion(), (x2, y4)),
    "DistKLDivCriterion": (lambda: nn.DistKLDivCriterion(),
                           (jax.nn.log_softmax(x2, axis=-1),
                            jax.nn.softmax(x2b, axis=-1))),
    "HingeEmbeddingCriterion": (lambda: nn.HingeEmbeddingCriterion(),
                                (xpos[:, 0], jnp.asarray([1., -1., 1., -1.]))),
    "KLDCriterion": (lambda: nn.KLDCriterion(), ((x2, x2b), x2)),
    "L1Cost": (lambda: nn.L1Cost(), (x2, x2)),
    "MSECriterion": (lambda: nn.MSECriterion(), (x2, x2b)),
    "MarginCriterion": (lambda: nn.MarginCriterion(),
                        (x2[:, 0], jnp.asarray([1., -1., 1., -1.]))),
    "MarginRankingCriterion": (lambda: nn.MarginRankingCriterion(),
                               ((x2[:, 0], x2b[:, 0]),
                                jnp.asarray([1., -1., 1., -1.]))),
    "MultiCriterion": (lambda: nn.MultiCriterion()
                       .add(nn.MSECriterion())
                       .add(nn.AbsCriterion(), 0.5), (x2, x2b)),
    "MultiLabelMarginCriterion": (
        lambda: nn.MultiLabelMarginCriterion(),
        (xprob, jnp.asarray([[1, 0, 0, 0, 0, 0, 0, 0]] * 4, jnp.int32))),
    "MultiMarginCriterion": (lambda: nn.MultiMarginCriterion(), (x2, y4)),
    "ParallelCriterion": (lambda: nn.ParallelCriterion()
                          .add(nn.MSECriterion())
                          .add(nn.AbsCriterion(), 0.5),
                          ((x2, x2), (x2b, x2b))),
    "ChunkedSoftmaxCE": (lambda: nn.ChunkedSoftmaxCE(chunk=128),
                         (jax.nn.log_softmax(x2, axis=-1), y4)),
    "SmoothL1Criterion": (lambda: nn.SmoothL1Criterion(), (x2, x2b)),
    "TimeDistributedCriterion": (
        lambda: nn.TimeDistributedCriterion(nn.MSECriterion()),
        (seq, jnp.zeros_like(seq))),
}

def _canonical_graph():
    """Two-branch DAG: input fans out to two Linear branches joined by
    CAddTable — exercises node wiring, fan-out, and multi-input join
    through the serializer (reference: nn/StaticGraph.scala)."""
    inp = nn.Input()
    a = nn.ReLU()(nn.Linear(8, 3)(inp))
    b = nn.Linear(8, 3)(inp)
    return nn.Graph(inp, nn.CAddTable()(a, b))


CANON["Graph"] = (_canonical_graph, (x2,))

CANON["SparseJoinTable"] = (
    lambda: nn.SparseJoinTable([8, 8]),  # _sp_idx ids are < 8
    ((jnp.asarray(_sp_idx), jnp.asarray(_sp_val)),
     (jnp.asarray(_sp_idx), jnp.asarray(_sp_val))))

# classes that legitimately cannot auto-construct: name -> reason
SKIP = {}


# ------------------------------------------------------------------ tests

def test_catalog_fully_enumerated():
    """Every discovered class has a canonical spec or a skip reason, and
    coverage is >90% of the catalog."""
    found = discover()
    covered = set(CANON) | set(CANON_CRIT)
    missing = sorted(set(found) - covered - set(SKIP))
    assert not missing, f"classes with no serialization spec: {missing}"
    pct = len(covered & set(found)) / len(found)
    assert pct > 0.9, f"catalog coverage {pct:.0%} <= 90%"


@pytest.mark.parametrize("name", sorted(CANON), ids=sorted(CANON))
def test_module_roundtrip(tmp_path, name):
    build, inputs = CANON[name]
    module = build()
    variables = module.init(jax.random.PRNGKey(3))
    out0, _ = module.apply(variables, *inputs, training=False)
    save_module(str(tmp_path), module, variables=variables)
    loaded, lvars = load_module(str(tmp_path))
    out1, _ = loaded.apply(lvars, *inputs, training=False)
    a_leaves = jax.tree_util.tree_leaves(out0)
    b_leaves = jax.tree_util.tree_leaves(out1)
    assert len(a_leaves) == len(b_leaves)
    for a, b in zip(a_leaves, b_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("name", sorted(CANON_CRIT), ids=sorted(CANON_CRIT))
def test_criterion_roundtrip(name):
    build, (inp, tgt) = CANON_CRIT[name]
    crit = build()
    loss0 = crit(inp, tgt)
    rebuilt = spec_to_module(module_to_spec(crit))
    assert type(rebuilt) is type(crit)
    loss1 = rebuilt(inp, tgt)
    np.testing.assert_array_equal(np.asarray(loss0), np.asarray(loss1))
