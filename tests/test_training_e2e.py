"""End-to-end training — the ★ minimum slice of SURVEY.md §7.3:
LeNet-5 on (synthetic) MNIST, jitted, converging, with checkpoint + TB
summaries (reference: models/lenet/Train.scala PR1 config)."""

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import (
    Adam, SGD, Optimizer, Trigger, Top1Accuracy, Loss, Evaluator, Predictor,
)
from bigdl_tpu.serialization.checkpoint import Checkpoint
from bigdl_tpu.visualization import TrainSummary, ValidationSummary

logging.basicConfig(level=logging.INFO)


@pytest.fixture(scope="module")
def mnist_data():
    return synthetic_mnist(512, seed=0), synthetic_mnist(128, seed=9)


class TestLeNetEndToEnd:
    def test_lenet_converges(self, mnist_data, tmp_path_factory):
        train, test = mnist_data
        tmp = tmp_path_factory.mktemp("lenet")
        model = lenet.build(10).build(jax.random.PRNGKey(7))
        train_summary = TrainSummary(str(tmp / "logs"), "lenet")
        val_summary = ValidationSummary(str(tmp / "logs"), "lenet")

        opt = (Optimizer(model, DataSet.array(train), nn.ClassNLLCriterion(),
                         batch_size=64)
               .set_optim_method(Adam(learningrate=2e-3))
               .set_end_when(Trigger.max_epoch(3))
               .set_validation(Trigger.every_epoch(), DataSet.array(test),
                               [Top1Accuracy()], 64)
               .set_checkpoint(str(tmp / "ckpt"), Trigger.every_epoch())
               .set_train_summary(train_summary)
               .set_validation_summary(val_summary))
        trained = opt.optimize()

        acc = Evaluator(trained).test(DataSet.array(test), [Top1Accuracy()], 64)
        top1 = acc["Top1Accuracy"].result()[0]
        assert top1 > 0.9, f"LeNet failed to learn synthetic MNIST: {top1}"

        # checkpoint exists and loads
        ck = Checkpoint(str(tmp / "ckpt"))
        variables, slots, train_state = ck.load()
        assert train_state["epoch"] >= 2

        # TB summaries readable
        losses = train_summary.read_scalar("Loss")
        assert len(losses) >= 10
        assert losses[-1][1] < losses[0][1]  # loss went down

    def test_predictor(self, mnist_data):
        train, test = mnist_data
        model = lenet.build(10).build(jax.random.PRNGKey(0))
        preds = Predictor(model, batch_size=32).predict_class(
            DataSet.array(test[:50]))
        assert preds.shape == (50,)
        assert preds.dtype in (np.int32, np.int64)

    def test_checkpoint_resume(self, mnist_data, tmp_path):
        train, _ = mnist_data
        model = lenet.build(10).build(jax.random.PRNGKey(1))
        opt = (Optimizer(model, DataSet.array(train[:128]),
                         nn.ClassNLLCriterion(), batch_size=64)
               .set_optim_method(SGD(learningrate=0.05))
               .set_end_when(Trigger.max_iteration(4))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2)))
        opt.optimize()

        model2 = lenet.build(10).build(jax.random.PRNGKey(2))
        opt2 = (Optimizer(model2, DataSet.array(train[:128]),
                          nn.ClassNLLCriterion(), batch_size=64)
                .set_optim_method(SGD(learningrate=0.05))
                .set_end_when(Trigger.max_iteration(8))
                .set_checkpoint(str(tmp_path), Trigger.several_iteration(2))
                .resume_from_checkpoint())
        trained = opt2.optimize()
        # resumed run continued counting from the saved neval
        ck = Checkpoint(str(tmp_path))
        _, _, ts = ck.load()
        assert ts["neval"] == 8

    def test_graph_lenet_trains(self, mnist_data):
        train, _ = mnist_data
        model = lenet.graph(10).build(jax.random.PRNGKey(3))
        opt = (Optimizer(model, DataSet.array(train[:128]),
                         nn.ClassNLLCriterion(), batch_size=32)
               .set_optim_method(Adam(learningrate=1e-3))
               .set_end_when(Trigger.max_iteration(3)))
        trained = opt.optimize()
        out = trained.evaluate().forward(jnp.ones((2, 28, 28, 1)))
        assert out.shape == (2, 10)


class TestParameterHistogramTrigger:
    def test_histograms_with_donated_buffers(self, mnist_data,
                                             tmp_path_factory):
        """Regression (ADVICE r1): the deferred _emit path used to read
        param buffers already donated to the next step's dispatch —
        np.asarray raised 'Array has been deleted'. Histograms are now
        materialized at snapshot time."""
        train, _ = mnist_data
        tmp = tmp_path_factory.mktemp("hist")
        model = lenet.build(10).build(jax.random.PRNGKey(2))
        summary = TrainSummary(str(tmp / "logs"), "hist")
        summary.set_summary_trigger("Parameters",
                                    Trigger.several_iteration(2))
        (Optimizer(model, DataSet.array(train[:128]),
                   nn.ClassNLLCriterion(), batch_size=32)
         .set_optim_method(Adam(learningrate=1e-3))
         .set_end_when(Trigger.max_iteration(5))
         .set_train_summary(summary)
         .optimize())
        summary.writer.flush()
        # histogram events parse as (tag, None, step) — scalar events
        # always carry a value, so value-None identifies the histograms
        import os as _os

        from bigdl_tpu.visualization.tensorboard import read_events
        logdir = summary.log_dir
        tags = set()
        for fname in _os.listdir(logdir):
            if "tfevents" in fname:
                for tag, value, _step in read_events(
                        _os.path.join(logdir, fname)):
                    if value is None:
                        tags.add(tag)
        assert tags, "no histogram events written"
