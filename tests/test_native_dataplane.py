"""Native (C++) data plane vs the pure-Python reference paths."""

import struct

import numpy as np
import pytest

from bigdl_tpu.dataset import native


@pytest.fixture(scope="module")
def have_native():
    ok = native.available()
    assert ok, "native data plane failed to build (g++ present per image)"
    return ok


def test_normalize_matches_numpy(have_native):
    rng = np.random.RandomState(0)
    img = rng.randint(0, 256, (8, 16, 16, 3), np.uint8)
    mean, std = [10.0, 20.0, 30.0], [2.0, 3.0, 4.0]
    out = native.normalize_u8(img, mean, std)
    ref = (img.astype(np.float32) - np.asarray(mean, np.float32)) / \
        np.asarray(std, np.float32)
    # native multiplies by a precomputed reciprocal → 1-ulp-level drift
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-6)


def test_idx_decode_roundtrip(have_native):
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (5, 9, 7), np.uint8)
    raw = struct.pack(">IIII", 2051, 5, 9, 7) + imgs.tobytes()
    out = native.decode_idx_images(raw)
    np.testing.assert_array_equal(out, imgs)

    labels = rng.randint(0, 10, (5,)).astype(np.uint8)
    raw_l = struct.pack(">II", 2049, 5) + labels.tobytes()
    np.testing.assert_array_equal(native.decode_idx_labels(raw_l), labels)


def test_idx_decode_rejects_bad_magic(have_native):
    raw = struct.pack(">IIII", 1234, 1, 2, 2) + bytes(4)
    with pytest.raises(ValueError, match="decode failed"):
        native.decode_idx_images(raw)


def test_cifar_decode_matches_python(have_native):
    rng = np.random.RandomState(2)
    n = 4
    recs = []
    for i in range(n):
        label = np.uint8(i % 10)
        chw = rng.randint(0, 256, (3, 32, 32), np.uint8)
        recs.append(bytes([label]) + chw.tobytes())
    raw = b"".join(recs)
    imgs, labels = native.decode_cifar10(raw)
    assert imgs.shape == (n, 32, 32, 3)
    # python reference
    buf = np.frombuffer(raw, np.uint8).reshape(n, 3073)
    ref = buf[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1)
    np.testing.assert_array_equal(imgs, ref)
    np.testing.assert_array_equal(labels, buf[:, 0])


def test_prefetcher_covers_epoch(have_native):
    rng = np.random.RandomState(3)
    n, h, w, c = 64, 8, 8, 1
    images = rng.randint(0, 256, (n, h, w, c), np.uint8)
    # encode the sample index in the label to track coverage
    labels = np.arange(n, dtype=np.int32)
    # n_threads=1: multi-worker draw/push ordering is not globally FIFO,
    # so epoch coverage within the first 4 consumed batches is only
    # guaranteed with a single worker
    p = native.Prefetcher(images, labels, batch_size=16, mean=[0.0],
                          std=[1.0], n_threads=1, seed=7)
    assert p.native
    seen = []
    for _ in range(4):  # one epoch = 4 batches of 16
        img, lbl = p.next()
        assert img.shape == (16, h, w, c)
        seen.extend(lbl.tolist())
        # batch content matches source images for its labels
        np.testing.assert_allclose(
            img, images[lbl].astype(np.float32), atol=1e-6)
    # a full epoch visits every sample exactly once
    assert sorted(seen) == list(range(n))
    p.close()


def test_prefetcher_augmentation_changes_images(have_native):
    rng = np.random.RandomState(4)
    images = rng.randint(0, 256, (32, 8, 8, 3), np.uint8)
    labels = np.arange(32, dtype=np.int32)
    p = native.Prefetcher(images, labels, batch_size=8, mean=[0.0] * 3,
                          std=[1.0] * 3, pad=2, hflip=True, n_threads=1,
                          seed=1)
    img, lbl = p.next()
    raw = images[lbl].astype(np.float32)
    assert not np.allclose(img, raw)  # some shift/flip happened
    p.close()


def test_python_fallback_prefetcher():
    # force the fallback path regardless of toolchain
    rng = np.random.RandomState(5)
    images = rng.randint(0, 256, (32, 4, 4), np.uint8)
    labels = np.arange(32, dtype=np.int32)
    import unittest.mock as mock

    with mock.patch.object(native, "_load", return_value=None):
        p = native.Prefetcher(images, labels, batch_size=8, mean=[0.0],
                              std=[1.0], seed=2)
    assert not p.native
    seen = []
    for _ in range(4):
        img, lbl = p.next()
        assert img.shape == (8, 4, 4, 1)
        seen.extend(lbl.tolist())
    assert sorted(seen) == list(range(32))
    p.close()


def test_prefetch_dataset_trains_lenet():
    # the native plane driving real training through the Optimizer API
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import PrefetchDataSet
    from bigdl_tpu.models import lenet
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.RandomState(0)
    # learnable synthetic task: class = quadrant with brightest patch
    n = 256
    images = np.zeros((n, 28, 28, 1), np.uint8)
    labels = np.zeros((n,), np.int32)
    for i in range(n):
        cls = i % 4
        y0, x0 = (cls // 2) * 14, (cls % 2) * 14
        images[i, y0:y0 + 14, x0:x0 + 14, 0] = 200
        images[i] += rng.randint(0, 30, (28, 28, 1)).astype(np.uint8)
        labels[i] = cls

    ds = PrefetchDataSet(images, labels, batch_size=32, mean=[128.0],
                         std=[64.0], n_threads=2, seed=0)
    model = lenet.build(4)
    trained = (Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9))
               .set_end_when(Trigger.max_iteration(40))
               .optimize())
    ds.close()

    test_x = (images[:64].astype(np.float32) - 128.0) / 64.0
    out, _ = trained.apply(trained.variables, jax.numpy.asarray(test_x))
    acc = float((np.asarray(out).argmax(-1) == labels[:64]).mean())
    assert acc > 0.9, acc


# ------------------------------------------------- BDLS record-file plane

def _make_shards(tmp_path, n=48, h=6, w=6, c=3, shards=3):
    from bigdl_tpu.dataset.records import write_shards

    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (n, h, w, c), np.uint8)
    labels = np.arange(n, dtype=np.int32) % 7
    paths = write_shards(images, labels, str(tmp_path), num_shards=shards)
    return images, labels, paths


def test_record_shards_roundtrip_eval(tmp_path):
    from bigdl_tpu.dataset.records import (RecordFileDataSet, read_header)

    images, labels, paths = _make_shards(tmp_path)
    assert len(paths) == 3
    n, h, w, c = read_header(paths[0])
    assert (h, w, c) == (6, 6, 3)

    ds = RecordFileDataSet(str(tmp_path), batch_size=8, mean=[0.0] * 3,
                           std=[1.0] * 3)
    assert ds.size() == 48
    got_img, got_lbl = [], []
    for mb in ds.data(train=False):
        got_img.append(mb.input)
        got_lbl.append(mb.target)
    got_img = np.concatenate(got_img)
    got_lbl = np.concatenate(got_lbl)
    np.testing.assert_array_equal(got_lbl, labels)
    np.testing.assert_allclose(got_img, images.astype(np.float32))
    ds.close()


def test_file_prefetcher_covers_epoch_native(tmp_path, have_native):
    images, labels, paths = _make_shards(tmp_path)
    # one worker: delivery order == take order, so the first 6 batches
    # are exactly one epoch (multi-worker delivery may interleave)
    p = native.FilePrefetcher(paths, batch_size=8, mean=[0.0] * 3,
                              std=[1.0] * 3, n_threads=1, seed=1)
    assert p.native
    assert p.n == 48 and p.shape == (6, 6, 3)
    seen = []
    for _ in range(6):  # one epoch
        img, lbl = p.next()
        assert img.shape == (8, 6, 6, 3)
        seen.extend(lbl.tolist())
    # every record appears exactly its per-epoch count (labels are i%7)
    want = sorted((np.arange(48) % 7).tolist())
    assert sorted(seen) == want
    p.close()


def test_file_prefetcher_python_fallback(tmp_path):
    import unittest.mock as mock

    images, labels, paths = _make_shards(tmp_path)
    with mock.patch.object(native, "_load", return_value=None):
        p = native.FilePrefetcher(paths, batch_size=8, mean=[0.0] * 3,
                                  std=[1.0] * 3, seed=3)
    assert not p.native
    img, lbl = p.next()
    assert img.shape == (8, 6, 6, 3)
    # values must match the source records exactly (mean 0 / std 1)
    for j in range(8):
        match = (images.astype(np.float32) == img[j]).all(axis=(1, 2, 3))
        assert match.any()
    p.close()


def test_file_prefetcher_rejects_garbage(tmp_path):
    bad = tmp_path / "junk.bdls"
    bad.write_bytes(b"NOPE" + b"\0" * 60)
    with pytest.raises(ValueError):
        native.FilePrefetcher([str(bad)], batch_size=4, mean=[0.0],
                              std=[1.0])


def test_record_dataset_trains_through_optimizer(tmp_path):
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import RecordFileDataSet, write_shards
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.RandomState(0)
    n = 192
    images = np.zeros((n, 12, 12, 1), np.uint8)
    labels = np.zeros((n,), np.int32)
    for i in range(n):
        cls = i % 2
        if cls:
            images[i, 3:9, 3:9, 0] = 220
        images[i] += rng.randint(0, 25, (12, 12, 1)).astype(np.uint8)
        labels[i] = cls
    write_shards(images, labels, str(tmp_path), num_shards=2)

    ds = RecordFileDataSet(str(tmp_path), batch_size=32, mean=[64.0],
                           std=[64.0], n_threads=2, seed=0)
    model = nn.Sequential(
        nn.Reshape([144]), nn.Linear(144, 16), nn.ReLU(),
        nn.Linear(16, 2), nn.LogSoftMax())
    trained = (Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(30))
               .optimize())
    # the disk pipeline fed a converging model
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    res = Evaluator(trained).test(ds, [Top1Accuracy()], batch_size=32)
    assert res["Top1Accuracy"].result()[0] > 0.9
    ds.close()


def test_file_prefetcher_u8_mode(tmp_path, have_native):
    images, labels, paths = _make_shards(tmp_path)
    p = native.FilePrefetcher(paths, batch_size=8, mean=[0.0] * 3,
                              std=[1.0] * 3, n_threads=1, seed=1,
                              out_dtype="u8")
    img, lbl = p.next()
    assert img.dtype == np.uint8 and img.shape == (8, 6, 6, 3)
    # raw bytes match source records (no host normalization)
    for j in range(8):
        match = (images == img[j]).all(axis=(1, 2, 3))
        assert match.any()
    p.close()


def test_native_resize_matches_numpy(have_native):
    import unittest.mock as mock

    from bigdl_tpu.dataset import vision

    rng = np.random.RandomState(2)
    img = rng.randint(0, 255, (37, 53, 3)).astype(np.float32)
    fast = native.resize_bilinear(img, 24, 31)
    assert fast is not None and fast.shape == (24, 31, 3)
    with mock.patch.object(native, "resize_bilinear", return_value=None):
        slow = vision._bilinear_resize(img, 24, 31)
    np.testing.assert_allclose(fast, slow, atol=1e-3, rtol=1e-5)
