"""Tensor-parallel sharded serving (ISSUE 10): sharded-vs-unsharded
BITWISE parity, the compile-count guard, paged warm==cold under tp,
serving_params resharding round-trips, and the disaggregated-prefill
handoff path.

The load-bearing claim is the tp_shard_gather construction
(models/transformer.py / serving/tp.py): head-parallel attention is a
pure batch split, the column gemms keep each output element's
contraction extent, and the per-layer collectives concatenate DISJOINT
shards — so a sharded engine's tokens are the unsharded engine's
tokens bit-for-bit, which is what lets failover, prefix reuse and
handoff cross sharding layouts without a tolerance anywhere."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.transformer import build_lm
from bigdl_tpu.parallel import make_mesh
from bigdl_tpu.serving import (EngineRouter, InferenceEngine, Request,
                               gather_serving_params,
                               shard_serving_params, tp_serving_model)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="tp serving tests need the 8-device virtual CPU mesh "
           "(tests/conftest.py forces it)")

# one shared model: every engine (sharded or not) over it shares
# jitted executables per (model-or-wrapper, shapes) — and the wrapper
# itself is memoized per (model, mesh, axis), so the whole module
# compiles each layout once
_LM = None


def _lm():
    global _LM
    if _LM is None:
        _LM = build_lm(vocab_size=50, dim=32, num_heads=4,
                       num_layers=2, max_len=64)
        _LM.build(jax.random.PRNGKey(0))
    return _LM


def _mesh(tp):
    return make_mesh({"model": tp}, devices=jax.devices()[:tp])


def _reqs():
    # greedy + seeded sampling + per-row knobs, both prefill buckets
    return [
        Request(prompt=[1, 2, 3], max_new_tokens=6, seed=1),
        Request(prompt=list(range(1, 11)), max_new_tokens=6,
                temperature=0.9, top_k=5, seed=7),
        Request(prompt=[4, 5], max_new_tokens=5, temperature=1.0,
                top_p=0.9, seed=3),
        Request(prompt=[9] * 7, max_new_tokens=4, temperature=0.7,
                seed=11),
    ]


def _engine(tp=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_buckets", (8, 16))
    if tp:
        kw["tp_mesh"] = _mesh(tp)
    return InferenceEngine(_lm(), **kw)


class TestShardedParity:
    """tp=2 / tp=4 tokens bitwise identical to tp=1 — the acceptance
    bar (greedy AND seeded sampling, slot eviction in between)."""

    def test_tp2_bitwise(self):
        ref = _engine().run(_reqs())
        got = _engine(tp=2).run(_reqs())
        assert [g.tokens for g in got] == [r.tokens for r in ref]
        assert [g.finish_reason for g in got] \
            == [r.finish_reason for r in ref]

    @pytest.mark.slow
    def test_tp4_bitwise(self):
        """tier-2 (ISSUE 10 budget satellite): same construction as
        tp=2 on a bigger mesh — tp=4 bitwise stays pinned on every
        driver run by the tp_serve dryrun leg (greedy + seeded
        sampling + compile counts), and test_tp2_bitwise stays
        tier-1."""
        ref = _engine().run(_reqs())
        got = _engine(tp=4).run(_reqs())
        assert [g.tokens for g in got] == [r.tokens for r in ref]

    @pytest.mark.slow
    def test_tp2_bitwise_bf16_compute(self):
        """bf16 KV compute (cache_dtype=bf16: keys/values stored and
        multiplied in bf16, scores still fp32): the construction is
        dtype-blind, so sharded == unsharded holds bitwise in reduced
        precision too."""
        kw = dict(cache_dtype=jnp.bfloat16)
        ref = _engine(**kw).run(_reqs())
        got = _engine(tp=2, **kw).run(_reqs())
        assert [g.tokens for g in got] == [r.tokens for r in ref]

    def test_prefix_warm_equals_cold_under_tp(self):
        """The paged warm==cold pin (ISSUE 8) re-run under tp=2: a
        cached-prefix admission decodes bitwise identical to its cold
        run, and the cold run is bitwise identical to the unsharded
        cold run — one contract across both features."""
        P = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=5, temperature=0.8, seed=11)
        kw = dict(block_size=4, max_len=32)
        cold_ref = _engine(**kw).run([Request(**P)])[0]
        eng = _engine(tp=2, **kw)
        cold = eng.run([Request(**P)])[0]       # seeds the radix tree
        warm = eng.run([Request(**P)])[0]       # hits it
        assert eng.stats["prefix_hits"] == 1
        assert cold.tokens == cold_ref.tokens
        assert warm.tokens == cold.tokens


class TestCompileContract:
    def test_buckets_plus_one_per_sharded_engine(self):
        """A sharded engine compiles (#buckets used) prefills + 1
        decode; the second traffic wave and a second engine over the
        same (model, mesh, axis) compile NOTHING — the #buckets+1
        contract holds for sharded pools exactly as for plain ones.
        A FRESH model object isolates the count from the module's
        shared (already-compiled) wrapper."""
        m = build_lm(vocab_size=50, dim=32, num_heads=4, num_layers=2,
                     max_len=64)
        m.build(jax.random.PRNGKey(0))
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              tp_mesh=_mesh(2))
        eng.run(_reqs())                        # wave 1: both buckets
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1
        eng.run(_reqs())                        # wave 2: zero compiles
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1
        twin = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                               tp_mesh=_mesh(2))  # memoized wrapper
        twin.run(_reqs()[:1])
        assert twin.stats["prefill_traces"] == 0
        assert twin.stats["decode_traces"] == 0

    def test_wrapper_memoized(self):
        w1 = tp_serving_model(_lm(), _mesh(2))
        w2 = tp_serving_model(_lm(), _mesh(2))
        assert w1 is w2
        assert w1.tp == 2
        # an already-wrapped model passes through on the same layout
        # (a fleet factory reusing engine.model with tp_mesh=) and is
        # refused — not silently double-sharded — on another
        assert tp_serving_model(w1, _mesh(2)) is w1
        with pytest.raises(ValueError, match="already tp-wrapped"):
            tp_serving_model(w1, _mesh(4))

    def test_divisibility_guards(self):
        m = build_lm(vocab_size=16, dim=24, num_heads=3, num_layers=1,
                     max_len=16)
        m.build(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="num_heads"):
            tp_serving_model(m, _mesh(2))

    def test_training_tp_model_refused_unsharded(self):
        """A tp_axis-armed (training-TP) model served WITHOUT tp_mesh
        would trace an unbound all_gather deep in jit — the engine
        must refuse up front and name the fix."""
        from bigdl_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM)

        m = TransformerLM(TransformerConfig(vocab_size=16, max_len=16,
                                            dim=16, num_heads=2,
                                            num_layers=1),
                          tp_axis="model")
        with pytest.raises(ValueError, match="tp_mesh"):
            InferenceEngine(m, slots=1, variables={"params": {}})


class TestResharding:
    def test_round_trip_across_tp_sizes(self):
        """A 'checkpointed' (host-gathered) sharded serving_params
        tree re-places onto any other tp degree with every leaf
        bit-identical — leaves are GLOBAL values, the mesh only places
        them (the zero2 resharding story, serving side)."""
        m = _lm()
        ref = gather_serving_params(
            m.serving_params(m.variables))      # unsharded host form
        sp2 = tp_serving_model(m, _mesh(2)).serving_params(m.variables)
        host = gather_serving_params(sp2)       # tp=2 checkpoint form
        flat_a = jax.tree_util.tree_leaves(ref)
        flat_b = jax.tree_util.tree_leaves(host)
        assert all(np.array_equal(a, b)
                   for a, b in zip(flat_a, flat_b))
        sp4 = shard_serving_params(_mesh(4), host)   # reshard 2 → 4
        flat_c = jax.tree_util.tree_leaves(gather_serving_params(sp4))
        assert all(np.array_equal(a, c)
                   for a, c in zip(flat_a, flat_c))
        # and the resharded tree actually SERVES bitwise-identically
        ref_tok = _engine().run(_reqs()[:2])
        eng = InferenceEngine(tp_serving_model(m, _mesh(4)),
                              variables={"params": sp4},
                              slots=2, prefill_buckets=(8, 16))
        got = eng.run(_reqs()[:2])
        assert [g.tokens for g in got] == [r.tokens for r in ref_tok]


class TestHandoff:
    """Disaggregated prefill (the ISSUE 10 stretch): a prefill-role
    engine exports KV block contents, the router seats them on
    serving engines, tokens stay bitwise identical — including ACROSS
    sharding layouts."""

    def test_handoff_bitwise_and_routed(self):
        ref = _engine().run(_reqs())
        pf = _engine(role="prefill")
        de = _engine()
        router = EngineRouter([de], prefill_engines=[pf],
                              handoff_len=7)
        got = router.run(_reqs())
        assert [g.tokens for g in got] == [r.tokens for r in ref]
        assert all(g.status == "done" for g in got)
        # the two long prompts went through the tier, the short two
        # prefilled in place
        assert router.stats["prefill_dispatched"] == 2
        assert router.stats["handoffs"] == 2
        assert pf.stats["handoffs_out"] == 2
        assert de.stats["handoffs_in"] == 2
        assert pf.stats["decode_steps"] == 0    # prefill tier never decodes

    @pytest.mark.slow
    def test_handoff_across_layouts(self):
        """tp=2 prefill tier feeding an UNSHARDED decode engine:
        prefill bits are layout-invariant, so the handed-off request
        still decodes bit-identically."""
        ref = _engine().run(_reqs())
        pf = _engine(role="prefill", tp=2)
        de = _engine()
        router = EngineRouter([de], prefill_engines=[pf],
                              handoff_len=7)
        got = router.run(_reqs())
        assert [g.tokens for g in got] == [r.tokens for r in ref]

    def test_prefill_engine_seeds_importer_prefix_cache(self):
        """An imported prompt registers in the decode engine's radix
        tree: the SAME prompt resubmitted directly (below the handoff
        threshold path is irrelevant — same engine) hits the prefix
        cache and stays bitwise identical."""
        P = dict(prompt=[5, 9, 3, 7, 2, 8, 4, 6, 1, 3, 9, 2, 7],
                 max_new_tokens=5, temperature=0.8, seed=11)
        kw = dict(block_size=4, max_len=32)
        ref = _engine(**kw).run([Request(**P)])[0]
        pf = _engine(role="prefill", **kw)
        de = _engine(**kw)
        router = EngineRouter([de], prefill_engines=[pf],
                              handoff_len=8)
        first = router.run([Request(**P)])[0]
        assert first.tokens == ref.tokens
        again = de.run([Request(**P)])[0]       # direct, post-handoff
        assert de.stats["prefix_hits"] == 1
        assert again.tokens == ref.tokens
        # and a REPEATED handoff of the same prompt reuses the
        # importer's cached chain instead of re-scattering duplicates
        reused = router.run([Request(**P)])[0]
        assert reused.tokens == ref.tokens
        assert de.stats["prefix_hits"] == 2
        assert de.stats["prefix_blocks_reused"] > 0

    def test_backlog_retries_when_slots_free_mid_round(self):
        """A package that cannot seat THIS round (the only slot busy)
        must retry after the slot frees — not trip run()'s
        stuck-backlog RuntimeError. Regression: seating runs at the
        top of step(), so a slot freed later the same round is only
        seatable next round, and the guard must allow that round."""
        ref = _engine().run(_reqs()[:2])
        pf = _engine(role="prefill")
        de = _engine(slots=1)
        router = EngineRouter([de], prefill_engines=[pf],
                              handoff_len=1)
        got = router.run(_reqs()[:2])
        assert [g.tokens for g in got] == [r.tokens for r in ref]
        assert de.stats["handoffs_in"] == 2

    def test_role_guards(self):
        with pytest.raises(ValueError, match="role"):
            _engine(role="frontend")
        with pytest.raises(ValueError, match="prefill"):
            # watchdog/retry guard the decode dispatch, which a
            # prefill tier never runs — dead knobs are refused
            _engine(role="prefill", step_timeout_s=0.1)
        pf = _engine(role="prefill")
        with pytest.raises(ValueError, match="prefill-role"):
            pf.import_handoff(None)
        with pytest.raises(ValueError, match="EngineRouter"):
            # direct run() would export-and-never-finish: clear error,
            # not a KeyError out of the drain loop
            pf.run(_reqs()[:1])
        with pytest.raises(ValueError, match="role='prefill'"):
            EngineRouter([_engine()], prefill_engines=[_engine()],
                         handoff_len=4)

    def test_mismatched_layout_rejected(self):
        """A package from a different block_size (or model) fleet is a
        CONFIG error — import_handoff must say so, not crash in table
        surgery or silently retry forever."""
        pf = _engine(role="prefill", block_size=4, max_len=32)
        pf.submit(_reqs()[1])
        pf.step()
        (pkg,) = pf.take_handoffs()
        de = _engine(block_size=8, max_len=32)
        with pytest.raises(ValueError, match="block_size"):
            de.import_handoff(pkg)
        # mixed cache dtype would silently CAST — a bit-identity
        # break, not a crash — so it must refuse too
        de16 = _engine(block_size=4, max_len=32,
                       cache_dtype=jnp.bfloat16)
        with pytest.raises(ValueError, match="cache_dtype"):
            de16.import_handoff(pkg)


def test_tp_health_and_gauge():
    """health() reports the shard count; the serving_tp_shards gauge
    and the tp label ride the engine's registry series."""
    from bigdl_tpu import obs

    prev = obs.set_enabled(True)
    obs.reset_all()
    try:
        eng = _engine(tp=2)
        eng.run(_reqs()[:1])
        h = eng.health()
        assert h["tp"] == 2 and h["role"] == "both"
        snap = obs.get_registry().snapshot()["metrics"]
        tp_series = snap["serving_tp_shards"]["series"]
        assert any(s["labels"]["engine"] == eng.obs_name
                   and s["value"] == 2 for s in tp_series)
        req_series = snap["serving_requests_total"]["series"]
        assert all(s["labels"]["tp"] == "2" for s in req_series
                   if s["labels"]["engine"] == eng.obs_name)
    finally:
        obs.reset_all()
        obs.set_enabled(prev)
