"""GPipe pipeline-parallel training step vs the single-device oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.models.transformer import TransformerConfig, TransformerLM
from bigdl_tpu.optim import SGD
from bigdl_tpu.parallel import make_mesh, shard_params, slot_specs_for
from bigdl_tpu.parallel.pipeline import make_pipeline_train_step, pipeline_specs

CFG = TransformerConfig(vocab_size=32, max_len=32, dim=16, num_heads=2,
                        num_layers=4, dropout=0.0)


def _data(b=8, s=12):
    rng = np.random.RandomState(1)
    return (jnp.asarray(rng.randint(0, 32, (b, s)).astype(np.int32)),
            jnp.asarray(rng.randint(0, 32, (b, s)).astype(np.int32)))


def _oracle(params, slots, toks, tgts, lr):
    model = TransformerLM(CFG, name="lm")
    method = SGD(learningrate=lr, momentum=0.9)

    def loss_fn(p):
        logp, _ = model.apply({"params": p, "state": {}}, toks)
        return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], -1))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_p, new_s = method.update(grads, params, slots, jnp.asarray(lr),
                                 jnp.asarray(0))
    return new_p, new_s, loss


@pytest.mark.parametrize("axes,dp", [
    # pipe-only layout: a strict subset of the pipe+data case below —
    # tier-2 (slow) to keep tier-1 margin (ISSUE 8 budget satellite)
    pytest.param({"pipe": 4}, None, marks=pytest.mark.slow),
    ({"pipe": 4, "data": 2}, "data")])
def test_pipeline_matches_single_device(axes, dp):
    n_dev = int(np.prod(list(axes.values())))
    mesh = make_mesh(axes, devices=jax.devices()[:n_dev])
    model = TransformerLM(CFG, name="lm")
    params = model.init(jax.random.PRNGKey(0))["params"]
    method = SGD(learningrate=0.1, momentum=0.9)
    slots = method.init_slots(params)
    toks, tgts = _data()

    ref_p, _, ref_loss = _oracle(params, slots, toks, tgts, 0.1)

    specs = pipeline_specs("pipe")
    step = make_pipeline_train_step(model, method, mesh, pipe_axis="pipe",
                                    dp_axis=dp, microbatches=4)
    pp = shard_params(mesh, specs, params)
    ps = shard_params(mesh, slot_specs_for(method, specs), slots)
    tok_spec = NamedSharding(mesh, P(dp, None) if dp else P())
    new_p, _, loss = step(pp, ps, jax.device_put(toks, tok_spec),
                          jax.device_put(tgts, tok_spec),
                          jnp.asarray(0.1), jnp.asarray(0),
                          jax.random.PRNGKey(0))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(new_p),
            jax.tree_util.tree_leaves_with_path(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=str(ka))


def test_pipeline_rejects_bad_layer_split():
    mesh = make_mesh({"pipe": 8})
    model = TransformerLM(TransformerConfig(num_layers=4, dim=16,
                                            num_heads=2, vocab_size=16),
                          name="lm")
    with pytest.raises(ValueError, match="not divisible"):
        make_pipeline_train_step(model, SGD(), mesh, microbatches=2)


@pytest.mark.slow
def test_interleaved_pipeline_matches_single_device():
    """1F1B-interleaved (virtual stages): same math as the oracle, with
    params in virtual layout; bubble fraction strictly below GPipe's.

    tier-2 (ISSUE 10 budget satellite): the pipeline
    1F1B-interleaved dryrun leg asserts loss==oracle + bubble < GPipe
    on every driver run, and the pipe+data
    test_pipeline_matches_single_device keeps the pipeline step
    tier-1."""
    from bigdl_tpu.parallel.pipeline import (interleaved_bubble_fraction,
                                             to_virtual_layout)

    mesh = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    cfg8 = TransformerConfig(vocab_size=32, max_len=32, dim=16,
                             num_heads=2, num_layers=8, dropout=0.0)
    model = TransformerLM(cfg8, name="lm")  # 4 stages x 2 virtual
    params = model.init(jax.random.PRNGKey(0))["params"]
    method = SGD(learningrate=0.1, momentum=0.9)
    slots = method.init_slots(params)
    toks, tgts = _data()

    def oracle(params, slots):
        def loss_fn(p):
            logp, _ = model.apply({"params": p, "state": {}}, toks)
            return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], -1))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_p, _ = method.update(grads, params, slots, jnp.asarray(0.1),
                                 jnp.asarray(0))
        return new_p, loss

    ref_p, ref_loss = oracle(params, slots)

    specs = pipeline_specs("pipe")
    step = make_pipeline_train_step(model, method, mesh, pipe_axis="pipe",
                                    microbatches=4, virtual_stages=2)
    assert step.bubble_fraction < (4 - 1) / (4 + 4 - 1)  # below GPipe
    assert abs(step.bubble_fraction
               - interleaved_bubble_fraction(4, 4, 2)) < 1e-9

    vp = to_virtual_layout(params, 4, 2)
    vs = to_virtual_layout(slots, 4, 2)
    pp = shard_params(mesh, specs, vp)
    ps = shard_params(mesh, slot_specs_for(method, specs), vs)
    new_p, _, loss = step(pp, ps, toks, tgts, jnp.asarray(0.1),
                          jnp.asarray(0), jax.random.PRNGKey(0))
    new_p = to_virtual_layout(jax.device_get(new_p), 4, 2, inverse=True)

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(new_p),
            jax.tree_util.tree_leaves_with_path(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=str(ka))


def test_virtual_layout_roundtrip_and_bubble_table():
    from bigdl_tpu.parallel.pipeline import (_injection_schedule,
                                             interleaved_bubble_fraction,
                                             to_virtual_layout)

    # GPipe degenerate case: inject 0..m-1, bubble matches closed form
    assert _injection_schedule(4, 6, 1) == [0, 1, 2, 3, 4, 5]
    assert abs(interleaved_bubble_fraction(4, 6, 1) - 3 / 9) < 1e-9
    # v=2 halves warmup: 4 stages x 8 microbatches 0.273 → 0.158
    assert interleaved_bubble_fraction(4, 8, 2) < 0.16 < 0.273

    blocks = {"w": jnp.arange(16.0).reshape(8, 2)}
    tree = {"embed": jnp.ones((3,)), "blocks": blocks}
    vt = to_virtual_layout(tree, 2, 2)
    # device 0 rows = chunks (0,2) → global layers [0,1] and [4,5]
    np.testing.assert_array_equal(
        np.asarray(vt["blocks"]["w"][:4, 0]), [0, 2, 8, 10])
    rt = to_virtual_layout(vt, 2, 2, inverse=True)
    np.testing.assert_array_equal(np.asarray(rt["blocks"]["w"]),
                                  np.asarray(blocks["w"]))
