"""Profiler + logging utils tests (SURVEY.md §5.1/§5.5 equivalents)."""

import logging
import os

import jax
import jax.numpy as jnp

from bigdl_tpu.utils import profiler
from bigdl_tpu.utils.logger_filter import redirect_logs


def test_fenced_timer_measures_completed_work():
    x = jnp.ones((256, 256))

    @jax.jit
    def f(a):
        return a @ a

    with profiler.FencedTimer() as t:
        y = f(x)
        t.fence(y)
    assert t.elapsed is not None and t.elapsed > 0


def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "tb")
    with profiler.trace(logdir):
        with profiler.step(0):
            jnp.asarray([1.0, 2.0]).sum().block_until_ready()
    found = []
    for root, _, files in os.walk(logdir):
        found.extend(files)
    assert found, "trace produced no profile files"


def test_annotate_is_usable():
    with profiler.annotate("region"):
        pass


def test_redirect_logs(tmp_path):
    logpath = str(tmp_path / "bigdl.log")
    redirect_logs(logpath, noisy=("some.noisy.lib",))
    noisy = logging.getLogger("some.noisy.lib")
    noisy.info("hello file")
    with open(logpath) as f:
        content = f.read()
    assert "hello file" in content
    assert noisy.propagate is False
