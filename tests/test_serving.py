"""Serving plane: KV-cache decode parity vs the full-forward oracle,
sampler semantics, continuous-batching equivalence, and the
compile-count guard (zero mid-stream recompiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.transformer import (TransformerConfig, TransformerLM,
                                          build_lm)
from bigdl_tpu.serving import (InferenceEngine, Request, bucket_for,
                               default_buckets, filter_logits,
                               sample_logits)


def _tiny_lm(max_len=64, layers=2):
    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=layers,
                 max_len=max_len)
    m.build(jax.random.PRNGKey(0))
    return m


# one shared model for the engine tests that don't assert compile
# counts: engines over the SAME model share jitted executables
# (engine._prefill_step/_decode_step are static-arg'd on the model),
# so these tests pay the decode/prefill compile once, not per test
_SHARED_LM = None


def _shared_lm():
    global _SHARED_LM
    if _SHARED_LM is None:
        _SHARED_LM = _tiny_lm()
    return _SHARED_LM


class TestDecodeParity:
    """prefill+decode logits must equal the full forward at every
    position (fp32 exact-tolerance; bf16 cache loose)."""

    def test_matches_full_forward_fp32(self):
        m = _tiny_lm()
        v = m.variables
        toks = np.random.RandomState(0).randint(0, 50, (2, 20)).astype(
            np.int32)
        full, _ = m.apply(v, jnp.asarray(toks))        # log-probs

        cache = m.init_cache(2, 64)
        logits, cache = m.prefill(v, jnp.asarray(toks[:, :12]), cache)
        np.testing.assert_allclose(
            np.asarray(jax.nn.log_softmax(logits)),
            np.asarray(full[:, 11]), atol=1e-5)
        for t in range(12, 20):
            logits, cache = m.decode_step(
                v, jnp.asarray(toks[:, t]),
                jnp.full((2,), t, jnp.int32), cache)
            np.testing.assert_allclose(
                np.asarray(jax.nn.log_softmax(logits)),
                np.asarray(full[:, t]), atol=1e-5)

    def test_ragged_prefill_lengths(self):
        """Right-padded prompts: the returned logits are each row's
        last REAL token's, unaffected by the pad tail."""
        m = _tiny_lm()
        v = m.variables
        toks = np.random.RandomState(1).randint(0, 50, (2, 12)).astype(
            np.int32)
        full, _ = m.apply(v, jnp.asarray(toks))
        cache = m.init_cache(2, 64)
        logits, _ = m.prefill(v, jnp.asarray(toks), cache,
                              lengths=jnp.asarray([5, 9], jnp.int32))
        lp = np.asarray(jax.nn.log_softmax(logits))
        np.testing.assert_allclose(lp[0], np.asarray(full[0, 4]),
                                   atol=1e-5)
        np.testing.assert_allclose(lp[1], np.asarray(full[1, 8]),
                                   atol=1e-5)

    @pytest.mark.slow
    def test_bf16_cache_loose(self):
        m = _tiny_lm()
        v = m.variables
        toks = np.random.RandomState(2).randint(0, 50, (1, 10)).astype(
            np.int32)
        full, _ = m.apply(v, jnp.asarray(toks))
        cache = m.init_cache(1, 64, dtype=jnp.bfloat16)
        assert cache[0]["k"].dtype == jnp.bfloat16
        _, cache = m.prefill(v, jnp.asarray(toks[:, :6]), cache)
        for t in range(6, 10):
            logits, cache = m.decode_step(
                v, jnp.asarray(toks[:, t]),
                jnp.full((1,), t, jnp.int32), cache)
            np.testing.assert_allclose(
                np.asarray(jax.nn.log_softmax(logits)),
                np.asarray(full[:, t]), atol=0.1)

    def test_serving_params_layout_identical(self):
        """The per-layer serving weight layout is a pure repack: prefill
        and decode emit bit-identical logits vs the stacked layout."""
        m = _tiny_lm()
        v = m.variables
        sp = m.serving_params(v)
        assert isinstance(sp["blocks"], tuple)
        assert m.serving_params({"params": sp}) is sp   # idempotent
        toks = np.random.RandomState(3).randint(0, 50, (2, 10)).astype(
            np.int32)
        l1, c1 = m.prefill(v, jnp.asarray(toks), m.init_cache(2, 64))
        l2, c2 = m.prefill({"params": sp}, jnp.asarray(toks),
                           m.init_cache(2, 64))
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        pos = jnp.full((2,), 10, jnp.int32)
        nxt = jnp.asarray(toks[:, -1])
        d1, _ = m.decode_step(v, nxt, pos, c1)
        d2, _ = m.decode_step({"params": sp}, nxt, pos, c2)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

    def test_mha_decode_parity(self):
        """MultiHeadAttention.apply_prefill/apply_decode vs apply."""
        from bigdl_tpu.nn.attention import MultiHeadAttention

        mha = MultiHeadAttention(16, 2, causal=True)
        v = mha.build(jax.random.PRNGKey(0)).variables
        x = jnp.asarray(np.random.RandomState(0).rand(2, 9, 16),
                        jnp.float32)
        ref, _ = mha.apply(v, x)
        cache = mha.init_cache(2, 12)
        y, cache = mha.apply_prefill(v, x[:, :4], cache)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref[:, :4]),
                                   atol=1e-5)
        for t in range(4, 9):
            y, cache = mha.apply_decode(v, x[:, t], cache,
                                        jnp.full((2,), t, jnp.int32))
            np.testing.assert_allclose(np.asarray(y),
                                       np.asarray(ref[:, t]), atol=1e-5)

    def test_guards(self):
        from bigdl_tpu.nn.attention import MultiHeadAttention

        m_sp = TransformerLM(TransformerConfig(vocab_size=8, dim=16,
                                               num_heads=2, num_layers=1,
                                               max_len=8), sp_axis="seq")
        with pytest.raises(NotImplementedError, match="single-mesh"):
            m_sp.init_cache(1, 8)
        m_moe = TransformerLM(TransformerConfig(
            vocab_size=8, dim=16, num_heads=2, num_layers=1, max_len=8,
            moe_experts=2))
        with pytest.raises(NotImplementedError, match="MoE"):
            m_moe.init_cache(1, 8)
        mha = MultiHeadAttention(16, 2, causal=False)
        mha.build(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="causal"):
            mha.apply_decode(mha.variables, jnp.zeros((1, 16)),
                             mha.init_cache(1, 8),
                             jnp.zeros((1,), jnp.int32))
        m = _tiny_lm(max_len=16)
        with pytest.raises(ValueError, match="max_len"):
            m.init_cache(1, 32)


class TestSampler:
    def _keys(self, n, seed=0):
        return jax.vmap(jax.random.PRNGKey)(
            jnp.arange(seed, seed + n, dtype=jnp.int32))

    def test_greedy_is_argmax(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(8, 20),
                             jnp.float32)
        out = sample_logits(logits, self._keys(8),
                            jnp.zeros((8,)), jnp.zeros((8,), jnp.int32),
                            jnp.ones((8,)))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.argmax(np.asarray(logits), -1))

    def test_top_k_support(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(64, 20), jnp.float32)
        out = np.asarray(sample_logits(
            logits, self._keys(64, 7), jnp.full((64,), 1.0),
            jnp.full((64,), 3, jnp.int32), jnp.ones((64,))))
        top3 = np.argsort(np.asarray(logits), axis=-1)[:, -3:]
        assert all(out[i] in top3[i] for i in range(64))

    def test_top_p_support(self):
        # probs [0.6, 0.3, 0.06, 0.04]: nucleus at 0.7 = {0, 1}
        p = np.asarray([0.6, 0.3, 0.06, 0.04], np.float32)
        logits = jnp.asarray(np.tile(np.log(p), (200, 1)))
        out = np.asarray(sample_logits(
            logits, self._keys(200, 11), jnp.ones((200,)),
            jnp.zeros((200,), jnp.int32), jnp.full((200,), 0.7)))
        assert set(out.tolist()) <= {0, 1}
        # and top_p=0.5 keeps only the argmax
        out = np.asarray(sample_logits(
            logits, self._keys(200, 23), jnp.ones((200,)),
            jnp.zeros((200,), jnp.int32), jnp.full((200,), 0.5)))
        assert set(out.tolist()) == {0}
        # degenerate top_p<=0 still keeps the top-1 (never all-masked
        # → uniform-noise sampling)
        out = np.asarray(sample_logits(
            logits[:8], self._keys(8, 31), jnp.ones((8,)),
            jnp.zeros((8,), jnp.int32), jnp.zeros((8,))))
        assert set(out.tolist()) == {0}

    def test_distribution_sane(self):
        p = np.asarray([0.5, 0.25, 0.15, 0.10], np.float32)
        n = 4000
        logits = jnp.asarray(np.tile(np.log(p), (n, 1)))
        out = np.asarray(sample_logits(
            logits, self._keys(n, 100), jnp.ones((n,)),
            jnp.zeros((n,), jnp.int32), jnp.ones((n,))))
        freq = np.bincount(out, minlength=4) / n
        np.testing.assert_allclose(freq, p, atol=0.04)

    def test_per_row_knobs_in_one_batch(self):
        """Greedy and filtered rows coexist in one call — the
        continuous-batching requirement."""
        rng = np.random.RandomState(3)
        logits = jnp.asarray(rng.randn(4, 10), jnp.float32)
        out = np.asarray(sample_logits(
            logits, self._keys(4, 40),
            jnp.asarray([0.0, 1.0, 0.0, 1.0]),
            jnp.asarray([0, 2, 0, 0], jnp.int32),
            jnp.asarray([1.0, 1.0, 1.0, 0.9])))
        am = np.argmax(np.asarray(logits), -1)
        assert out[0] == am[0] and out[2] == am[2]
        top2 = np.argsort(np.asarray(logits)[1])[-2:]
        assert out[1] in top2

    def test_filter_logits_masks(self):
        logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0]])
        f = np.asarray(filter_logits(logits, jnp.ones((1,)),
                                     jnp.asarray([2], jnp.int32),
                                     jnp.ones((1,))))
        assert (f[0, 2:] < -1e29).all() and (f[0, :2] > -1e29).all()

    def test_filter_support_never_empty(self):
        """Regression: the top-p cutoff is a logit threshold (exact),
        not a prob threshold — comparing two independently computed
        softmaxes disagrees by ~1 ULP and emptied the support for
        confident rows (argmax then became Gumbel-uniform noise)."""
        rng = np.random.RandomState(9)
        logits = jnp.asarray(rng.randn(128, 1000) * 3, jnp.float32)
        f = np.asarray(filter_logits(
            logits, jnp.full((128,), 0.7),
            jnp.zeros((128,), jnp.int32), jnp.full((128,), 0.5)))
        am = np.argmax(np.asarray(logits), -1)
        assert all(f[i, am[i]] > -1e29 for i in range(128))


class TestEngine:
    def test_matches_run_alone(self):
        """Slot eviction/reuse is invisible: a request generates the
        same tokens batched through 2 slots (5 requests → slots are
        evicted and reused) as it does alone (one at a time through a
        single shared engine — exercising slot reuse there too)."""
        m = _shared_lm()
        reqs = [
            Request(prompt=[1, 2, 3], max_new_tokens=6),
            Request(prompt=list(range(1, 11)), max_new_tokens=8,
                    temperature=0.9, top_k=5, seed=7),
            Request(prompt=[4, 5], max_new_tokens=5, temperature=1.0,
                    top_p=0.9, seed=3),
            Request(prompt=[9] * 7, max_new_tokens=4),
            Request(prompt=[3, 1, 4, 1, 5], max_new_tokens=7,
                    temperature=0.7, seed=11),
        ]
        joint = InferenceEngine(m, slots=2, prefill_buckets=(8, 16))
        got = joint.run([Request(**vars(r)) for r in reqs])
        alone = InferenceEngine(m, slots=2, prefill_buckets=(8, 16))
        for r, res in zip(reqs, got):
            ref = alone.run([Request(**vars(r))])[0]
            assert res.tokens == ref.tokens, (res, ref)
            assert res.finish_reason == ref.finish_reason

    def test_greedy_matches_full_forward_oracle(self):
        """Teacher-forcing check: every greedily generated token must
        be the argmax of ONE full forward over prompt+generation at
        the position that produced it (a single compile, unlike
        re-forwarding per step)."""
        m = _shared_lm()
        v = m.variables
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8,))
        res = eng.run([Request(prompt=[1, 2, 3], max_new_tokens=6)])[0]
        full = [1, 2, 3] + res.tokens
        lp, _ = m.apply(v, jnp.asarray([full]))
        am = np.asarray(jnp.argmax(lp[0], -1))
        assert res.tokens == [int(am[i]) for i in range(2, 8)]

    def test_stop_ids(self):
        m = _shared_lm()
        kw = dict(prompt=[1, 2, 3], max_new_tokens=8, temperature=0.9,
                  seed=5)
        eng = InferenceEngine(m, slots=1, prefill_buckets=(8,))
        free = eng.run([Request(**kw)])[0]
        assert len(free.tokens) == 8
        stop = free.tokens[2]
        cut = free.tokens.index(stop)   # first occurrence ends the run
        # same engine (same executables, slot reused); per-request PRNG
        # streams make the rerun identical until the stop hits
        res = eng.run([Request(**kw, stop_ids=(stop,))])[0]
        assert res.finish_reason == "stop_id"
        assert res.tokens == free.tokens[:cut]

    def test_cache_full(self):
        m = _tiny_lm(max_len=16)
        eng = InferenceEngine(m, slots=1, prefill_buckets=(8,))
        res = eng.run([Request(prompt=[1] * 6, max_new_tokens=100)])[0]
        assert res.finish_reason == "cache_full"
        # prompt occupies [0,6); writes advance to position 15 → 11
        # generated tokens before the clock would overflow
        assert len(res.tokens) == 11

    def test_compile_count_guard(self):
        """Ragged simulated traffic — varying lengths, mid-stream
        arrivals, slot eviction/reuse, AND the reliability knobs
        (priorities, deadlines, bounded queue, a poison injection) —
        compiles exactly (#buckets used) prefills + 1 decode, and a
        second traffic wave compiles NOTHING. The reliability layer is
        host-side bookkeeping plus (B,) operands by construction, so
        arming any of it must never retrace."""
        from bigdl_tpu.utils import faults

        m = _tiny_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8, 16),
                              max_queue=8,
                              overload_policy="shed-oldest")
        rng = np.random.RandomState(0)
        for n in (3, 10, 6):
            eng.submit(Request(prompt=list(rng.randint(1, 50, n)),
                               max_new_tokens=int(rng.randint(2, 7)),
                               priority=int(n), deadline_s=3600.0))
        for _ in range(4):                      # partial drain
            eng.step()
        for n in (12, 2, 8):                    # mid-stream arrivals
            eng.submit(Request(prompt=list(rng.randint(1, 50, n)),
                               max_new_tokens=int(rng.randint(2, 7)),
                               temperature=0.8, seed=int(n),
                               max_queue_wait_s=3600.0))
        eng.run()
        assert eng.stats["requests_done"] == 6
        # lengths 3,6,2 → bucket 8; 10,12,8 → bucket 8 or 16: exactly
        # the two buckets were used
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1
        # second wave: every shape already compiled — including a
        # serve_nan poison injection (the poison operand is (B,))
        faults.set_plan(faults.FaultPlan(
            f"serve_nan@{eng.stats['decode_steps'] + 1}"))
        try:
            for n in (5, 11, 7, 16):
                eng.submit(Request(prompt=list(rng.randint(1, 50, n)),
                                   max_new_tokens=3))
            eng.run()
        finally:
            faults.set_plan(None)
        assert eng.stats["prefill_traces"] == 2
        assert eng.stats["decode_traces"] == 1
        assert eng.stats["poisoned"] == 1
        assert eng.stats["requests_done"] == 9   # 10th evicted poisoned

    def test_poisoned_cobatch_isolation(self):
        """Batcher equivalence under poison: a serve_nan-injected row
        evicts ONLY its own request (status 'poisoned'); the co-batched
        request's tokens stay bit-identical to running it alone."""
        from bigdl_tpu.utils import faults

        m = _shared_lm()
        vic = dict(prompt=[1, 2, 3], max_new_tokens=6, temperature=0.8,
                   seed=5)
        oth = dict(prompt=[4, 5, 6], max_new_tokens=6, temperature=0.9,
                   seed=9)
        alone = InferenceEngine(m, slots=2, prefill_buckets=(8,)).run(
            [Request(**oth)])[0]
        faults.set_plan(faults.FaultPlan("serve_nan@1"))
        try:
            eng = InferenceEngine(m, slots=2, prefill_buckets=(8,))
            got_v, got_o = eng.run([Request(**vic), Request(**oth)])
        finally:
            faults.set_plan(None)
        assert got_v.status == "poisoned" and len(got_v.tokens) == 1
        assert got_o.status == "done"
        assert got_o.tokens == alone.tokens
        assert eng.stats["poisoned"] == 1

    def test_submit_rejects_oversize(self):
        m = _shared_lm()
        eng = InferenceEngine(m, slots=1, prefill_buckets=(8,))
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit(Request(prompt=[1] * 9))
        with pytest.raises(ValueError, match="empty"):
            eng.submit(Request(prompt=[]))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(Request(prompt=[1], max_new_tokens=0))
        eng.submit(Request(prompt=[1], id=7))
        with pytest.raises(ValueError, match="in flight"):
            eng.submit(Request(prompt=[2], id=7))

    def test_submit_rejects_duplicate_id_in_occupied_slot(self):
        """The duplicate-id guard must scan OCCUPIED SLOTS too, not
        just the queue — a resubmitted id of a request that already
        left the queue for a slot is still in flight."""
        m = _shared_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8,))
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=4, id=42))
        eng.step()                    # admits 42 into a slot
        assert [r.id for r in eng._req if r is not None] == [42]
        with pytest.raises(ValueError, match="in flight"):
            eng.submit(Request(prompt=[4, 5], id=42))
        eng.run()

    def test_auto_ids_skip_user_claimed_values(self):
        """Auto-assignment must skip over ids the user already claimed
        explicitly — never error on (or duplicate) its own counter."""
        m = _shared_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8,))
        eng.submit(Request(prompt=[1, 2], max_new_tokens=2, id=0))
        auto = eng.submit(Request(prompt=[3, 4], max_new_tokens=2))
        assert auto != 0
        eng.run()

    def test_presubmitted_results_not_dropped(self):
        """A request queued via submit() before run(other_requests)
        finishes during the run and stays retrievable in
        engine.completed — never silently discarded."""
        m = _shared_lm()
        eng = InferenceEngine(m, slots=2, prefill_buckets=(8,))
        early_id = eng.submit(Request(prompt=[1, 2], max_new_tokens=3))
        got = eng.run([Request(prompt=[3, 4], max_new_tokens=3)])
        assert len(got) == 1 and got[0].id != early_id
        assert early_id in eng.completed
        assert len(eng.completed[early_id].tokens) == 3


def test_bucketing_helpers():
    assert default_buckets(64) == (16, 32, 64)
    assert default_buckets(48) == (16, 32, 48)
    assert bucket_for(17, (16, 32, 64)) == 32
    assert bucket_for(16, (16, 32, 64)) == 16
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(65, (16, 32, 64))
