"""Mixed-precision policy tests (bf16 compute / fp32 master weights —
the TPU-first counterpart of the reference's FP16CompressedTensor wire
compression, see utils/precision.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample
from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger, Evaluator
from bigdl_tpu.utils.precision import DEFAULT_MIXED, Policy, cast_floats


def test_cast_floats_leaves_ints_alone():
    tree = {"w": jnp.ones((2, 2), jnp.float32),
            "idx": jnp.zeros((3,), jnp.int32)}
    out = cast_floats(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["idx"].dtype == jnp.int32


def test_policy_roundtrip():
    p = Policy()
    tree = {"a": jnp.ones((4,), jnp.float32)}
    c = p.cast_to_compute(tree)
    assert c["a"].dtype == jnp.bfloat16
    back = p.cast_to_param(c)
    assert back["a"].dtype == jnp.float32


def test_grads_through_cast_are_fp32():
    lin = nn.Linear(4, 2)
    v = lin.init(jax.random.PRNGKey(0))
    x = jnp.ones((3, 4))

    def loss(p):
        p16 = cast_floats(p, jnp.bfloat16)
        y, _ = lin.apply({"params": p16, "state": {}},
                         jnp.asarray(x, jnp.bfloat16))
        return jnp.sum(jnp.asarray(y, jnp.float32) ** 2)

    g = jax.grad(loss)(v["params"])
    assert g["weight"].dtype == jnp.float32
    assert float(jnp.abs(g["weight"]).sum()) > 0


def test_training_converges_under_bf16():
    """Tiny LeNet-ish problem must converge with set_precision('bf16')."""
    rng = np.random.RandomState(0)
    ys = rng.randint(0, 2, 256).astype(np.int32)
    # class-separated intensities: class 0 dim, class 1 bright
    xs = (rng.rand(256, 8, 8, 1) * 0.4 +
          ys[:, None, None, None] * 0.6).astype(np.float32)
    samples = [Sample(x, int(y)) for x, y in zip(xs, ys)]
    train = DataSet.array(samples[:192])
    val = DataSet.array(samples[192:])

    model = nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([4 * 3 * 3]),
        nn.Linear(4 * 3 * 3, 2),
        nn.LogSoftMax(),
    )
    opt = (Optimizer(model, train, nn.ClassNLLCriterion(), batch_size=64)
           .set_optim_method(SGD(learningrate=0.5))
           .set_end_when(Trigger.max_epoch(15))
           .set_precision("bf16"))
    trained = opt.optimize()

    # master weights stay fp32
    for _, p in trained.parameters():
        assert p.dtype == jnp.float32
    res = Evaluator(trained).test(val, [Top1Accuracy()], batch_size=64)
    acc = list(res.values())[0].result()[0]
    assert acc > 0.9, f"bf16 training failed to converge: {acc}"
