"""Estimator/pipeline API tests (reference: DLEstimatorSpec, DLClassifierSpec
in the org.apache.spark.ml test tree)."""

import jax
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.ml import DLClassifier, DLEstimator
from bigdl_tpu.optim import Adam, Trigger

KEY = jax.random.PRNGKey(0)


def _toy_df(n=64, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    return {"features": list(X), "label": list(y)}, X, y


class TestDLClassifier:
    def test_fit_transform(self):
        df, X, y = _toy_df(128)
        model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2),
                              nn.LogSoftMax()).build(KEY)
        clf = (DLClassifier(model, nn.ClassNLLCriterion(), [4])
               .set_batch_size(32)
               .set_optim_method(Adam(1e-2))
               .set_max_epoch(30))
        fitted = clf.fit(df)
        out = fitted.transform(df)
        preds = np.asarray(out["prediction"])
        acc = (preds == y).mean()
        assert acc > 0.9, f"classifier failed to fit: {acc}"

    def test_pandas_roundtrip(self):
        pd = pytest.importorskip("pandas")
        df_dict, X, y = _toy_df(64)
        df = pd.DataFrame({"features": df_dict["features"],
                           "label": df_dict["label"]})
        model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2),
                              nn.LogSoftMax()).build(KEY)
        clf = (DLClassifier(model, nn.ClassNLLCriterion(), [4])
               .set_batch_size(32).set_max_epoch(2))
        out = clf.fit(df).transform(df)
        assert "prediction" in out.columns
        assert len(out) == 64


class TestDLEstimator:
    def test_regression_fit(self):
        rng = np.random.RandomState(1)
        X = rng.randn(96, 3).astype(np.float32)
        w_true = np.asarray([1.0, -2.0, 0.5], np.float32)
        y = X @ w_true
        df = {"features": list(X), "label": list(y[:, None])}
        model = nn.Sequential(nn.Linear(3, 1)).build(KEY)
        est = (DLEstimator(model, nn.MSECriterion(), [3], [1])
               .set_batch_size(32)
               .set_optim_method(Adam(5e-2))
               .set_max_epoch(40))
        fitted = est.fit(df)
        out = fitted.transform(df)
        preds = np.asarray(out["prediction"]).reshape(-1)
        mse = float(((preds - y) ** 2).mean())
        assert mse < 0.05, f"estimator failed to fit: mse={mse}"

    def test_transfer_learning_shape(self):
        """The reference's MLPipeline transfer demo: freeze-ish a trained
        body, fit a new head via the estimator (functionally: fit works on
        a composed Sequential)."""
        body = nn.Sequential(nn.Linear(4, 8), nn.ReLU()).build(KEY)
        head = nn.Linear(8, 2)
        full = nn.Sequential(body, head, nn.LogSoftMax()).build(KEY)
        df, X, y = _toy_df(32)
        clf = (DLClassifier(full, nn.ClassNLLCriterion(), [4])
               .set_batch_size(16).set_max_epoch(2))
        out = clf.fit(df).transform(df)
        assert len(out["prediction"]) == 32
