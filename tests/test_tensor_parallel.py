"""dp×tp×sp transformer training step vs the single-device oracle on the
8-device CPU mesh (2 data × 2 model × 2 seq)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.models.transformer import TransformerConfig, TransformerLM
from bigdl_tpu.optim import SGD
from bigdl_tpu.parallel import (
    make_mesh, make_transformer_train_step, shard_params, slot_specs_for,
    transformer_tp_specs,
)

CFG = TransformerConfig(vocab_size=32, max_len=32, dim=16, num_heads=4,
                        num_layers=2, dropout=0.0)


def _data(b=4, s=16):
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 32, (b, s)).astype(np.int32)
    tgts = rng.randint(0, 32, (b, s)).astype(np.int32)
    return jnp.asarray(toks), jnp.asarray(tgts)


def _single_device_step(params, slots, toks, tgts, method, lr):
    model = TransformerLM(CFG, name="lm")

    def loss_fn(p):
        logp, _ = model.apply({"params": p, "state": {}}, toks,
                              training=True, rng=jax.random.PRNGKey(9))
        return jnp.mean(-jnp.take_along_axis(logp, tgts[..., None], -1))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_p, new_s = method.update(grads, params, slots,
                                 jnp.asarray(lr), jnp.asarray(0))
    return new_p, new_s, loss


@pytest.mark.parametrize("sp_mode", [
    # ring-mode gradients keep their focused tier-1 oracle in
    # test_ring_attention[ring]; this 10 s end-to-end variant is
    # tier-2 — zigzag (the mode with no other step-level coverage)
    # stays tier-1 (ISSUE 8 budget satellite)
    pytest.param("ring", marks=pytest.mark.slow), "zigzag"])
def test_dp_tp_sp_step_matches_single_device(sp_mode):
    """dp x tp x sp step == single-device oracle at loss AND parameter
    level; zigzag (balanced causal ring + permuted feed) must agree
    exactly — the LM loss is a mean over positions, so the zigzag
    permutation cancels."""
    mesh = make_mesh({"data": 2, "model": 2, "seq": 2})
    model = TransformerLM(CFG, tp_axis="model", sp_axis="seq",
                          sp_mode=sp_mode, name="lm")
    variables = TransformerLM(CFG, name="lm").init(jax.random.PRNGKey(0))
    params = variables["params"]
    method = SGD(learningrate=0.1, momentum=0.9)
    slots = method.init_slots(params)
    toks, tgts = _data()

    # oracle
    ref_p, ref_s, ref_loss = _single_device_step(
        params, slots, toks, tgts, SGD(learningrate=0.1, momentum=0.9),
        0.1)

    specs = transformer_tp_specs("model")
    step = make_transformer_train_step(model, method, mesh,
                                       dp_axis="data", tp_axis="model",
                                       sp_axis="seq")
    sp_params = shard_params(mesh, specs, params)
    sp_slots = shard_params(mesh, slot_specs_for(method, specs), slots)
    tok_sharding = NamedSharding(mesh, P("data", "seq"))
    new_p, new_s, loss = step(
        sp_params, sp_slots,
        jax.device_put(toks, tok_sharding),
        jax.device_put(tgts, tok_sharding),
        jnp.asarray(0.1), jnp.asarray(0), jax.random.PRNGKey(9))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(new_p),
            jax.tree_util.tree_leaves_with_path(ref_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=str(ka))


def test_loss_decreases_over_steps():
    mesh = make_mesh({"data": 2, "model": 2, "seq": 2})
    model = TransformerLM(CFG, tp_axis="model", sp_axis="seq", name="lm")
    params = TransformerLM(CFG, name="lm").init(
        jax.random.PRNGKey(0))["params"]
    method = SGD(learningrate=0.3)
    specs = transformer_tp_specs("model")
    step = make_transformer_train_step(model, method, mesh,
                                       dp_axis="data", tp_axis="model",
                                       sp_axis="seq")
    sp_params = shard_params(mesh, specs, params)
    sp_slots = shard_params(mesh, slot_specs_for(method, specs),
                            method.init_slots(params))
    toks, tgts = _data()
    tok_sharding = NamedSharding(mesh, P("data", "seq"))
    toks = jax.device_put(toks, tok_sharding)
    tgts = jax.device_put(tgts, tok_sharding)

    losses = []
    for i in range(30):
        sp_params, sp_slots, loss = step(
            sp_params, sp_slots, toks, tgts, jnp.asarray(0.3),
            jnp.asarray(i), jax.random.PRNGKey(0))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_tp_axis_mismatch_rejected():
    mesh = make_mesh({"data": 8})
    model = TransformerLM(CFG, name="lm")  # no tp_axis
    try:
        make_transformer_train_step(model, SGD(), mesh, dp_axis="data",
                                    tp_axis="model", sp_axis=None)
        assert False, "expected ValueError"
    except ValueError as e:
        assert "tp_axis" in str(e)
