"""Multi-process multi-host smoke (SURVEY §4 "Distributed-without-a-
cluster"): 2 real jax.distributed processes × 4 virtual CPU devices run
DP/ZeRO-1 training through Engine.init_distributed + DistriOptimizer
with per-host sharded data, checkpoint, and resume. The launcher child
processes build their own CPU-pinned jax, so this test just drives
scripts/multihost_smoke.py and asserts its artifact."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_dp_training_with_checkpoint_resume():
    env = dict(os.environ)
    # children set their own XLA flags; keep the parent's pytest flags out
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "multihost_smoke.py"),
         "--legs", "smoke"],  # kill_resume leg (~4 min) runs out of band;
        # its last artifact section is asserted below if present
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(os.path.join(REPO, "MULTIHOST.json")) as f:
        result = json.load(f)
    assert result["ok"] is True
    assert result["processes"] == 2
    assert result["return_codes"] == [0, 0]
    # replicated parameter plane: all processes ended bit-identical
    assert len(set(result["digests"])) == 1
    # failure-recovery leg (scripts/multihost_smoke.py --legs kill_resume):
    # one worker SIGKILLed mid-training, full restart + resume must end
    # bit-identical to the uninterrupted run
    if "kill_resume" in result:
        assert result["kill_resume"]["ok"] is True
        assert result["kill_resume"]["bit_identical"] is True
