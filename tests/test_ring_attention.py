"""Sequence-parallel attention vs the single-device oracle, on the
8-device CPU mesh (the reference tests distributed paths on Spark
local[N]; same idea — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.ops.flash_attention import attention_reference
from bigdl_tpu.parallel import make_mesh, make_ring_attention


def _qkv(rng, b=2, h=8, s=64, d=8):
    kq, kk, kv = jax.random.split(rng, 3)
    return (jax.random.normal(kq, (b, h, s, d)),
            jax.random.normal(kk, (b, h, s, d)),
            jax.random.normal(kv, (b, h, s, d)))


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"seq": 8})


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(mesh, mode, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = attention_reference(q, k, v, causal=causal)
    fn = make_ring_attention(mesh, causal=causal, mode=mode)
    spec = NamedSharding(mesh, P(None, None, "seq", None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("mode", [
    "ring", "ulysses",
    # zigzag grads also ride the end-to-end step-parity check in
    # test_tensor_parallel[zigzag] every tier-1 run — this focused
    # 16 s oracle is tier-2 (ISSUE 8 budget satellite)
    pytest.param("zigzag", marks=pytest.mark.slow)])
def test_grads_match_full_attention(mesh, mode):
    q, k, v = _qkv(jax.random.PRNGKey(1), s=32)
    fn = make_ring_attention(mesh, causal=True, mode=mode)
    spec = NamedSharding(mesh, P(None, None, "seq", None))

    def loss_sp(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gs = jax.grad(loss_sp, argnums=(0, 1, 2))(
        *(jax.device_put(x, spec) for x in (q, k, v)))
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_long_context_scales(mesh):
    # sequence 8x the per-device chunk; just exercise a longer shape
    q, k, v = _qkv(jax.random.PRNGKey(2), b=1, h=2, s=256, d=16)
    ref = attention_reference(q, k, v, causal=True)
    fn = make_ring_attention(mesh, causal=True, mode="ring")
    spec = NamedSharding(mesh, P(None, None, "seq", None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_bad_heads(mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3), h=4)  # 4 heads on 8 devices
    fn = make_ring_attention(mesh, mode="ulysses")
    spec = NamedSharding(mesh, P(None, None, "seq", None))
    with pytest.raises(ValueError, match="not divisible"):
        fn(*(jax.device_put(x, spec) for x in (q, k, v)))


def test_zigzag_matches_full_attention(mesh):
    """Load-balanced causal ring == dense causal oracle (VERDICT r3
    weak 6: half the ring idled on causal masks with contiguous
    chunks)."""
    q, k, v = _qkv(jax.random.PRNGKey(3))
    ref = attention_reference(q, k, v, causal=True)
    fn = make_ring_attention(mesh, causal=True, mode="zigzag")
    spec = NamedSharding(mesh, P(None, None, "seq", None))
    out = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_requires_causal(mesh):
    with pytest.raises(ValueError, match="causal"):
        make_ring_attention(mesh, causal=False, mode="zigzag")


def test_zigzag_positions_cover_and_balance():
    from bigdl_tpu.parallel.ring_attention import zigzag_positions

    n, s_local = 4, 16
    pos = zigzag_positions(n, s_local)
    allpos = np.sort(np.concatenate([np.asarray(p) for p in pos]))
    np.testing.assert_array_equal(allpos, np.arange(n * s_local))
    # causal work (number of visible kv rows summed over the device's
    # q rows) is equal across devices
    work = [int(sum(p + 1 for p in np.asarray(dev))) for dev in pos]
    assert len(set(work)) == 1, work


def test_zigzag_rejects_indivisible_sequence(mesh):
    fn = make_ring_attention(mesh, causal=True, mode="zigzag")
    q = jnp.zeros((1, 2, 12, 8))  # 12 not divisible by 2*8
    with pytest.raises(ValueError, match="divisible"):
        fn(q, q, q)
