"""TransformerLM tests: shapes, causality, convergence smoke, and
sequence-parallel apply on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.models.transformer import TransformerConfig, TransformerLM, build_lm
from bigdl_tpu.parallel import make_mesh

from bigdl_tpu.parallel.shard_map_compat import shard_map


def test_forward_shape():
    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=2,
                 max_len=64)
    variables = m.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 50)
    out, _ = m.apply(variables, toks)
    assert out.shape == (2, 16, 50)
    # log-probs sum to one
    np.testing.assert_allclose(np.asarray(jnp.exp(out).sum(-1)), 1.0,
                               atol=1e-5)


def test_causality():
    m = build_lm(vocab_size=50, dim=32, num_heads=2, num_layers=2,
                 max_len=64)
    variables = m.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 50)
    out1, _ = m.apply(variables, toks)
    toks2 = toks.at[:, 8:].set(0)
    out2, _ = m.apply(variables, toks2)
    np.testing.assert_allclose(np.asarray(out1[:, :8]),
                               np.asarray(out2[:, :8]), atol=1e-5)


def test_converges_on_repetition():
    # learn to predict a repeating token pattern
    m = build_lm(vocab_size=8, dim=32, num_heads=2, num_layers=2,
                 max_len=32)
    variables = m.init(jax.random.PRNGKey(0))
    pattern = jnp.asarray([[1, 2, 3, 4] * 8], jnp.int32)
    x, y = pattern[:, :-1], pattern[:, 1:]

    params = variables["params"]

    @jax.jit
    def step(params):
        def loss_fn(p):
            out, _ = m.apply({"params": p, "state": {}}, x)
            return -jnp.mean(jnp.take_along_axis(out, y[..., None],
                                                 axis=-1))
        loss, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, g), loss

    for _ in range(60):
        params, loss = step(params)
    assert float(loss) < 0.1, float(loss)


def test_sequence_parallel_matches_single_device():
    mesh = make_mesh({"seq": 8})
    cfg = TransformerConfig(vocab_size=40, max_len=64, dim=32, num_heads=2,
                            num_layers=2)
    m_single = TransformerLM(cfg, name="lm")
    m_sp = TransformerLM(cfg, sp_axis="seq", name="lm")
    variables = m_single.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 40)

    ref, _ = m_single.apply(variables, toks)

    def body(params, toks):
        out, _ = m_sp.apply({"params": params, "state": {}}, toks)
        return out

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq", None),
        check_vma=False,
    ))
    out = fn(variables["params"],
             jax.device_put(toks, NamedSharding(mesh, P(None, "seq"))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("policy", ["full", "dots", "attn_saved"])
def test_remat_matches_no_remat(policy):
    """jax.checkpoint must not change values or grads, only memory —
    for EVERY policy, including attn_saved (FFN-half-only checkpoint,
    the bench.py LM default)."""
    import numpy as np

    from bigdl_tpu.models.transformer import (
        TransformerConfig, TransformerLM)

    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 50, (2, 16)), jnp.int32)
    base = dict(vocab_size=50, max_len=16, dim=32, num_heads=4,
                num_layers=2)
    m1 = TransformerLM(TransformerConfig(**base, remat=False))
    m2 = TransformerLM(TransformerConfig(**base, remat=True,
                                         remat_policy=policy))
    v = m1.init(jax.random.PRNGKey(0))

    def loss(model, p):
        out, _ = model.apply({"params": p, "state": {}}, toks)
        return jnp.mean(out ** 2)

    l1, g1 = jax.value_and_grad(lambda p: loss(m1, p))(v["params"])
    l2, g2 = jax.value_and_grad(lambda p: loss(m2, p))(v["params"])
    assert abs(float(l1) - float(l2)) < 1e-6
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


class TestSwitchMoELM:
    """TransformerConfig.moe_experts: Switch/GShard-FFN transformer."""

    def _cfg(self, top_k=1):
        return TransformerConfig(vocab_size=64, max_len=32, dim=32,
                                 num_heads=4, num_layers=2, dropout=0.0,
                                 moe_experts=4, moe_top_k=top_k)

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_forward_loss_and_grads(self, top_k):
        model = TransformerLM(self._cfg(top_k), name="lm")
        v = model.init(jax.random.PRNGKey(0))
        assert v["params"]["blocks"]["w1"].shape == (2, 4, 32, 128)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        tgts = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 64)
        logp, _ = model.apply(v, toks)
        assert logp.shape == (2, 16, 64)
        # loss includes the positive aux term
        loss = model.loss(v, toks, tgts, chunk=16)
        h, aux = model.apply_hidden(v, toks, with_aux=True)
        assert float(aux) > 0.0
        g = jax.grad(lambda p: model.loss(
            {"params": p, "state": {}}, toks, tgts, chunk=16))(v["params"])
        leaves = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
        # router must receive gradient (through routing AND aux)
        assert float(jnp.abs(g["blocks"]["router"]).sum()) > 0

    def test_trains_through_optimizer(self):
        from bigdl_tpu import nn as bnn
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.text import synthetic_next_token
        from bigdl_tpu.optim import Adam, Optimizer, Trigger

        model = TransformerLM(self._cfg(), name="lm")
        model.build(jax.random.PRNGKey(0))
        data = synthetic_next_token(64, 64, 16)
        opt = (Optimizer(model, DataSet.array(data),
                         bnn.ChunkedSoftmaxCE(), batch_size=16)
               .set_optim_method(Adam(3e-3))
               .set_end_when(Trigger.max_iteration(20)))
        opt.log_every = 100
        trained = opt.optimize()
        # loss finite and decreased vs iteration 1 is covered by the
        # convergence harness elsewhere; here: end-to-end runs + params
        # moved
        p0 = model.init(jax.random.PRNGKey(0))["params"]
        moved = jax.tree_util.tree_map(
            lambda a, b: float(jnp.abs(a - b).max()),
            trained.variables["params"], p0)
        assert max(jax.tree_util.tree_leaves(moved)) > 0

    def test_moe_rejects_tp(self):
        with pytest.raises(NotImplementedError, match="tensor"):
            TransformerLM(self._cfg(), tp_axis="model", name="lm")


def test_moe_lm_expert_choice_routing():
    """moe_routing='expert_choice' wires through the LM: forward runs,
    aux is exactly 0 (balanced by construction), grads flow."""
    cfg = TransformerConfig(vocab_size=64, max_len=32, dim=32,
                            num_heads=4, num_layers=2, dropout=0.0,
                            moe_experts=4, moe_routing="expert_choice")
    m = TransformerLM(cfg)
    v = m.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 16)), jnp.int32)
    h, aux = m.apply_hidden({"params": v["params"], "state": {}}, toks,
                            with_aux=True)
    assert h.shape == (2, 16, 32)
    assert float(aux) == 0.0

    def loss(p):
        out, _ = m.apply({"params": p, "state": {}}, toks)
        return jnp.mean(out ** 2)

    g = jax.grad(loss)(v["params"])
    gn = sum(float(jnp.abs(l).sum())
             for l in jax.tree_util.tree_leaves(g))
    assert np.isfinite(gn) and gn > 0
